"""Self-healing solve campaigns: auto-resume supervision above the solve.

The stack below this module already turns every failure it can into a
clean, resumable death — transient retry (PR 4), coordinated abort /
exit 124 with an intact checkpoint prefix (PR 6), preemption grace /
exit 75 (resilience/preempt.py) — but nothing *above* the solve resumed
it: every witness run still needed an operator watching. This is the
solve-side sibling of the serve fleet's supervisor (serve/supervisor.py)
for the multi-day 5x6 → 6x6 → 7x6 campaign regime (ROADMAP item 1),
where "Strongly Solving 7x6 Connect-Four on Consumer Grade Hardware"
(arXiv 2507.05267) and the Pentago solve (arXiv 1404.0743) show the
binding constraint is surviving crashes, preemptions, and disk
exhaustion — not FLOPs.

One :class:`Campaign` drives one game to completion:

* **attempts** — launch the solve (a single process, or the whole
  ``tools/launch_multihost.py`` world) against one checkpoint
  directory; every death classified from exit codes + log tails; resume
  is just the next attempt (the engines' own resume machinery does the
  rest);
* **backoff** — bounded exponential between failed attempts, reset
  whenever an attempt made progress (sealed something new);
* **no-progress breaker** — K consecutive attempts dying without
  sealing a new level abort the campaign with a diagnosis bundle (last
  checkpoint progress, quarantine inventory, per-rank log tails):
  retrying a deterministic failure forever is not resilience;
* **disk budget** — free space below the soft threshold (or an
  ENOSPC-classified death) triggers retention GC of superseded
  artifacts (utils/checkpoint.gc_superseded); below the hard floor the
  campaign aborts cleanly, prefix intact;
* **ledger** — an append-only ``campaign.jsonl`` (fsync per record)
  makes every witness run a committed, auditable, resumable artifact;
  ``tools/obs_report.py`` folds it into the campaign summary.

Exit codes: 0 solved; 3 no-progress breaker / attempts exhausted;
4 disk hard floor; 75 the campaign itself was preempted (SIGTERM —
forwarded to the attempt, which drains gracefully; rerun the same
command to continue).

This module is deliberately jax-free at import (like coordination.py):
the supervisor must start instantly and survive anything the solve
process does to itself. The one jax-importing dependency
(LevelCheckpointer, for GC) is imported lazily when a GC actually runs.
"""

from __future__ import annotations

import dataclasses
import json
import os
import pathlib
import shutil
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from gamesmanmpi_tpu.obs import default_registry
from gamesmanmpi_tpu.obs import flightrec
from gamesmanmpi_tpu.obs import status as obs_status
from gamesmanmpi_tpu.resilience.preempt import GRACE_EXIT_CODE
from gamesmanmpi_tpu.resilience.faults import (
    KILL_EXIT_CODE,
    TORN_EXIT_CODE,
)
from gamesmanmpi_tpu.resilience.supervisor import WATCHDOG_EXIT_CODE
from gamesmanmpi_tpu.utils.env import env_bool, env_float, env_int

#: Campaign exit codes (documented in docs/DISTRIBUTED.md "Campaigns").
SOLVED_EXIT_CODE = 0
USAGE_EXIT_CODE = 2
NO_PROGRESS_EXIT_CODE = 3
DISK_FLOOR_EXIT_CODE = 4

#: The campaign CLI's COMPLETE exit-code contract. gamesman-lint's
#: GM506/GM507 exit-code-parity rules hold this registry, the
#: ``classify`` method below, and ``tools/run_campaign.py``'s
#: documented "Exit codes:" list in two-way lockstep: an exit code
#: that none of them knows is a death that silently classifies as
#: ``crash`` (docs/ANALYSIS.md).
CAMPAIGN_EXIT_CODES = {
    SOLVED_EXIT_CODE: "solved",
    USAGE_EXIT_CODE: "usage",
    NO_PROGRESS_EXIT_CODE: "no-progress breaker / attempts exhausted",
    DISK_FLOOR_EXIT_CODE: "disk hard floor",
    GRACE_EXIT_CODE: "campaign preempted",
}

#: Log-tail markers that classify a death as disk exhaustion (the
#: injected ``enospc`` fault kind and the real OSError both match).
ENOSPC_MARKERS = ("ENOSPC", "No space left on device", "[Errno 28]")

#: Log-tail markers that classify a death as memory exhaustion: the
#: injected ``oom`` fault kind, the host-memory guard
#: (resilience/memguard.py), XLA's allocator (RESOURCE_EXHAUSTED), a
#: bare Python MemoryError, and the glibc/errno spellings. The kernel
#: OOM-killer's SIGKILL stays ``signal`` — it leaves no tail to read,
#: which is exactly why the guard exists.
OOM_MARKERS = (
    "RESOURCE_EXHAUSTED", "MemoryError", "HostMemoryExceeded",
    "out of memory", "Out of memory", "Cannot allocate memory",
    "ENOMEM", "[Errno 12]", "oom-kill",
)

#: Log-tail marker of parallel/mesh.make_mesh's infeasible-geometry
#: ValueError ("requested N shards but only M devices"): an oom
#: escalation that overshot the PHYSICAL device count (on real
#: hardware the fake-device pin is inert) dies with this in its tail —
#: the policy reverts the shard escalation instead of crash-looping the
#: same impossible mesh into the no-progress breaker.
MESH_INFEASIBLE_MARKER = "shards but only"

#: Death causes that read as "a rank/host was lost" rather than a
#: deterministic failure: with ``elastic_ranks`` the next attempt
#: retries the world at W-1 ranks (floor 1) — the checkpoint tree is
#: world-size-elastic (reshard-on-resume, docs/DISTRIBUTED.md
#: "Elastic resume"), so shrinking the world beats waiting for a host
#: that may never come back.
LOST_RANK_CAUSES = ("killed", "signal", "deadline_abort", "timeout")

#: Bytes of each attempt log kept in the diagnosis bundle.
LOG_TAIL_BYTES = 4000

_REPO = pathlib.Path(__file__).resolve().parents[2]


def checkpoint_progress(directory) -> dict:
    """A jax-free snapshot of what the checkpoint tree has sealed —
    the campaign's progress observable. Tolerates a missing or torn
    manifest (a brand-new campaign has neither)."""
    path = pathlib.Path(directory) / "manifest.json"
    try:
        manifest = json.loads(path.read_text())
    except (OSError, ValueError):
        manifest = {}
    solved = set(int(k) for k in manifest.get("levels", []))
    solved |= {int(k) for k in manifest.get("sharded_levels", {})}
    forward = set(int(k) for k in manifest.get("forward_levels", []))
    forward |= {int(k) for k in manifest.get("forward_level_shards", {})}
    dense = [int(k) for k in manifest.get("dense_levels", [])]
    # Sealed geometry (elastic resume): the shard counts the tree's
    # shard artifacts carry and the last stamped world size — the
    # campaign's ledger records them per attempt so every geometry
    # change (reshard adoption, escalation) is auditable. Jax-free
    # manifest reads, mirroring LevelCheckpointer.sealed_geometry.
    counts = set()
    if manifest.get("frontier_shards"):
        counts.add(int(manifest["frontier_shards"]))
    for v in manifest.get("forward_level_shards", {}).values():
        counts.add(int(v))
    for v in manifest.get("sharded_levels", {}).values():
        counts.add(int(v))
    counts.discard(0)
    run = manifest.get("run", {})
    return {
        "solved_levels": sorted(solved),
        "deepest_solved": max(solved) if solved else None,
        "forward_levels": len(forward),
        "frontiers_complete": bool(
            manifest.get("frontiers_complete") or manifest.get("frontiers")
            or manifest.get("frontier_shards")
        ),
        "dense_levels": len(dense),
        "epoch": int(run.get("epoch", 0)),
        "shard_counts": sorted(counts),
        "shards": next(iter(counts)) if len(counts) == 1 else None,
        "num_processes": (
            int(run["num_processes"]) if "num_processes" in run else None
        ),
    }


def progress_score(progress: dict) -> tuple:
    """Monotone progress measure, compared lexicographically. A flat
    count would lie at the forward->backward seam: consolidating the
    frontier snapshot DELETES the per-level forward seals it supersedes
    (drop_forward_level_shards), so an attempt that finished forward
    would read as regression. Phase-ordered, that transition is always
    an increase: frontiers-complete beats any forward count, a newly
    solved level beats anything within the backward phase."""
    return (
        int(progress["frontiers_complete"]),
        len(progress["solved_levels"]),
        progress["forward_levels"],
        progress["dense_levels"],
    )


class _Ledger:
    """Append-only JSONL, one fsync'd line per record: the ledger must
    survive the campaign process dying mid-write (the same durability
    stance as the checkpoint manifest, without its atomic-replace —
    appends never tear earlier records, and obs_report's loader skips a
    torn tail line)."""

    def __init__(self, path):
        self.path = pathlib.Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)

    def log(self, record: dict) -> None:
        line = json.dumps({"wall_time": time.time(), **record},
                          default=str)
        with open(self.path, "a") as fh:
            fh.write(line + "\n")
            fh.flush()
            os.fsync(fh.fileno())


@dataclasses.dataclass
class CampaignConfig:
    """One campaign's shape. Every numeric default reads its
    ``GAMESMAN_CAMPAIGN_*`` / ``GAMESMAN_CKPT_DISK_*`` env twin
    (docs/CONFIG.md), so ``tools/run_campaign.py`` flags and env agree.
    """

    solver_args: List[str]  # game spec + solve CLI flags (no ckpt flag)
    checkpoint_dir: str
    processes: int = 1  # >1 = a real launch_multihost world
    max_attempts: int = None  # type: ignore[assignment]
    no_progress_limit: int = None  # type: ignore[assignment]
    backoff_base_secs: float = None  # type: ignore[assignment]
    backoff_max_secs: float = None  # type: ignore[assignment]
    attempt_timeout_secs: float = None  # type: ignore[assignment]
    disk_soft_mb: float = None  # type: ignore[assignment]
    disk_floor_mb: float = None  # type: ignore[assignment]
    #: oom death -> escalate geometry for the next attempt: shards
    #: double (under max_shards) and the store cache halves (to
    #: cache_floor_mb). The reshard-on-resume loaders make the changed
    #: geometry a plain resume (docs/DISTRIBUTED.md "Elastic resume").
    oom_escalate: bool = None  # type: ignore[assignment]
    max_shards: int = None  # type: ignore[assignment]
    cache_floor_mb: int = None  # type: ignore[assignment]
    #: lost-rank death (killed/signal/deadline_abort/timeout) -> retry
    #: the world at W-1 ranks (floor 1). Opt-in: shrinking a world is a
    #: policy decision, not a default.
    elastic_ranks: bool = None  # type: ignore[assignment]
    ledger_path: Optional[str] = None  # default <ckpt>/campaign.jsonl
    log_dir: Optional[str] = None  # default <ckpt>/logs
    #: per-attempt chaos: attempt i (1-based) runs with GAMESMAN_FAULTS
    #: set to chaos[i-1] ("" = clean); attempts past the list run clean.
    #: Multi-process attempts arm rank 0 only (the other ranks die by
    #: coordinated abort — the realistic preemption shape).
    chaos: List[str] = dataclasses.field(default_factory=list)
    local_devices: Optional[int] = None  # multihost fake devices/rank

    def __post_init__(self):
        if self.max_attempts is None:
            self.max_attempts = env_int("GAMESMAN_CAMPAIGN_MAX_ATTEMPTS", 8)
        if self.no_progress_limit is None:
            self.no_progress_limit = env_int(
                "GAMESMAN_CAMPAIGN_NO_PROGRESS", 3
            )
        if self.backoff_base_secs is None:
            self.backoff_base_secs = env_float(
                "GAMESMAN_CAMPAIGN_BACKOFF_BASE_SECS", 1.0
            )
        if self.backoff_max_secs is None:
            self.backoff_max_secs = env_float(
                "GAMESMAN_CAMPAIGN_BACKOFF_MAX_SECS", 60.0
            )
        if self.attempt_timeout_secs is None:
            self.attempt_timeout_secs = env_float(
                "GAMESMAN_CAMPAIGN_ATTEMPT_SECS", 0.0
            )
        if self.disk_soft_mb is None:
            self.disk_soft_mb = env_float("GAMESMAN_CKPT_DISK_SOFT_MB", 0.0)
        if self.disk_floor_mb is None:
            self.disk_floor_mb = env_float(
                "GAMESMAN_CKPT_DISK_FLOOR_MB", 0.0
            )
        if self.oom_escalate is None:
            self.oom_escalate = env_bool(
                "GAMESMAN_CAMPAIGN_OOM_ESCALATE", True
            )
        if self.max_shards is None:
            self.max_shards = env_int("GAMESMAN_CAMPAIGN_MAX_SHARDS", 64)
        if self.cache_floor_mb is None:
            self.cache_floor_mb = env_int(
                "GAMESMAN_CAMPAIGN_CACHE_FLOOR_MB", 16
            )
        if self.elastic_ranks is None:
            self.elastic_ranks = env_bool(
                "GAMESMAN_CAMPAIGN_ELASTIC_RANKS", False
            )
        if self.ledger_path is None:
            self.ledger_path = str(
                pathlib.Path(self.checkpoint_dir) / "campaign.jsonl"
            )
        if self.log_dir is None:
            self.log_dir = str(pathlib.Path(self.checkpoint_dir) / "logs")


class CampaignAborted(RuntimeError):
    """The campaign gave up (breaker / disk floor); ``code`` is the
    process exit code, the diagnosis bundle is already on disk."""

    def __init__(self, reason: str, code: int):
        super().__init__(reason)
        self.code = code


class Campaign:
    """Drives one solve to completion across attempts. ``run()`` returns
    the campaign exit code (see module docstring)."""

    def __init__(self, config: CampaignConfig, echo=None):
        self.cfg = config
        self.ledger = _Ledger(config.ledger_path)
        self.echo = echo or (lambda msg: print(msg, file=sys.stderr,
                                               flush=True))
        pathlib.Path(config.checkpoint_dir).mkdir(parents=True,
                                                  exist_ok=True)
        pathlib.Path(config.log_dir).mkdir(parents=True, exist_ok=True)
        #: written by the signal handler (lock-free: a plain flag plus
        #: os.kill of the recorded child pids — GM205's contract).
        self._preempted = False
        self._child_pids: List[int] = []
        #: live attempt geometry (the adaptive-degradation state): the
        #: policy mutates these between attempts; every change lands on
        #: the ledger before the next attempt runs with it.
        self._processes = config.processes
        self._local_devices = config.local_devices
        self._shards = self._parse_shards(config.solver_args)
        self._shards0 = self._shards
        self._cache_mb: Optional[int] = None  # None = inherit env
        self._geometry_dirty = False
        #: live mission-control state (ISSUE 15): mirrored by the run
        #: loop for the /status payload — plain attribute stores read by
        #: HTTP handler threads, never locked (the progress contract).
        self._attempt = 0
        self._last_cause: Optional[str] = None
        self._no_progress = 0
        self._backoff_deadline: Optional[float] = None
        self._status_server = None
        #: where the CHILD's ephemeral status server publishes its
        #: bound address; the campaign proxies it through its own
        #: stable port so one URL survives restarts.
        self._solve_addr_file = pathlib.Path(config.log_dir) / "status_addr"

    # ----------------------------------------------------- geometry args

    @staticmethod
    def _parse_shards(args) -> Optional[int]:
        """The solve CLI's ``--devices N`` from the solver args (the
        sharded engine's shard count), or None — the policy only
        escalates shard counts it can actually rewrite."""
        for i, a in enumerate(args):
            if a == "--devices" and i + 1 < len(args):
                try:
                    return int(args[i + 1])
                except ValueError:
                    return None
            if a.startswith("--devices="):
                try:
                    return int(a.split("=", 1)[1])
                except ValueError:
                    return None
        return None

    @staticmethod
    def _rewrite_devices(args: List[str], shards: int) -> List[str]:
        out = list(args)
        for i, a in enumerate(out):
            if a == "--devices" and i + 1 < len(out):
                out[i + 1] = str(shards)
                return out
            if a.startswith("--devices="):
                out[i] = f"--devices={shards}"
                return out
        return out

    def _effective_cache_mb(self) -> int:
        """The store cache budget the NEXT attempt will run with: the
        policy's override, else the inherited env/default (mirrors
        store/blockstore's GAMESMAN_STORE_CACHE_MB default of 256)."""
        if self._cache_mb is not None:
            return self._cache_mb
        return env_int("GAMESMAN_STORE_CACHE_MB", 256)

    # ------------------------------------------------------------ signals

    def request_preempt(self) -> None:
        # Lock-free by contract (GM205): CPython delivers signals on
        # this (main) thread, so the handler must not take any lock the
        # interrupted code could hold. Forward the grace signal to every
        # live attempt process; they drain and exit 75.
        self._preempted = True
        for pid in list(self._child_pids):
            try:
                os.kill(pid, signal.SIGTERM)
            except OSError:
                pass

    def install_signal_handlers(self):
        """SIGTERM/SIGINT preempt the campaign (and, forwarded, the
        attempt). Returns a restore callable; no-op off the main
        thread."""
        previous = {}

        def _on_signal(signum, frame):
            self.request_preempt()

        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                previous[sig] = signal.signal(sig, _on_signal)
            except ValueError:
                pass

        def restore():
            for sig, handler in previous.items():
                signal.signal(sig, handler)

        return restore

    # ----------------------------------------------------------- attempts

    def _attempt_env(self, attempt: int) -> dict:
        env = dict(os.environ)
        env.pop("GAMESMAN_FAULTS", None)
        for k in list(env):
            if k.startswith("GAMESMAN_FAULTS_RANK_"):
                env.pop(k)
        spec = ""
        if attempt <= len(self.cfg.chaos):
            spec = self.cfg.chaos[attempt - 1]
        if spec:
            if self._processes > 1:
                env["GAMESMAN_FAULTS_RANK_0"] = spec
            else:
                env["GAMESMAN_FAULTS"] = spec
        if self._cache_mb is not None:
            # The oom policy's shrunken store-cache budget.
            env["GAMESMAN_STORE_CACHE_MB"] = str(self._cache_mb)
        if self._processes == 1 and self.cfg.processes > 1:
            # Degraded from a world to a single process: a stale
            # distributed wiring in the inherited env would make the
            # lone attempt dial a coordinator that no longer exists.
            for k in ("GAMESMAN_COORDINATOR", "GAMESMAN_NUM_PROCESSES",
                      "GAMESMAN_PROCESS_ID", "GAMESMAN_COORD_ADDR"):
                env.pop(k, None)
        if (self._geometry_dirty and self._processes == 1
                and self._shards):
            # Escalated single-process attempts must actually HAVE the
            # new shard count's devices: pin the fake-device count and
            # drop an inherited XLA_FLAGS whose stale
            # host_platform_device_count would win over it (same
            # leak-prevention as launch_multihost's child env).
            env.pop("XLA_FLAGS", None)
            env["GAMESMAN_FAKE_DEVICES"] = str(self._shards)
        # Flight recorder (ISSUE 15): every attempt checkpoints its ring
        # at level boundaries into the log dir, so even a SIGKILLed
        # attempt leaves flightrec_<rank>.json from its last boundary.
        # An operator's explicit dir wins.
        env.setdefault("GAMESMAN_FLIGHTREC_DIR", str(self.cfg.log_dir))
        if self._status_server is not None:
            # The campaign owns the operator-facing status port; the
            # child binds an ephemeral one and publishes its address,
            # which _status_payload proxies — one port, every attempt.
            env["GAMESMAN_STATUS_PORT"] = "0"
            env["GAMESMAN_STATUS_ADDR_FILE"] = str(self._solve_addr_file)
        return env

    def _solver_args(self) -> List[str]:
        args = list(self.cfg.solver_args)
        if self._shards is not None and self._shards != self._shards0:
            args = self._rewrite_devices(args, self._shards)
        return args + [
            "--checkpoint-dir", str(self.cfg.checkpoint_dir),
        ]

    def _status_payload(self) -> dict:
        """The campaign's /status body: its own attempt/backoff/breaker
        state, the jax-free checkpoint progress, and — when the current
        attempt's child has published its status address — the child's
        live /status proxied through (one operator port that survives
        every restart). Runs on HTTP handler threads: reads only plain
        attributes the run loop replaces atomically."""
        now = time.monotonic()
        deadline = self._backoff_deadline
        payload = {
            "kind": "campaign",
            "attempt": self._attempt,
            "max_attempts": self.cfg.max_attempts,
            "last_cause": self._last_cause,
            "no_progress": self._no_progress,
            "no_progress_limit": self.cfg.no_progress_limit,
            "breaker": (
                "open" if self._no_progress >= self.cfg.no_progress_limit
                else "closed"
            ),
            "backoff_secs_remaining": (
                round(max(0.0, deadline - now), 3)
                if deadline is not None and deadline > now else None
            ),
            "preempted": self._preempted,
            "processes": self._processes,
            "shards": self._shards,
            "cache_mb": self._cache_mb,
            "progress": checkpoint_progress(self.cfg.checkpoint_dir),
        }
        try:
            addr = self._solve_addr_file.read_text().strip()
        except OSError:
            addr = None
        if addr:
            # Outer budget > the child's own per-peer scrape deadline x
            # world: the child's rank-0 handler may spend up to
            # (W-1) x GAMESMAN_STATUS_SCRAPE_TIMEOUT assembling its
            # fleet view (slow/dead peers), and the proxy must not time
            # out first — that would report "solve": null exactly when
            # the operator is investigating a sick fleet.
            per_peer = env_float("GAMESMAN_STATUS_SCRAPE_TIMEOUT", 2.0)
            budget = max(5.0, per_peer * (self._processes + 1))
            payload["solve"] = obs_status.fetch_status(addr,
                                                       timeout=budget)
        return payload

    def _run_attempt(self, attempt: int) -> dict:
        """Launch one attempt and wait it out; -> {"rcs": {rank: rc},
        "log_tails": {name: str}, "wall_secs": float}. A ``None`` rc
        means the attempt timeout killed a straggler."""
        t0 = time.monotonic()
        try:
            # A dead child's stale address must not be proxied as live.
            self._solve_addr_file.unlink()
        except OSError:
            pass
        timeout = self.cfg.attempt_timeout_secs or None
        if self._processes > 1:
            out = self._run_attempt_world(attempt, timeout)
        else:
            out = self._run_attempt_single(attempt, timeout)
        out["wall_secs"] = time.monotonic() - t0
        return out

    def _run_attempt_single(self, attempt: int, timeout) -> dict:
        log_dir = pathlib.Path(self.cfg.log_dir)
        out_path = log_dir / f"attempt_{attempt:03d}.out"
        err_path = log_dir / f"attempt_{attempt:03d}.err"
        with open(out_path, "w") as out_f, open(err_path, "w") as err_f:
            proc = subprocess.Popen(
                [sys.executable, str(_REPO / "solve_launcher.py"),
                 *self._solver_args()],
                cwd=str(_REPO), env=self._attempt_env(attempt),
                stdout=out_f, stderr=err_f,
            )
            self._child_pids.append(proc.pid)
            try:
                try:
                    rc: Optional[int] = proc.wait(timeout=timeout)
                except subprocess.TimeoutExpired:
                    rc = None
            finally:
                self._child_pids.remove(proc.pid)
                if proc.poll() is None:
                    # Timeout — or an unwinding exception (Ctrl-C):
                    # either way the attempt must not outlive its
                    # supervisor.
                    proc.kill()
                    proc.wait()
        return {
            "rcs": {0: rc},
            "log_tails": {
                "attempt": _tail(err_path) + _tail(out_path),
            },
        }

    def _run_attempt_world(self, attempt: int, timeout) -> dict:
        # Lazy import: tools/ lives at the repo root, next to the
        # package — resolvable from the package path without assuming
        # the caller's cwd.
        if str(_REPO) not in sys.path:
            sys.path.insert(0, str(_REPO))
        from tools.launch_multihost import start_world

        env = self._attempt_env(attempt)
        world = start_world(
            self._solver_args(),
            processes=self._processes,
            log_dir=str(pathlib.Path(self.cfg.log_dir)
                        / f"attempt_{attempt:03d}"),
            env=env,
            local_devices=self._local_devices,
        )
        self._child_pids.extend(world.pids())
        results = None
        try:
            # timeout None = wait forever, same as the single-process
            # path: the attempt-timeout knob is OFF by default and a
            # hidden cap would reap multi-day world attempts.
            results = world.wait(timeout)
        finally:
            for pid in world.pids():
                if pid in self._child_pids:
                    self._child_pids.remove(pid)
            if results is None:
                # An unwinding exception (Ctrl-C without the signal
                # handlers, an OSError mid-wait): the ranks must not
                # outlive their supervisor — same contract as the
                # single-process path's finally.
                world.send_signal(signal.SIGKILL)
        return {
            "rcs": {r.rank: r.returncode for r in results},
            "log_tails": {
                f"rank{r.rank}": r.stderr[-LOG_TAIL_BYTES:]
                + r.stdout[-LOG_TAIL_BYTES:]
                for r in results
            },
        }

    # ------------------------------------------------------ classification

    @staticmethod
    def classify(rcs: Dict[int, Optional[int]], log_tails: dict) -> str:
        """One word per death, for the ledger and the breaker."""
        if all(rc == 0 for rc in rcs.values()):
            return "complete"
        tails = " ".join(log_tails.values())
        if any(m in tails for m in ENOSPC_MARKERS):
            return "enospc"
        if any(m in tails for m in OOM_MARKERS):
            # Memory exhaustion — the injected `oom` kind, the
            # host-memory guard, XLA's RESOURCE_EXHAUSTED, or a bare
            # MemoryError. A degradable death: the oom policy escalates
            # geometry (S->2S, smaller cache) for the next attempt.
            return "oom"
        codes = set(rcs.values())
        # Injected deaths first: in a mixed world (rank 0 SIGKILLed,
        # peers exit 124 through the coordinated abort) the CAUSE is the
        # kill, the 124s are its sympathetic shadow. Grace (75) likewise
        # beats 124: a wedged rank force-exited, but the world was
        # preempted.
        if KILL_EXIT_CODE in codes:
            return "killed"
        if TORN_EXIT_CODE in codes:
            return "torn_kill"
        if GRACE_EXIT_CODE in codes:
            return "preempted"
        if WATCHDOG_EXIT_CODE in codes:
            return "deadline_abort"
        if None in codes:
            return "timeout"
        if any(rc is not None and rc < 0 for rc in codes):
            return "signal"
        return "crash"

    # ------------------------------------------------- adaptive geometry

    def _maybe_revert_shards(self, cause: str, tails: str,
                             attempt: int) -> None:
        """An ESCALATED attempt that died at mesh construction
        (``requested N shards but only M devices``) asked for a
        geometry this host cannot provide — e.g. real hardware, where
        GAMESMAN_FAKE_DEVICES cannot conjure devices. Step the shard
        escalation back down (never below the original request) so the
        campaign retries a feasible geometry; the shrunken cache is
        kept — it is the half of the oom answer that is always
        legal."""
        if cause != "crash" or not self._shards or not self._shards0:
            return
        if self._shards <= self._shards0:
            return
        if MESH_INFEASIBLE_MARKER not in tails:
            return
        prev = self._shards
        self._shards = max(self._shards0, self._shards // 2)
        self.ledger.log({
            "phase": "campaign_reshard",
            "attempt": attempt,
            "cause": "infeasible",
            "from_shards": prev,
            "to_shards": self._shards,
            "from_cache_mb": self._effective_cache_mb(),
            "to_cache_mb": self._effective_cache_mb(),
            "processes": self._processes,
        })
        default_registry().counter(
            "gamesman_campaign_reshards_total",
            "attempt-geometry escalations (shards/cache) between "
            "campaign attempts",
        ).inc()
        default_registry().counter(
            "gamesman_campaign_degrade_total",
            "graceful campaign degradations by kind",
            kind="infeasible",
        ).inc()
        self.echo(
            f"[campaign] escalated geometry is infeasible on this "
            f"host: reverting shards {prev}->{self._shards}"
        )

    def _apply_policy(self, cause: str, attempt: int) -> None:
        """Graceful degradation between attempts (ISSUE 13): an ``oom``
        death escalates geometry — shards double (under
        ``max_shards``), the store cache halves (to ``cache_floor_mb``)
        — and a lost-rank death (opt-in ``elastic_ranks``) retries the
        world at W-1 ranks. The reshard-on-resume loaders make every
        change a plain resume; every change is a ledger record and a
        ``gamesman_campaign_*`` counter BEFORE the next attempt runs
        with it."""
        if cause == "oom" and self.cfg.oom_escalate:
            from_shards = self._shards
            from_cache = self._effective_cache_mb()
            changed = False
            if self._shards and self._shards * 2 <= self.cfg.max_shards:
                self._shards *= 2
                if self._processes > 1:
                    # The world must still be able to host the mesh:
                    # ceil(S / W) fake devices per rank.
                    self._local_devices = max(
                        int(self._local_devices or 1),
                        -(-self._shards // self._processes),
                    )
                changed = True
            new_cache = max(self.cfg.cache_floor_mb, from_cache // 2)
            if new_cache < from_cache:
                self._cache_mb = new_cache
                changed = True
            if not changed:
                return  # already at the ceiling/floor: plain retry
            self._geometry_dirty = True
            self.ledger.log({
                "phase": "campaign_reshard",
                "attempt": attempt,
                "cause": cause,
                "from_shards": from_shards,
                "to_shards": self._shards,
                "from_cache_mb": from_cache,
                "to_cache_mb": self._effective_cache_mb(),
                "processes": self._processes,
            })
            default_registry().counter(
                "gamesman_campaign_reshards_total",
                "attempt-geometry escalations (shards/cache) between "
                "campaign attempts",
            ).inc()
            default_registry().counter(
                "gamesman_campaign_degrade_total",
                "graceful campaign degradations by kind",
                kind="oom",
            ).inc()
            self.echo(
                f"[campaign] oom: escalating geometry for the next "
                f"attempt (shards {from_shards}->{self._shards}, "
                f"store cache {from_cache}->"
                f"{self._effective_cache_mb()} MB)"
            )
        elif (cause in LOST_RANK_CAUSES and self.cfg.elastic_ranks
                and self._processes > 1):
            from_processes = self._processes
            self._processes -= 1
            if self._shards:
                self._local_devices = max(
                    int(self._local_devices or 1),
                    -(-self._shards // self._processes),
                )
            self._geometry_dirty = True
            self.ledger.log({
                "phase": "campaign_degrade",
                "attempt": attempt,
                "kind": "lost_rank",
                "cause": cause,
                "from_processes": from_processes,
                "to_processes": self._processes,
                "shards": self._shards,
            })
            default_registry().counter(
                "gamesman_campaign_degrade_total",
                "graceful campaign degradations by kind",
                kind="lost_rank",
            ).inc()
            self.echo(
                f"[campaign] lost rank ({cause}): retrying at "
                f"{self._processes} rank(s)"
            )

    # ------------------------------------------------------------- disk

    def _free_mb(self) -> float:
        return shutil.disk_usage(self.cfg.checkpoint_dir).free / (1 << 20)

    def _gc(self, reason: str) -> dict:
        """Retention GC on the (quiescent — no attempt is live) tree.
        The jax-importing checkpointer loads HERE, not at module import:
        a campaign that never needs GC never pays it."""
        free_before = self._free_mb()
        from gamesmanmpi_tpu.utils.checkpoint import LevelCheckpointer

        ck = LevelCheckpointer(self.cfg.checkpoint_dir)
        quarantined = ck.quarantine_inventory()
        freed = ck.gc_superseded()
        rec = {
            "phase": "campaign_gc",
            "reason": reason,
            "freed_files": freed["files"],
            "freed_bytes": freed["bytes"],
            "kinds": freed["kinds"],
            "quarantined": quarantined,
            "free_mb_before": round(free_before, 1),
            "free_mb_after": round(self._free_mb(), 1),
        }
        self.ledger.log(rec)
        self.echo(
            f"[campaign] gc ({reason}): freed {freed['files']} files / "
            f"{freed['bytes']} bytes"
        )
        return freed

    def _check_disk(self, had_enospc: bool) -> None:
        """ENOSPC death, soft threshold, or hard floor -> retention GC
        first; still under the floor after GC -> abort. The floor is
        always evaluated AFTER a GC ran (the documented contract — an
        operator setting only the floor still gets the reclaim pass
        before the campaign gives up). Raises CampaignAborted."""
        free = self._free_mb()
        soft, floor = self.cfg.disk_soft_mb, self.cfg.disk_floor_mb
        if had_enospc or (soft > 0 and free < soft) \
                or (floor > 0 and free < floor):
            reason = ("enospc" if had_enospc
                      else "soft_threshold" if soft > 0 and free < soft
                      else "hard_floor")
            self._gc(reason)
            free = self._free_mb()
        if floor > 0 and free < floor:
            raise CampaignAborted(
                f"free disk {free:.1f} MiB under the hard floor "
                f"{floor:.1f} MiB after retention GC",
                DISK_FLOOR_EXIT_CODE,
            )

    # ---------------------------------------------------------- diagnosis

    def _write_diagnosis(self, reason: str, attempt: int,
                         last: Optional[dict]) -> str:
        """The abort bundle: everything an operator needs to decide
        what is wrong WITHOUT re-running — last checkpoint progress,
        quarantine inventory, the final attempt's per-rank log tails."""
        bundle = {
            "reason": reason,
            "attempts": attempt,
            "checkpoint_dir": str(self.cfg.checkpoint_dir),
            "progress": checkpoint_progress(self.cfg.checkpoint_dir),
            # Geometry at abort time: the sealed tree's shape vs what
            # the final attempt ran with — a mismatch the operator can
            # read directly instead of reverse-engineering from logs.
            "geometry": {
                "attempt_shards": self._shards,
                "attempt_processes": self._processes,
                "cache_mb": self._cache_mb,
            },
            "quarantine": [
                {"file": p.name, "bytes": p.stat().st_size}
                for p in sorted(
                    pathlib.Path(self.cfg.checkpoint_dir).glob("*.corrupt")
                )
            ],
            "log_tails": (last or {}).get("log_tails", {}),
            "rcs": {str(k): v for k, v in (last or {}).get(
                "rcs", {}).items()},
        }
        path = pathlib.Path(self.cfg.ledger_path).with_name(
            "campaign_diagnosis.json"
        )
        path.write_text(json.dumps(bundle, indent=1, default=str))
        return str(path)

    # ---------------------------------------------------------------- run

    def _backoff(self, consecutive_failures: int) -> float:
        return min(
            self.cfg.backoff_max_secs,
            self.cfg.backoff_base_secs * (2 ** max(
                0, consecutive_failures - 1
            )),
        )

    def _sleep_backoff(self, secs: float) -> None:
        deadline = time.monotonic() + secs
        self._backoff_deadline = deadline  # /status shows the countdown
        try:
            while not self._preempted and time.monotonic() < deadline:
                time.sleep(
                    min(0.2, max(0.0, deadline - time.monotonic()))
                )
        finally:
            self._backoff_deadline = None

    def run(self) -> int:
        # Mission-control endpoint (GAMESMAN_STATUS_PORT): the campaign
        # holds the operator port across every attempt and proxies the
        # live child's status through it.
        self._status_server = obs_status.maybe_status_server(
            self._status_payload
        )
        try:
            return self._run()
        finally:
            if self._status_server is not None:
                self._status_server.stop()
                self._status_server = None

    def _run(self) -> int:
        cfg = self.cfg
        t0 = time.monotonic()
        self.ledger.log({
            "phase": "campaign_start",
            "solver_args": self._solver_args(),
            "processes": cfg.processes,
            "max_attempts": cfg.max_attempts,
            "no_progress_limit": cfg.no_progress_limit,
            "chaos": cfg.chaos,
        })
        # One counter serves both the breaker (vs no_progress_limit)
        # and the backoff curve: a failure that made progress resets
        # both by definition.
        no_progress = 0
        last = None
        attempt = 0
        try:
            while True:
                if self._preempted:
                    self.ledger.log({"phase": "campaign_preempted",
                                     "attempts": attempt})
                    self.echo("[campaign] preempted; rerun to continue")
                    return GRACE_EXIT_CODE
                self._check_disk(had_enospc=False)
                attempt += 1
                self._attempt = attempt
                before = checkpoint_progress(cfg.checkpoint_dir)
                self.echo(
                    f"[campaign] attempt {attempt}/{cfg.max_attempts} "
                    f"(resume level "
                    f"{before['deepest_solved']}, "
                    f"forward {before['forward_levels']})"
                )
                last = self._run_attempt(attempt)
                cause = self.classify(last["rcs"], last["log_tails"])
                self._last_cause = cause
                flightrec.record(
                    "campaign_attempt", attempt=attempt, cause=cause,
                    rcs=json.dumps(
                        {str(k): v for k, v in last["rcs"].items()}
                    ),
                )
                if cause != "complete":
                    # The death classifier's post-mortem: the campaign's
                    # own ring (attempt history, causes, geometry moves)
                    # lands next to the attempt's per-rank dumps.
                    flightrec.dump(cause, directory=cfg.log_dir,
                                   rank="campaign")
                after = checkpoint_progress(cfg.checkpoint_dir)
                progressed = progress_score(after) > progress_score(before)
                self.ledger.log({
                    "phase": "campaign_attempt",
                    "attempt": attempt,
                    "rcs": {str(k): v for k, v in last["rcs"].items()},
                    "cause": cause,
                    "wall_secs": round(last["wall_secs"], 3),
                    "resume_level": before["deepest_solved"],
                    "progressed": progressed,
                    "solved_before": len(before["solved_levels"]),
                    "solved_after": len(after["solved_levels"]),
                    "forward_after": after["forward_levels"],
                    # Attempt geometry (elastic resume): what this
                    # attempt ran with vs what the tree was sealed at
                    # going in — a sealed_shards != shards row IS a
                    # reshard adoption, auditable from the ledger alone.
                    "shards": self._shards,
                    "processes": self._processes,
                    "cache_mb": self._cache_mb,
                    "sealed_shards": before.get("shards"),
                })
                if cause == "complete":
                    self.ledger.log({
                        "phase": "campaign_done",
                        "attempts": attempt,
                        "wall_secs": round(time.monotonic() - t0, 3),
                    })
                    self.echo(
                        f"[campaign] solved after {attempt} attempt(s)"
                    )
                    return 0
                self.echo(
                    f"[campaign] attempt {attempt} died: {cause} "
                    f"rcs={last['rcs']} progressed={progressed}"
                )
                if self._preempted:
                    # The SIGTERM was ours, forwarded: the attempt
                    # drained (exit 75) — this is a campaign preemption,
                    # not a failure the breaker should count.
                    self.ledger.log({"phase": "campaign_preempted",
                                     "attempts": attempt})
                    self.echo("[campaign] preempted; rerun to continue")
                    return GRACE_EXIT_CODE
                if cause == "enospc":
                    self._check_disk(had_enospc=True)
                self._maybe_revert_shards(
                    cause, " ".join(last["log_tails"].values()), attempt
                )
                self._apply_policy(cause, attempt)
                if progressed:
                    no_progress = 0
                else:
                    no_progress += 1
                self._no_progress = no_progress
                if no_progress >= cfg.no_progress_limit:
                    raise CampaignAborted(
                        f"{no_progress} consecutive attempts died "
                        f"(last cause: {cause}) without sealing "
                        "anything new",
                        NO_PROGRESS_EXIT_CODE,
                    )
                if attempt >= cfg.max_attempts and not progressed:
                    # The budget bounds FLAPPING, not work: an attempt
                    # that sealed something new is the campaign doing
                    # its job (a multi-day 7x6 run may legitimately eat
                    # dozens of preemptions), so only a budget-exhausted
                    # NON-progressing attempt aborts here — the breaker
                    # above already catches sustained no-progress sooner.
                    raise CampaignAborted(
                        f"attempt budget exhausted "
                        f"({cfg.max_attempts}; last cause: {cause})",
                        NO_PROGRESS_EXIT_CODE,
                    )
                backoff = self._backoff(max(no_progress, 1))
                self.ledger.log({"phase": "campaign_backoff",
                                 "secs": round(backoff, 3)})
                self._sleep_backoff(backoff)
        except CampaignAborted as e:
            bundle = self._write_diagnosis(str(e), attempt, last)
            self.ledger.log({
                "phase": "campaign_abort",
                "reason": str(e),
                "code": e.code,
                "attempts": attempt,
                "diagnosis": bundle,
                "wall_secs": round(time.monotonic() - t0, 3),
            })
            self.echo(f"[campaign] ABORT: {e} (diagnosis: {bundle})")
            return e.code


def _tail(path, nbytes: int = LOG_TAIL_BYTES) -> str:
    try:
        with open(path, "rb") as fh:
            fh.seek(0, os.SEEK_END)
            size = fh.tell()
            fh.seek(max(0, size - nbytes))
            return fh.read().decode(errors="replace")
    except OSError:
        return ""
