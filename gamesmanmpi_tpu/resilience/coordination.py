"""Cross-rank retry/abort consensus: the epoch barrier.

PR 4's retry supervisor is rank-local: it re-enters a level step from
held inputs, which is exactly right in one process and a latent
deadlock in N — one rank retrying a step that contains an ``all_to_all``
while its peers proceed into the collective wedges the job forever.
The missing primitive is agreement: at every collective fault point all
ranks must either enter together, retry together, or abort together.

This module is that primitive, deliberately tiny and jax-free (it must
keep working when the accelerator runtime is the thing that is sick):

* :class:`CoordinatorServer` — a thread-based TCP service rank 0 hosts
  next to the jax coordinator. State is a table of *epoch rounds*; each
  participant proposes a verdict (``ok`` / ``retry`` / ``abort``) for
  an epoch and blocks until the round resolves:

  - all ``world`` ranks arrived → ``abort`` if anyone proposed abort,
    else ``retry`` if anyone proposed retry, else ``ok``;
  - the round's deadline expired first → ``abort`` (reason
    ``timeout``) to everyone present — a peer that never arrives (dead,
    wedged, or diverged onto a different epoch) must not hold the
    fleet;
  - a late joiner of an already-resolved round gets the recorded
    decision if the round resolved by consensus, and ``abort`` (reason
    ``late``) if it resolved by timeout — its peers have already given
    up on this epoch, so proceeding alone would desynchronize.

* :class:`EpochBarrier` — the per-rank client. ``propose()`` carries a
  monotonically increasing sequence number mixed into the epoch key:
  the sharded solve's control flow is replicated (counts are
  all_gathered), so every rank proposes the same epochs in the same
  order, and any divergence turns into mismatched epochs that resolve
  as coordinated timeouts instead of silent corruption. Coordinator
  death surfaces as a socket error → :class:`CoordinationError` within
  the deadline, never a hang.

Deadlines: ``GAMESMAN_BARRIER_SECS`` (round + client wait budget,
default 30 s). The wire format is one JSON line each way per proposal —
at one round per retried level step the coordinator is microscopic
next to the collectives it guards.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from typing import Dict, List, Optional

from gamesmanmpi_tpu.obs import default_registry
from gamesmanmpi_tpu.resilience import faults
from gamesmanmpi_tpu.utils.env import env_float, env_opt

#: Verdicts a participant may propose / decisions a round may reach.
OK, RETRY, ABORT = "ok", "retry", "abort"

#: Resolved rounds kept for late joiners before being evicted.
_RESOLVED_KEEP = 1024


class CoordinationError(RuntimeError):
    """The consensus service failed (coordinator death, deadline, wire
    junk) — the caller must treat the step as a coordinated abort."""


class CoordinatedAbort(RuntimeError):
    """The fleet agreed to abort this step (a peer hit a fatal fault,
    timed out, or diverged). Checkpoint prefix is intact; a restarted
    run resumes."""


class _Round:
    """One epoch's in-flight state on the coordinator."""

    __slots__ = ("verdicts", "waiters", "t0", "decision", "reason")

    def __init__(self, now: float):
        self.verdicts: Dict[int, str] = {}
        self.waiters: List[socket.socket] = []
        self.t0 = now
        self.decision: Optional[str] = None
        self.reason = ""


def _decide(verdicts: Dict[int, str]) -> str:
    vs = set(verdicts.values())
    if ABORT in vs:
        return ABORT
    if RETRY in vs:
        return RETRY
    return OK


def _send_json(conn: socket.socket, obj: dict) -> None:
    conn.sendall((json.dumps(obj) + "\n").encode())


def _recv_line(conn: socket.socket, limit: int = 1 << 16) -> bytes:
    buf = bytearray()
    while not buf.endswith(b"\n"):
        chunk = conn.recv(4096)
        if not chunk:
            raise CoordinationError("connection closed mid-message")
        buf += chunk
        if len(buf) > limit:
            raise CoordinationError("oversized coordination message")
    return bytes(buf)


class CoordinatorServer:
    """Rank 0's consensus service (daemon threads, one per connection).

    ``world`` is the participant count; a round resolves when all
    ``world`` ranks proposed, or at ``deadline`` seconds after its first
    proposal, whichever is sooner.
    """

    def __init__(self, world: int, *, host: str = "127.0.0.1",
                 port: int = 0, deadline: float = 30.0,
                 clock=time.monotonic):
        if world < 1:
            raise ValueError("world size must be >= 1")
        self.world = int(world)
        self.deadline = float(deadline)
        self._clock = clock
        self._lock = threading.Lock()
        self._rounds: Dict[str, _Round] = {}  # guarded-by: _lock
        self._resolved: Dict[str, tuple] = {}  # guarded-by: _lock
        #: rank -> announced service address (the address book the live
        #: status fleet scraper reads; ISSUE 15). guarded-by: _lock
        self._peers: Dict[int, str] = {}
        self._closed = False
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(max(8, 2 * world))
        self.host, self.port = self._sock.getsockname()[:2]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="gamesman-coord-accept",
            daemon=True,
        )
        self._accept_thread.start()
        self._sweep_thread = threading.Thread(
            target=self._sweep_loop, name="gamesman-coord-sweep",
            daemon=True,
        )
        self._sweep_thread.start()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def close(self) -> None:
        with self._lock:
            self._closed = True
            pending = [
                (r, w) for r in self._rounds.values() for w in r.waiters
            ]
            self._rounds.clear()
        for _, w in pending:
            try:
                w.close()  # waiters see EOF -> CoordinationError
            except OSError:
                pass
        try:
            self._sock.close()
        except OSError:
            pass

    # --------------------------------------------------------------- serving

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._sock.accept()
            except OSError:  # closed
                return
            threading.Thread(
                target=self._serve_one, args=(conn,),
                name="gamesman-coord-conn", daemon=True,
            ).start()

    # wire: producer, consumer
    def _serve_one(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(self.deadline + 5.0)
            req = json.loads(_recv_line(conn).decode())
            if req.get("op") == "ping":
                _send_json(conn, {"ok": True, "world": self.world})
                conn.close()
                return
            if req.get("op") == "announce":
                # Address-book registration (one line, no round): rank i
                # publishes where its /status endpoint listens so rank
                # 0's fleet-merged status view can scrape it.
                with self._lock:
                    self._peers[int(req["rank"])] = str(req["addr"])
                _send_json(conn, {"ok": True})
                conn.close()
                return
            if req.get("op") == "peers":
                with self._lock:
                    peers = {str(r): a for r, a in self._peers.items()}
                _send_json(conn, {"ok": True, "peers": peers})
                conn.close()
                return
            if req.get("op") != "propose":
                _send_json(conn, {"error": "unknown op"})
                conn.close()
                return
            self._propose(conn, str(req["epoch"]), int(req["rank"]),
                          str(req["verdict"]))
        except (OSError, ValueError, KeyError, CoordinationError):
            try:
                conn.close()
            except OSError:
                pass

    # wire: producer
    def _propose(self, conn, epoch: str, rank: int, verdict: str) -> None:
        if verdict not in (OK, RETRY, ABORT):
            _send_json(conn, {"error": f"bad verdict {verdict!r}"})
            conn.close()
            return
        # Socket replies happen OUTSIDE the lock: sendall can block on a
        # sick peer, and the lock also gates the deadline sweep.
        notify: List[socket.socket] = []
        decision = reason = None
        with self._lock:
            if self._closed:
                notify = [conn]
                decision, reason = ABORT, "closed"
            else:
                done = self._resolved.get(epoch)
                if done is not None:
                    decision, reason = done
                    if reason == "timeout":
                        # Late joiner of a timed-out round: its peers
                        # already gave up on this epoch — proceeding
                        # alone would desynchronize the fleet.
                        decision, reason = ABORT, "late"
                    notify = [conn]
                else:
                    rnd = self._rounds.get(epoch)
                    if rnd is None:
                        rnd = self._rounds[epoch] = _Round(self._clock())
                    rnd.verdicts[rank] = verdict
                    rnd.waiters.append(conn)
                    if len(rnd.verdicts) >= self.world:
                        decision, reason = _decide(rnd.verdicts), "consensus"
                        notify = self._resolve(epoch, rnd, decision, reason)
        for c in notify:
            self._reply_and_close(c, decision, reason)

    # requires-lock: _lock
    def _resolve(self, epoch: str, rnd: _Round, decision: str,
                 reason: str) -> List[socket.socket]:
        """Record the round's outcome; return the waiters to notify
        (the caller replies after releasing the lock)."""
        rnd.decision, rnd.reason = decision, reason
        self._rounds.pop(epoch, None)
        self._resolved[epoch] = (decision, reason)
        while len(self._resolved) > _RESOLVED_KEEP:
            self._resolved.pop(next(iter(self._resolved)))
        waiters, rnd.waiters = rnd.waiters, []
        return waiters

    @staticmethod
    # wire: producer
    def _reply_and_close(conn, decision: str, reason: str) -> None:
        try:
            _send_json(conn, {"decision": decision, "reason": reason})
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _sweep_loop(self) -> None:
        poll = min(0.05, max(0.01, self.deadline / 100))
        while True:
            time.sleep(poll)
            notify: List[socket.socket] = []
            with self._lock:
                if self._closed:
                    return
                now = self._clock()
                expired = [
                    (e, r) for e, r in list(self._rounds.items())
                    if now - r.t0 > self.deadline
                ]
                for epoch, rnd in expired:
                    notify.extend(
                        self._resolve(epoch, rnd, ABORT, "timeout")
                    )
            for conn in notify:
                self._reply_and_close(conn, ABORT, "timeout")


class EpochBarrier:
    """One rank's handle on the consensus service.

    ``propose(tag, verdict)`` blocks until the fleet decides; every call
    advances the local sequence number folded into the epoch key (see
    module docstring). ``barrier(tag)`` is the agreement form: it
    proposes ``ok`` and raises :class:`CoordinatedAbort` unless everyone
    reached the same epoch — used to verify all ranks agree on resume
    state (identical tags → consensus; divergent tags → timeout abort).
    """

    def __init__(self, address: str, rank: int, *,
                 deadline: float = 30.0, connect_timeout: float = 10.0):
        host, _, port = address.rpartition(":")
        if not host or not port:
            raise ValueError(
                f"coordination address {address!r} is not host:port"
            )
        self.host, self.port = host, int(port)
        self.rank = int(rank)
        self.deadline = float(deadline)
        self.connect_timeout = float(connect_timeout)
        self.seq = 0

    # ----------------------------------------------------------------- wire

    def _connect(self) -> socket.socket:
        """Dial the coordinator, retrying refusals inside the connect
        budget (rank 0 may still be binding when peers arrive)."""
        faults.fire("coord.handshake", rank=self.rank)
        t0 = time.monotonic()
        while True:
            try:
                conn = socket.create_connection(
                    (self.host, self.port), timeout=self.connect_timeout
                )
                return conn
            except OSError as e:
                if time.monotonic() - t0 > self.connect_timeout:
                    raise CoordinationError(
                        f"cannot reach coordinator {self.host}:{self.port}"
                        f" ({e})"
                    ) from e
                time.sleep(0.05)

    # wire: producer, consumer
    def propose(self, tag: str, verdict: str) -> str:
        """Propose ``verdict`` for this rank's next epoch round; return
        the fleet's decision (``ok``/``retry``/``abort``). Raises
        :class:`CoordinationError` on coordinator death or wire failure
        — always within roughly the round deadline, never a hang."""
        self.seq += 1
        epoch = f"{self.seq}:{tag}"
        faults.fire("coord.barrier", rank=self.rank, epoch=epoch)
        conn = self._connect()
        try:
            # The server replies the moment the round resolves; its own
            # deadline sweep bounds that, the socket timeout is the
            # belt-and-braces on a dead coordinator.
            conn.settimeout(self.deadline + 10.0)
            _send_json(conn, {
                "op": "propose", "epoch": epoch, "rank": self.rank,
                "verdict": verdict,
            })
            reply = json.loads(_recv_line(conn).decode())
        except (OSError, ValueError) as e:
            raise CoordinationError(
                f"coordination round {epoch!r} failed ({e})"
            ) from e
        finally:
            try:
                conn.close()
            except OSError:
                pass
        decision = reply.get("decision")
        if decision not in (OK, RETRY, ABORT):
            raise CoordinationError(
                f"coordinator replied junk for {epoch!r}: {reply!r}"
            )
        default_registry().counter(
            "gamesman_coord_rounds_total",
            "cross-rank consensus rounds by decision",
            decision=decision,
        ).inc()
        return decision

    def barrier(self, tag: str) -> None:
        """All ranks must reach the same ``tag`` (at the same sequence
        point) or everyone aborts — the agreement primitive resume
        verification uses."""
        decision = self.propose(tag, OK)
        if decision != OK:
            raise CoordinatedAbort(
                f"ranks disagree at barrier {tag!r} "
                f"(decision={decision})"
            )

    # wire: fetch
    def _one_shot(self, req: dict) -> dict:
        """One request/reply exchange outside the round protocol (the
        address-book ops — no sequence number, no consensus)."""
        conn = self._connect()
        try:
            conn.settimeout(self.connect_timeout)
            _send_json(conn, req)
            return json.loads(_recv_line(conn).decode())
        except (OSError, ValueError) as e:
            raise CoordinationError(
                f"coordination op {req.get('op')!r} failed ({e})"
            ) from e
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def announce(self, addr: str) -> None:
        """Publish this rank's service address (its /status endpoint)
        into the coordinator's address book."""
        self._one_shot({"op": "announce", "rank": self.rank,
                        "addr": str(addr)})

    def peers(self) -> Dict[int, str]:
        """The announced address book: ``{rank: "host:port"}``."""
        reply = self._one_shot({"op": "peers"})
        peers = reply.get("peers")
        if not isinstance(peers, dict):
            raise CoordinationError(f"coordinator replied junk: {reply!r}")
        out: Dict[int, str] = {}
        for r, a in peers.items():
            try:
                out[int(r)] = str(a)
            except (TypeError, ValueError):
                continue
        return out


class Coordination:
    """What a solver holds: the client, plus the server when this rank
    hosts it. ``close()`` tears both down (idempotent)."""

    def __init__(self, client: EpochBarrier,
                 server: Optional[CoordinatorServer] = None):
        self.client = client
        self.server = server

    def propose(self, tag: str, verdict: str) -> str:
        return self.client.propose(tag, verdict)

    def barrier(self, tag: str) -> None:
        self.client.barrier(tag)

    def announce(self, addr: str) -> None:
        self.client.announce(addr)

    def peers(self) -> Dict[int, str]:
        return self.client.peers()

    def close(self) -> None:
        if self.server is not None:
            self.server.close()
            self.server = None


def coordination_from_env(rank: int, world: int) -> Optional[Coordination]:
    """Build the rank's coordination handle from ``GAMESMAN_COORD_ADDR``
    (host:port; rank 0 binds the server there). None when unconfigured
    or single-process — the caller falls back to rank-local retry."""
    if world <= 1:
        return None
    addr = env_opt("GAMESMAN_COORD_ADDR")
    if not addr:
        return None
    deadline = env_float("GAMESMAN_BARRIER_SECS", 30.0)
    server = None
    if rank == 0:
        host, _, port = addr.rpartition(":")
        server = CoordinatorServer(
            world, host=host or "127.0.0.1", port=int(port),
            deadline=deadline,
        )
    client = EpochBarrier(addr, rank, deadline=deadline)
    return Coordination(client, server)
