"""Deterministic fault-injection registry.

Failure handling that is never exercised is failure handling that does
not work. This module weaves NAMED fault points through the solve and
serve stacks (checkpoint save/load, engine level steps, the sharded
collectives, the DB probe, the batcher flush) and arms them from one
environment variable, so every failure shape the system claims to
survive can be injected on demand — in-process by tests, or into a
subprocess for whole-process chaos (kill + resume + byte-parity, see
tests/test_resilience.py).

Grammar (``GAMESMAN_FAULTS``, comma-separated directives)::

    point:kind[:when]

* ``point`` — one of :data:`KNOWN_POINTS` (arming an unknown point is a
  ``ValueError``: a typo'd chaos run must not silently pass).
* ``kind`` — what happens when the directive fires:

  - ``transient`` — raise :class:`TransientFault` (classified transient
    by ``resilience.retry``; the retry supervisor must absorb it);
  - ``fatal`` — raise :class:`FatalFault` (must fail fast, checkpoint
    prefix intact);
  - ``delay=SECS`` — sleep (watchdog / deadline fodder);
  - ``kill[=CODE]`` — ``os._exit`` (default 77): process chaos, the
    moral equivalent of a preemption;
  - ``torn`` — truncate the file the call site is writing (the
    ``path=`` context) to half its bytes, then ``os._exit(86)``: a torn
    write followed by death, the silent-bit-rot shape the checkpoint
    crc catches;
  - ``enospc`` — raise ``OSError(ENOSPC)``, the disk-full shape: never
    transient (retrying a full disk fills it again), so the solve fails
    fast with the checkpoint prefix intact — exactly a torn write's
    degrade path — and the campaign supervisor answers with
    GC-and-retry (resilience/campaign.py);
  - ``oom`` — raise ``MemoryError`` (host allocator exhaustion; the
    message carries ``RESOURCE_EXHAUSTED`` so the campaign's log-tail
    death classifier lands on ``oom``): never transient — an OOM at a
    fixed shape OOMs again — so the solve fails fast, prefix intact,
    and the campaign answers with geometry escalation (more shards,
    smaller store cache; resilience/campaign.py).

* ``when`` — which visit fires (the schedule, always replayable):

  - an integer ``N`` (default 1) — exactly the Nth visit of the point;
  - ``always`` — every visit;
  - ``pPROB@SEED`` — seeded Bernoulli per visit (``p0.2@7``): random
    chaos that replays identically run to run.

A disarmed process pays one falsy-dict check per fault point; points
are only ever visited on host-side per-level/per-batch paths, never
per-position.
"""

from __future__ import annotations

import os
import random
import sys
import time
import warnings

from gamesmanmpi_tpu.obs import default_registry
from gamesmanmpi_tpu.utils.env import env_opt


class FaultError(RuntimeError):
    """Base of injected faults (never raised itself)."""


class TransientFault(FaultError):
    """Injected error the retry supervisor must absorb."""


class FatalFault(FaultError):
    """Injected error that must fail fast (never retried)."""


#: Exit codes for process-killing kinds, distinct from real crash codes
#: so the chaos harness can assert the *injected* death happened.
KILL_EXIT_CODE = 77
TORN_EXIT_CODE = 86

#: Every fault point woven into the codebase. The chaos harness
#: enumerates this dict — adding a call site without registering it here
#: means it never gets chaos coverage, so keep them in lockstep.
KNOWN_POINTS = {
    "engine.forward": "single-device forward: per-level expand+dedup sync",
    "engine.dedup": "single-device forward: inside the dedup span, pre-sync",
    "engine.backward": "single-device backward: per-level resolve",
    "sharded.forward": "sharded forward: per-level all_to_all expand step",
    "sharded.backward": "sharded backward: per-level owner-routed resolve",
    "sharded.collective": "sharded multi-process: collective entry, before "
                          "the pre-step consensus round",
    "coord.barrier": "coordination: top of every epoch-barrier proposal",
    "coord.handshake": "coordination: client dial of the rank-0 "
                       "coordinator socket",
    "ckpt.save_frontier": "checkpoint: after a frontier level is sealed",
    "ckpt.save_level": "checkpoint: after a solved level is sealed",
    "ckpt.load_level": "checkpoint: at the top of a resume level load",
    "db.probe": "DbReader: at the top of every batched level probe",
    "store.writebehind": "block store: after one write-behind payload "
                         "write lands, before any seal can run (a kill "
                         "here is the death-between-payload-and-seal "
                         "shape; resume must treat the unsealed stray "
                         "as absent)",
    "serve.flush": "Batcher worker: before the coalesced reader probe",
    "serve.block_decode": "DbReader: inside the per-block decode loader, "
                          "before read_block (a delay here is the "
                          "slow-decode shape query tracing must "
                          "attribute to the decode span)",
    "serve.worker_spawn": "fleet worker: at process start, before the "
                          "warm-start verify/self-probe gate",
    "serve.heartbeat": "fleet worker: each heartbeat-pipe beat (a delay "
                       "here is a liveness stall the supervisor kills)",
    "serve.reload": "supervisor: at the top of a rolling manifest "
                    "reload, before any worker is drained",
    "registry.fetch": "registry pull client: after one ranged blob "
                      "read lands in the staging file, before its "
                      "checksum verify (a torn here is the "
                      "torn-download shape the manifest sha catches; "
                      "a transient is a flaky transport the retry "
                      "supervisor must absorb)",
    "registry.publish": "registry server: after the payload directory "
                        "is installed, before the catalog seal (a kill "
                        "here is the death-between-payload-and-seal "
                        "shape — the old catalog must stay authoritative "
                        "and a re-publish must converge)",
    "registry.install": "registry pull client: after every staged file "
                        "verified, before the atomic rename-install (a "
                        "kill here leaves only the staging dir; the "
                        "fleet keeps serving the old epoch and a re-pull "
                        "resumes from verified bytes)",
    "jobs.claim": "solve-on-demand runner: after a claim record is "
                  "fsync'd to the job ledger, before the campaign "
                  "starts (a kill here is the runner death the "
                  "lease/dead-pid classifier must reclaim on the next "
                  "runner's resume)",
}


class _Directive:
    """One armed ``point:kind:when`` with its per-run schedule state."""

    __slots__ = ("point", "kind", "arg", "when", "visits", "rng")

    def __init__(self, point: str, kind: str, arg, when):
        self.point = point
        self.kind = kind
        self.arg = arg
        self.when = when  # int | "always" | ("p", prob, seed)
        self.visits = 0
        self.rng = (
            random.Random(when[2]) if isinstance(when, tuple) else None
        )

    def due(self) -> bool:
        if self.when == "always":
            return True
        if isinstance(self.when, int):
            return self.visits == self.when
        return self.rng.random() < self.when[1]


#: point -> [directives]; empty when disarmed (the fast-path check).
_ARMED: dict = {}


def _parse_when(tok: str):
    if tok == "always":
        return "always"
    if tok.startswith("p"):
        body = tok[1:].lstrip("=")
        prob, _, seed = body.partition("@")
        return ("p", float(prob), int(seed or 0))
    n = int(tok)
    if n < 1:
        raise ValueError(f"fault visit index must be >= 1, got {n}")
    return n


def _parse_directive(text: str) -> _Directive:
    parts = text.split(":")
    if len(parts) not in (2, 3):
        raise ValueError(
            f"bad fault directive {text!r}: expected point:kind[:when]"
        )
    point = parts[0].strip()
    if point not in KNOWN_POINTS:
        raise ValueError(
            f"unknown fault point {point!r}; known: "
            + ", ".join(sorted(KNOWN_POINTS))
        )
    kind, _, argtxt = parts[1].strip().partition("=")
    if kind not in ("transient", "fatal", "delay", "kill", "torn",
                    "enospc", "oom"):
        raise ValueError(f"unknown fault kind {kind!r} in {text!r}")
    arg = float(argtxt) if argtxt else None
    when = _parse_when(parts[2].strip()) if len(parts) == 3 else 1
    return _Directive(point, kind, arg, when)


def configure(spec: str | None) -> dict:
    """(Re)arm the registry from a ``GAMESMAN_FAULTS`` spec string.

    Replaces the whole table (schedules restart from visit 0) — tests
    arm, run, and :func:`clear`. Raises ``ValueError`` on junk specs.
    """
    table: dict = {}
    for text in (spec or "").split(","):
        text = text.strip()
        if not text:
            continue
        d = _parse_directive(text)
        table.setdefault(d.point, []).append(d)
    _ARMED.clear()
    _ARMED.update(table)
    return dict(_ARMED)


def clear() -> None:
    """Disarm every fault point."""
    _ARMED.clear()


def known_points(prefix: str = "") -> list[str]:
    """Registered fault points, optionally filtered by name prefix."""
    return sorted(p for p in KNOWN_POINTS if p.startswith(prefix))


def _inject(d: _Directive, point: str, path, ctx: dict) -> None:
    where = f"{point} (visit {d.visits}{', ' + repr(ctx) if ctx else ''})"
    sys.stderr.write(f"[faults] injecting {d.kind} at {where}\n")
    sys.stderr.flush()
    default_registry().counter(
        "gamesman_faults_injected_total", "injected faults fired",
        point=point, kind=d.kind,
    ).inc()
    # Flight recorder (ISSUE 15): an injected fault is exactly the kind
    # of recent event a post-mortem dump must show.
    from gamesmanmpi_tpu.obs import flightrec

    flightrec.record("fault", point=point, fault_kind=d.kind,
                     visit=d.visits)
    if d.kind == "transient":
        raise TransientFault(f"injected transient fault at {where}")
    if d.kind == "fatal":
        raise FatalFault(f"injected fatal fault at {where}")
    if d.kind == "delay":
        time.sleep(d.arg if d.arg is not None else 0.05)
        return
    if d.kind == "enospc":
        import errno

        raise OSError(
            errno.ENOSPC,
            f"No space left on device (injected at {where})",
            str(path) if path is not None else None,
        )
    if d.kind == "oom":
        raise MemoryError(
            f"injected oom (RESOURCE_EXHAUSTED: out of memory) at {where}"
        )
    if d.kind == "torn":
        if path is not None and os.path.exists(path):
            size = os.path.getsize(path)
            with open(path, "r+b") as fh:
                fh.truncate(max(1, size // 2))
            sys.stderr.write(f"[faults] tore {path} ({size} -> {size // 2})\n")
            sys.stderr.flush()
        os._exit(TORN_EXIT_CODE)
    if d.kind == "kill":
        os._exit(int(d.arg) if d.arg is not None else KILL_EXIT_CODE)


def fire(point: str, path=None, **ctx) -> None:
    """Visit a fault point; inject whatever is armed for it.

    ``path`` names the file a checkpoint call site just wrote (the
    ``torn`` kind's target); ``ctx`` is free-form diagnostics (level,
    shard) echoed into the injection banner.
    """
    if not _ARMED:
        return
    ds = _ARMED.get(point)
    if not ds:
        return
    for d in ds:
        d.visits += 1
        if d.due():
            _inject(d, point, path, ctx)


# Arm from the environment at import so subprocess chaos needs no code:
# the harness sets GAMESMAN_FAULTS and launches the stock CLI. A
# malformed env var degrades to disarmed with a warning (same contract
# as the engine's _env_int knobs) — in a chaos run the harness notices
# because the expected death never happens.
try:
    configure(env_opt("GAMESMAN_FAULTS"))
except ValueError as e:  # pragma: no cover - env misuse
    warnings.warn(f"GAMESMAN_FAULTS ignored: {e}")
