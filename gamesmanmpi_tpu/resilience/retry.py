"""Transient/fatal classification + bounded exponential-backoff retry.

The engines' unit of recovery is one level step: forward expand+dedup,
or a backward resolve. Each step's inputs (the frontier, the window
triples, the stored provenance) stay referenced on the host across the
step, so re-dispatching the kernels after a transient runtime error is
idempotent — the same property that makes checkpoint resume exact. The
retry wrapper here is what turns that property into behavior: classify
the error, back off, optionally re-dispatch (``reset``), and re-raise
anything fatal untouched.

What counts as transient: injected :class:`TransientFault`, and runtime
errors whose message carries a known transient marker (the gRPC-ish
status words a remote-relay XLA backend surfaces when the transport
hiccups). ``RESOURCE_EXHAUSTED`` is deliberately NOT transient — an OOM
at a fixed shape will OOM again; retrying it would just triple the time
to the real failure. Extend the marker list for a specific deployment
with ``GAMESMAN_RETRY_MARKERS`` (comma-separated substrings).

Knobs: ``GAMESMAN_RETRY_ATTEMPTS`` (total tries per step, default 3;
1 disables retry), ``GAMESMAN_RETRY_BASE_SECS`` (first backoff, default
0.25, doubling per retry).
"""

from __future__ import annotations

import time

from gamesmanmpi_tpu.obs import default_registry
from gamesmanmpi_tpu.resilience.faults import FatalFault, TransientFault
from gamesmanmpi_tpu.utils.env import env_float as _env_float
from gamesmanmpi_tpu.utils.env import env_int as _env_int
from gamesmanmpi_tpu.utils.env import env_str

#: Message substrings (matched case-insensitively) that mark a runtime
#: error as transient. Conservative: transport/scheduling words only,
#: never OOM or compile errors.
TRANSIENT_MARKERS = (
    "injected transient",
    "deadline_exceeded",
    "deadline exceeded",
    "unavailable",
    "aborted",
    "cancelled",
    "connection reset",
    "connection refused",
    "broken pipe",
    "socket closed",
    "transport closed",
)


def is_transient(exc: BaseException) -> bool:
    """Would retrying the failed step plausibly succeed?"""
    if isinstance(exc, TransientFault):
        return True
    if isinstance(exc, FatalFault):
        return False
    # jaxlib's XlaRuntimeError subclasses RuntimeError; transport-level
    # failures can also surface as bare OSError from the relay socket.
    if not isinstance(exc, (RuntimeError, OSError)):
        return False
    msg = str(exc).lower()
    extra = tuple(
        m.strip().lower()
        for m in env_str("GAMESMAN_RETRY_MARKERS", "").split(",")
        if m.strip()
    )
    return any(m in msg for m in TRANSIENT_MARKERS + extra)


def retry_call(fn, *, point: str, reset=None, level=None, attempts=None,
               base_secs=None, logger=None, on_retry=None, registry=None,
               classify=is_transient, sleep=time.sleep):
    """Call ``fn`` with bounded exponential-backoff retry on transients.

    ``reset`` runs before each re-attempt (re-dispatch kernels from the
    step's held inputs — e.g. drop a stale speculative expand and re-run
    from the frontier). ``on_retry(attempt, exc)`` lets the owner count
    retries into its stats; every retry also lands in
    ``gamesman_retries_total{point=...}`` and, when a logger is given,
    as a ``{"phase": "retry", ...}`` JSONL record (the per-level stream
    tools/obs_report.py folds into its retries column).

    Fatal errors re-raise immediately; exhausted transients re-raise the
    last error — the caller's existing failure path is unchanged.
    """
    attempts = (
        _env_int("GAMESMAN_RETRY_ATTEMPTS", 3) if attempts is None
        else int(attempts)
    )
    attempts = max(1, attempts)
    base = (
        _env_float("GAMESMAN_RETRY_BASE_SECS", 0.25) if base_secs is None
        else float(base_secs)
    )
    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 - classified just below
            if attempt >= attempts or not classify(e):
                raise
            reg = registry or default_registry()
            reg.counter(
                "gamesman_retries_total",
                "transient step failures absorbed by retry",
                point=point,
            ).inc()
            # Flight recorder (ISSUE 15): retries are post-mortem gold —
            # a death minutes after a burst of absorbed transients reads
            # completely differently from one out of the blue.
            from gamesmanmpi_tpu.obs import flightrec

            flightrec.record(
                "retry", point=point, attempt=attempt,
                level=level, error=str(e)[:120],
            )
            if on_retry is not None:
                on_retry(attempt, e)
            if logger is not None:
                rec = {
                    "phase": "retry",
                    "point": point,
                    "attempt": attempt,
                    "error": str(e)[:200],
                }
                if level is not None:
                    rec["level"] = int(level)
                logger.log(rec)
            if base > 0:
                sleep(base * (2 ** (attempt - 1)))
            if reset is not None:
                reset()
