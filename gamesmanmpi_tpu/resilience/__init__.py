"""resilience: deterministic fault injection, transient retry, watchdog.

The runs this system exists for — multi-hour retrograde sweeps over
billions of positions (the Pentago in-core solve, the weeks-long
computation behind "Othello is Solved") — are longer than this
environment's relay MTBF, and the serving layer must degrade instead of
dying under reader faults. Three pieces, one subsystem:

* ``faults`` — a deterministic fault-injection registry: named fault
  points woven into checkpoint save/load, engine level steps, the
  sharded collectives, the DB probe and the batcher flush, armed via
  ``GAMESMAN_FAULTS="point:kind:when"``. Every schedule is replayable
  (occurrence-indexed or seeded), and a disarmed point costs one dict
  lookup.
* ``retry`` — transient-vs-fatal classification of runtime errors plus
  ``retry_call``, the bounded exponential-backoff wrapper the engines
  put around each level's forward/dedup/backward step. Re-entry is from
  the level's checkpoint-consistent inputs (idempotent thanks to the
  atomic ``_savez``), so an absorbed transient is invisible in the
  solved tables and visible in ``gamesman_retries_total``.
* ``supervisor`` — a per-level watchdog whose deadline derives from
  recent level times: when progress stalls past it, thread stacks and
  the last known progress are dumped and the process aborts with the
  checkpoint prefix intact — turning the heartbeat's "observed wedge"
  into a recoverable abort.

Above them (ISSUE 12) sit the campaign pieces:

* ``preempt`` — preemption grace: SIGTERM/SIGUSR1 drain the solve to
  the next level boundary (rank-coordinated in the sharded engine) and
  exit 75 resumable, with a hard deadline behind it.
* ``campaign`` — the solve-side supervisor (``tools/run_campaign.py``):
  auto-resume with bounded backoff, a no-progress breaker with a
  diagnosis bundle, disk-budget GC-and-retry, and an append-only
  ``campaign.jsonl`` ledger. docs/DISTRIBUTED.md "Campaigns".

The capstone test, ``tests/test_resilience.py``, kills a solve at every
registered fault point, resumes it, and asserts byte parity with an
uninterrupted solve; ``tests/test_campaign.py`` does the same one layer
up, to whole campaigns. docs/CONFIG.md lists every knob.
"""

from gamesmanmpi_tpu.resilience.faults import (
    FatalFault,
    FaultError,
    TransientFault,
    clear,
    configure,
    fire,
    known_points,
)
from gamesmanmpi_tpu.resilience.preempt import (
    GRACE_EXIT_CODE,
    PreemptionRequested,
)
from gamesmanmpi_tpu.resilience.retry import is_transient, retry_call
from gamesmanmpi_tpu.resilience.supervisor import Watchdog, maybe_watchdog

__all__ = [
    "GRACE_EXIT_CODE",
    "PreemptionRequested",
    "FaultError",
    "TransientFault",
    "FatalFault",
    "configure",
    "clear",
    "fire",
    "known_points",
    "is_transient",
    "retry_call",
    "Watchdog",
    "maybe_watchdog",
]
