"""Preemption grace: SIGTERM/SIGUSR1 -> seal-what's-complete -> exit 75.

The campaign regime (resilience/campaign.py, ROADMAP item 1) runs on
preemptible capacity: the scheduler's SIGTERM arrives mid-level with a
short eviction window, and the difference between "resume from level k"
and "re-discover three hours of frontier" is whether the solver spends
that window sealing what is already complete. This module is the
solver-side half of that contract:

* the CLI installs :func:`install_grace_handler` around a solve —
  SIGTERM/SIGUSR1 set a flag (a plain attribute store: CPython runs
  handlers on the main thread, so a handler that took a lock could
  deadlock against the very code it interrupted — the GM205 rule) and
  arm a one-shot grace deadline;
* the engines call :func:`check` at every level boundary (and the
  sharded solver folds the check into a rank-coordinated epoch round,
  so every rank raises at the SAME program point);
* :class:`PreemptionRequested` unwinds through the solve's ``finally``
  blocks — pending pipelined seals flush, the write-behind queue
  drains — and the CLI exits :data:`GRACE_EXIT_CODE` (75, EX_TEMPFAIL:
  "resumable, try again"), which the campaign supervisor classifies as
  a clean preemption;
* if the solve thread is wedged (inside a collective, a compile) and
  never reaches a boundary, the grace deadline
  (``GAMESMAN_PREEMPT_GRACE_SECS``, default 30) force-exits 124 — the
  watchdog's resumable-abort code. Either way the tree is never torn:
  every payload write is tmp+os.replace and every seal is an atomic
  manifest replace, so the worst case is an unsealed stray that resume
  already ignores.
"""

from __future__ import annotations

import os
import signal
import sys
import threading
import time
from typing import Optional

from gamesmanmpi_tpu.utils.env import env_float

#: EX_TEMPFAIL: the solve exited resumable under preemption grace. The
#: campaign supervisor (and any process manager) reads this as "restart
#: me against the same checkpoint directory".
GRACE_EXIT_CODE = 75

#: Signals that request graceful preemption (SIGUSR1 is the spelling for
#: schedulers that reserve SIGTERM for the hard kill).
GRACE_SIGNALS = (signal.SIGTERM, signal.SIGUSR1)


class PreemptionRequested(Exception):
    """Raised at a level boundary after a grace signal: the solve must
    stop here, with everything complete-so-far sealed. Deliberately NOT
    transient (resilience.retry) — retrying a preemption defeats it."""


#: Module state, written only by the signal handler (main thread) and
#: read by the level-boundary checks. Plain attribute stores — atomic
#: under the GIL, and the handler must stay lock-free (GM205).
_requested = False
_signum: Optional[int] = None
_deadline_timer: Optional[threading.Timer] = None


def requested() -> bool:
    """Has a grace signal arrived? (One falsy check per level boundary.)"""
    return _requested


def reset() -> None:
    """Clear the flag and disarm the deadline (tests; and the CLI's
    handler-restore path, so a later programmatic solve in the same
    process does not inherit a stale preemption)."""
    global _requested, _signum, _deadline_timer
    _requested = False
    _signum = None
    t = _deadline_timer
    _deadline_timer = None
    if t is not None:
        t.cancel()


def _force_exit(grace_secs: float) -> None:  # pragma: no cover - kills
    # The solve thread never reached a boundary inside the grace window
    # — wedged in a collective or a compile. Exit 124 (the watchdog's
    # resumable-abort code): atomic writes mean the tree is still
    # consistent, just without this level's seal.
    sys.stderr.write(
        f"[preempt] grace deadline ({grace_secs:.0f}s) expired before a "
        "level boundary; forcing resumable abort\n"
    )
    sys.stderr.flush()
    # Post-mortem first (timer thread, NOT the signal handler — taking
    # the recorder lock here is legal; the handler itself stays
    # lock-free per GM205): what was in flight when the grace window
    # closed is exactly what the next attempt's operator asks.
    from gamesmanmpi_tpu.obs import flightrec

    flightrec.record("preempt_deadline", grace_secs=grace_secs)
    flightrec.dump("preempt_deadline")
    from gamesmanmpi_tpu.resilience.supervisor import WATCHDOG_EXIT_CODE

    os._exit(WATCHDOG_EXIT_CODE)


def _on_grace_signal(signum, frame) -> None:
    # Lock-free by contract (GM205): attribute stores and a daemon-timer
    # spawn only. Re-delivery while already draining is a no-op (the
    # first deadline stands — a scheduler often re-signals).
    global _requested, _signum, _deadline_timer
    if _requested:
        return
    _requested = True
    _signum = signum
    grace = env_float("GAMESMAN_PREEMPT_GRACE_SECS", 30.0)
    sys.stderr.write(
        f"[preempt] signal {signum}: draining to the next level boundary "
        f"(grace {grace:.0f}s)\n"
    )
    sys.stderr.flush()
    if grace > 0:
        t = threading.Timer(grace, _force_exit, args=(grace,))
        t.daemon = True
        t.start()
        _deadline_timer = t


def install_grace_handler():
    """Install the grace handlers for a solve; returns a zero-arg
    restore callable (also disarms any pending deadline). No-op (restore
    still returned) when not on the main thread — programmatic solves in
    worker threads keep their host application's signal setup."""
    previous = {}
    for sig in GRACE_SIGNALS:
        try:
            previous[sig] = signal.signal(sig, _on_grace_signal)
        except ValueError:  # not the main thread
            pass

    def restore():
        for sig, handler in previous.items():
            signal.signal(sig, handler)
        reset()

    return restore


def check(phase: str, level=None, logger=None) -> None:
    """Level-boundary preemption point: raise :class:`PreemptionRequested`
    when a grace signal has arrived. Called by the engines wherever
    ``progress`` is replaced — the one program point where everything
    before it is sealed or sealable by the solve's ``finally``."""
    if not _requested:
        return
    from gamesmanmpi_tpu.obs import default_registry

    default_registry().counter(
        "gamesman_preempts_total",
        "solves stopped at a level boundary by preemption grace",
        phase=phase,
    ).inc()
    rec = {"phase": "preempt", "in_phase": phase,
           "signum": _signum, "wall_time": time.time()}
    if level is not None:
        rec["level"] = int(level)
    if logger is not None:
        try:
            logger.log(rec)
        except Exception:  # noqa: BLE001 - the preemption must win
            pass
    raise PreemptionRequested(
        f"grace signal {_signum} at {phase} boundary"
        + (f" (level {level})" if level is not None else "")
    )
