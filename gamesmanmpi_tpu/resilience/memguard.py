"""Host-memory guard: turn a looming OOM into a clean resumable death.

The campaign regime's fourth failure class (after crash, preemption,
and disk exhaustion) is memory exhaustion. Uncaught it is the WORST
death the stack can take: the kernel OOM-killer delivers SIGKILL with
no log line to classify, mid-level, possibly mid-write — a death the
campaign supervisor can only read as an anonymous ``signal``. This
module converts it into the best one: the engines call :func:`check`
at every level boundary (the same program point as the preemption
check — everything before it is sealed or sealable by the solve's
``finally``); when resident-set size crosses
``GAMESMAN_HOST_MEM_LIMIT_MB`` (0 = off, the default) the solve raises
:class:`HostMemoryExceeded` — a ``MemoryError``, so never transient
(``resilience.retry``: an OOM at a fixed shape OOMs again) — whose
message carries ``RESOURCE_EXHAUSTED``, the marker the campaign's
log-tail death classifier maps to ``oom``. The campaign then answers
with geometry escalation — more shards, smaller store cache
(``resilience/campaign.py``) — instead of retrying the same shape into
the same wall.

Under multi-process execution the raise is rank-local by design: the
peers unwind through the collective deadline (exit 124), and the whole
world's next attempt runs at the escalated geometry.
"""

from __future__ import annotations

import time

from gamesmanmpi_tpu.obs.heartbeat import rss_bytes
from gamesmanmpi_tpu.utils.env import env_float


class HostMemoryExceeded(MemoryError):
    """Raised at a level boundary when host RSS crossed the guard
    limit: a clean, classifiable stand-in for the allocator failure or
    kernel OOM-kill that was coming. Deliberately a MemoryError —
    ``resilience.retry.is_transient`` must never retry it."""


def limit_mb() -> float:
    """The guard threshold (``GAMESMAN_HOST_MEM_LIMIT_MB``; 0 = off)."""
    return env_float("GAMESMAN_HOST_MEM_LIMIT_MB", 0.0)


def check(phase: str, level=None, logger=None) -> None:
    """Level-boundary memory guard: raise :class:`HostMemoryExceeded`
    when host RSS exceeds the configured limit. One env read + one
    ``/proc/self/statm`` read per level boundary when armed; a single
    falsy check when off."""
    lim = limit_mb()
    if lim <= 0:
        return
    rss = rss_bytes()
    if rss is None:
        # RSS unmeasurable on this host (masked /proc, exotic platform):
        # an armed guard that cannot read memory must not fail the solve
        # — the kernel OOM-killer path remains, exactly as if unarmed.
        return
    rss_mb = rss / (1 << 20)
    if rss_mb <= lim:
        return
    from gamesmanmpi_tpu.obs import default_registry

    default_registry().counter(
        "gamesman_oom_guard_trips_total",
        "solves stopped at a level boundary by the host-memory guard",
        phase=phase,
    ).inc()
    rec = {"phase": "oom_guard", "in_phase": phase,
           "rss_mb": round(rss_mb, 1), "limit_mb": lim,
           "wall_time": time.time()}
    if level is not None:
        rec["level"] = int(level)
    if logger is not None:
        try:
            logger.log(rec)
        except Exception:  # noqa: BLE001 - the guard must win
            pass
    raise HostMemoryExceeded(
        f"host RSS {rss_mb:.0f} MiB exceeds "
        f"GAMESMAN_HOST_MEM_LIMIT_MB={lim:.0f} at {phase} boundary"
        + (f" (level {level})" if level is not None else "")
        + " — RESOURCE_EXHAUSTED: out of memory; the checkpoint prefix"
        " is sealed and resumable — escalate shards or shrink"
        " GAMESMAN_STORE_CACHE_MB (the campaign's oom policy does both)"
    )
