"""Per-level watchdog: turn an observed wedge into a recoverable abort.

The heartbeat (obs/heartbeat.py) reports WHERE a solve stopped; it does
nothing about it. A wedged accelerator call cannot be interrupted from
Python — the only honest recovery is to dump diagnostics and abort the
process while the checkpoint prefix is intact (every save is atomic, so
a restart resumes exactly). The watchdog is the thread that makes that
call: it polls the solver's ``progress`` dict (already replaced
atomically at every phase/level boundary for the heartbeat) and, when
progress stalls past a deadline derived from recent level times, dumps
the last known progress, the recent level durations, and every thread's
stack, then runs its abort action (default ``os._exit(124)``).

Deadline model: levels in one solve vary by orders of magnitude, so a
fixed timeout is either useless or trigger-happy. The deadline is::

    max(min_secs, factor * max(recent level durations))

— a level may take ``factor``x longer than the slowest level seen so
far before it is declared wedged. ``min_secs`` covers the first level
(no history yet) and compilation stalls.

Enable with ``GAMESMAN_WATCHDOG_SECS`` (the ``min_secs`` floor;
``--watchdog-secs`` is the CLI spelling; 0/unset = off) and tune with
``GAMESMAN_WATCHDOG_FACTOR`` (default 10).
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque
from typing import Callable, Optional

from gamesmanmpi_tpu.obs import default_registry
from gamesmanmpi_tpu.utils.env import env_float as _env_float

WATCHDOG_EXIT_CODE = 124


def _default_action() -> None:  # pragma: no cover - kills the process
    os._exit(WATCHDOG_EXIT_CODE)


class Watchdog:
    """Stall detector over a ``progress`` callable (daemon thread).

    ``progress`` is the same zero-arg callable the heartbeat reads: a
    dict replaced (never mutated) at each phase/level boundary. Any
    change of the dict counts as progress; the duration of each finished
    segment feeds the adaptive deadline. ``action`` (default: hard
    process exit) runs once after diagnostics are dumped — tests inject
    a callback instead of dying.
    """

    def __init__(self, progress: Callable[[], dict], *, min_secs: float,
                 factor: float = 10.0, history: int = 8,
                 poll: Optional[float] = None, action=None, logger=None,
                 registry=None, clock=time.monotonic):
        if min_secs <= 0:
            raise ValueError("watchdog min_secs must be positive")
        self.progress = progress
        self.min_secs = float(min_secs)
        self.factor = float(factor)
        self.action = action or _default_action
        self.logger = logger
        self.registry = registry or default_registry()
        self.recent: deque = deque(maxlen=history)
        self.expired = False
        self._clock = clock
        self._poll = poll if poll is not None else max(0.05, min_secs / 4)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "Watchdog":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="gamesman-watchdog", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "Watchdog":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # ------------------------------------------------------------- watching

    def deadline(self) -> float:
        """Current stall budget: factor x slowest recent segment, floored
        at min_secs."""
        if not self.recent:
            return self.min_secs
        return max(self.min_secs, self.factor * max(self.recent))

    def _snapshot(self) -> dict:
        try:
            return dict(self.progress() or {})
        except Exception:  # the watched solver owns its own errors
            return {}

    def _run(self) -> None:
        last = self._snapshot()
        seg_t0 = self._clock()
        while not self._stop.wait(self._poll):
            now = self._clock()
            cur = self._snapshot()
            if cur != last:
                self.recent.append(now - seg_t0)
                last = cur
                seg_t0 = now
                continue
            stalled = now - seg_t0
            if stalled > self.deadline():
                self._expire(cur, stalled)
                return

    def _expire(self, snapshot: dict, stalled: float) -> None:
        self.expired = True
        rec = {
            "phase": "watchdog_abort",
            "progress": snapshot,
            "stalled_secs": round(stalled, 3),
            "deadline_secs": round(self.deadline(), 3),
            "recent_segment_secs": [round(s, 3) for s in self.recent],
        }
        sys.stderr.write(f"[watchdog] stall detected: {rec}\n")
        # Every thread's stack: the one artifact that distinguishes "XLA
        # call never returned" from "host loop deadlocked".
        try:
            import faulthandler

            faulthandler.dump_traceback(file=sys.stderr)
        except Exception:
            pass
        sys.stderr.flush()
        self.registry.counter(
            "gamesman_watchdog_expired_total",
            "watchdog stall aborts",
        ).inc()
        if self.logger is not None:
            try:
                self.logger.log(rec)
            except Exception:
                pass
        # Post-mortem before the abort action (ISSUE 15): the flight
        # recorder names the last completed level and the spans that
        # were in flight when progress stopped — the diagnosis an
        # exit-124 used to need a rerun under instrumentation for.
        # (Watchdog thread, never a signal handler — locking is fine.)
        from gamesmanmpi_tpu.obs import flightrec

        flightrec.record("watchdog_abort",
                         stalled_secs=round(stalled, 3))
        flightrec.dump("watchdog_abort")
        self.action()


def maybe_watchdog(progress, *, logger=None) -> Optional[Watchdog]:
    """Env-gated watchdog the engines wrap their solve with: started
    when ``GAMESMAN_WATCHDOG_SECS`` > 0, else None."""
    secs = _env_float("GAMESMAN_WATCHDOG_SECS", 0.0)
    if secs <= 0:
        return None
    return Watchdog(
        progress,
        min_secs=secs,
        factor=_env_float("GAMESMAN_WATCHDOG_FACTOR", 10.0),
        logger=logger,
    ).start()
