"""serve: batched HTTP query serving over a solved-position database.

The traffic-facing half of the ROADMAP north star: `db/` makes a solve
persistent, this package makes it servable — a stdlib ThreadingHTTPServer
whose concurrent requests coalesce through a micro-batching queue (with
an LRU hot-position cache) into single vectorized DbReader probes.
"""

from gamesmanmpi_tpu.serve.batcher import (
    Batcher,
    BatcherClosed,
    BatcherOverloaded,
    BatcherTimeout,
    BatcherTripped,
    BatcherUnavailable,
)
from gamesmanmpi_tpu.serve.server import QueryServer

__all__ = [
    "Batcher",
    "BatcherUnavailable",
    "BatcherClosed",
    "BatcherTimeout",
    "BatcherOverloaded",
    "BatcherTripped",
    "QueryServer",
]
