"""serve: batched HTTP query serving over solved-position databases.

The traffic-facing half of the ROADMAP north star: `db/` makes a solve
persistent, this package makes it servable — a stdlib ThreadingHTTPServer
whose concurrent requests coalesce through a micro-batching queue (with
an LRU hot-position cache) into single vectorized DbReader probes, and,
at fleet scale, a supervisor that runs N such servers as supervised
worker processes over ONE shared listening socket and many game DBs
(`supervisor.py` / `worker.py` / `manifest.py` — docs/SERVING.md
"Fleet serving").
"""

from gamesmanmpi_tpu.serve.batcher import (
    Batcher,
    BatcherClosed,
    BatcherOverloaded,
    BatcherTimeout,
    BatcherTripped,
    BatcherUnavailable,
)
from gamesmanmpi_tpu.serve.manifest import (
    FleetEntry,
    load_fleet_manifest,
    single_db_entries,
)
from gamesmanmpi_tpu.serve.server import QueryServer
from gamesmanmpi_tpu.serve.supervisor import ServeSupervisor

__all__ = [
    "Batcher",
    "BatcherUnavailable",
    "BatcherClosed",
    "BatcherTimeout",
    "BatcherOverloaded",
    "BatcherTripped",
    "QueryServer",
    "ServeSupervisor",
    "FleetEntry",
    "load_fleet_manifest",
    "single_db_entries",
]
