"""Serving-fleet supervisor: N query-serving workers, one socket.

One Python process cannot serve millions of users: a single GIL and a
single crash domain sit between the solved DBs and the traffic. This
module is the process-tree answer (ROADMAP item 3): a supervisor that

* binds the listening socket ONCE (``LISTEN_BACKLOG`` deep) and opens
  every fleet DB's ``DbReader`` in the parent, then
* spawns N workers that share the socket's accept queue — by ``fork``
  when the parent has never initialized a jax backend (the CLI path:
  the mmap'd DB pages, the page cache, and the imported interpreter all
  come for free), by re-exec (``python -m gamesmanmpi_tpu.serve.worker``
  with inherited fds) when fork would clone a live XLA runtime whose
  thread pools do not survive it, and
* owns their lifecycle: liveness via a heartbeat pipe per worker
  (crash = pipe EOF, hang = beat deadline), bounded exponential-backoff
  restart with a restart-storm breaker, warm-start gating (a worker
  joins the ready set only after ``db.check.verify_for_serving`` and a
  real self-probe — see serve/worker.py), and rolling restart / rolling
  fleet-manifest reload that drains ONE worker at a time so in-flight
  requests are never dropped.

The supervisor never serves queries itself and never touches a jax
backend; its control surface is a tiny HTTP endpoint (``/healthz``
aggregating per-worker state, ``/metrics``, ``POST /reload``) on a
separate control port.

Thread model: one scheduler thread (``run``) owns the state machine;
the control server's handler threads and signal handlers only read
snapshots (``status()``) or set request flags — both under ``_lock`` —
and wake the scheduler through a self-pipe. Worker-death handling is
edge-triggered off the pipes, so the idle supervisor costs zero CPU.
"""

from __future__ import annotations

import collections
import json
import os
import selectors
import signal
import socket
import subprocess
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from gamesmanmpi_tpu.obs import default_registry
from gamesmanmpi_tpu.resilience import faults
from gamesmanmpi_tpu.serve.manifest import FleetEntry, load_fleet_manifest
from gamesmanmpi_tpu.serve.server import LISTEN_BACKLOG, PROMETHEUS_CONTENT_TYPE
from gamesmanmpi_tpu.utils.env import env_float, env_int

__all__ = ["ServeSupervisor", "FleetEntry", "load_fleet_manifest"]

#: Slot states. ``broken`` is the restart-storm breaker: the slot has
#: died so often inside the storm window that restarting it immediately
#: would only burn CPU on a crash loop — it waits out a cool-off, then
#: half-opens with one more spawn attempt.
STATES = ("starting", "ready", "draining", "restarting", "broken", "stopped")


class _ForkProc:
    """Child handle for the fork spawn path (waitpid-based)."""

    def __init__(self, pid: int):
        self.pid = pid
        self._rc = None

    def kill(self, sig) -> None:
        if self._rc is None:
            try:
                os.kill(self.pid, sig)
            except ProcessLookupError:
                pass

    def poll(self):
        if self._rc is None:
            try:
                pid, status = os.waitpid(self.pid, os.WNOHANG)
            except ChildProcessError:
                return None
            if pid == self.pid:
                self._rc = os.waitstatus_to_exitcode(status)
        return self._rc


class _ExecProc:
    """Child handle for the re-exec spawn path (Popen-based)."""

    def __init__(self, proc):
        self._proc = proc
        self.pid = proc.pid

    def kill(self, sig) -> None:
        try:
            self._proc.send_signal(sig)
        except ProcessLookupError:
            pass

    def poll(self):
        return self._proc.poll()


class _Slot:
    """One worker slot's record. Mutated only under the supervisor's
    ``_lock`` (the scheduler thread does the mutating; the control
    thread reads copies via ``status()``)."""

    def __init__(self, idx: int):
        self.idx = idx
        self.gen = -1  # config generation the running worker was built from
        self.proc = None
        self.fd = None  # heartbeat pipe read end
        self.buf = b""
        self.state = "restarting"  # pre-first-spawn: due immediately
        self.pid = None
        self.health = "unknown"  # worker-reported /healthz status
        self.heard = False  # any pipe bytes from the CURRENT process yet
        self.half_open = False  # this spawn is a breaker's single probe
        self.last_msg = 0.0  # monotonic time of the last pipe message
        self.ready_info: dict = {}
        self.restarts = 0
        self.recent: list = []  # restart times inside the storm window
        self.backoff_n = 0
        self.next_spawn_at = 0.0  # monotonic; None = no spawn scheduled
        self.drain_deadline = None
        self.last_error = None
        self.slo: dict = {}  # latest burn-rate snapshot off the beat


class ServeSupervisor:
    """Fleet supervisor; construct, then ``run()`` (or ``start()`` for a
    background scheduler in tests/benches)."""

    def __init__(self, entries, *, workers: int = 2,
                 host: str = "127.0.0.1", port: int = 0,
                 control_port: int | None = 0,
                 manifest_path=None,
                 server_config: dict | None = None,
                 jsonl=None,
                 heartbeat_secs: float | None = None,
                 heartbeat_timeout: float | None = None,
                 restart_base: float | None = None,
                 restart_max: float | None = None,
                 storm_restarts: int | None = None,
                 storm_secs: float | None = None,
                 drain_grace: float = 10.0,
                 spawn=None, logger=None, registry=None):
        if workers < 1:
            raise ValueError(f"workers must be >= 1, got {workers}")
        self.entries: list[FleetEntry] = list(entries)
        if not self.entries:
            raise ValueError("a fleet needs at least one DB entry")
        self.workers = int(workers)
        self.manifest_path = manifest_path
        self.server_config = dict(server_config or {})
        self.jsonl = jsonl
        self.logger = logger
        self.registry = registry or default_registry()
        self.drain_grace = float(drain_grace)
        self.heartbeat_secs = (
            env_float("GAMESMAN_SERVE_HEARTBEAT_SECS", 1.0)
            if heartbeat_secs is None else float(heartbeat_secs)
        )
        if heartbeat_timeout is None:
            heartbeat_timeout = env_float(
                "GAMESMAN_SERVE_HEARTBEAT_TIMEOUT",
                max(5.0, 5.0 * self.heartbeat_secs),
            )
        self.heartbeat_timeout = float(heartbeat_timeout)
        self.restart_base = (
            env_float("GAMESMAN_SERVE_RESTART_BASE_SECS", 0.5)
            if restart_base is None else float(restart_base)
        )
        self.restart_max = (
            env_float("GAMESMAN_SERVE_RESTART_MAX_SECS", 30.0)
            if restart_max is None else float(restart_max)
        )
        self.storm_restarts = max(2, (
            env_int("GAMESMAN_SERVE_STORM_RESTARTS", 5)
            if storm_restarts is None else int(storm_restarts)
        ))
        self.storm_secs = (
            env_float("GAMESMAN_SERVE_STORM_SECS", 60.0)
            if storm_secs is None else float(storm_secs)
        )
        # Before the worker's FIRST pipe byte the silence deadline has
        # not started: a cold exec spawn pays interpreter + jax import
        # before it can say "hello", which must not read as a hang.
        self.spawn_grace = max(
            self.heartbeat_timeout,
            env_float("GAMESMAN_SERVE_SPAWN_GRACE_SECS", 60.0),
        )
        # The fleet's one listening socket: bound and listening BEFORE
        # any worker exists, so the accept queue outlives every one of
        # them — during a rolling restart arriving connections simply
        # wait in the backlog for the next accept.
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(LISTEN_BACKLOG)
        self.host, self.port = self._sock.getsockname()[:2]
        # Parent-side readers: opened BEFORE any spawn — this validates
        # every DB's identity once, establishes the mmaps whose
        # file-backed pages all workers share through the page cache,
        # and is what "fork after DbReader open" buys on the fork path.
        # The parent never probes them (a probe would initialize a jax
        # backend and forbid fork).
        self.readers = self._open_readers(self.entries)
        # Cross-worker decoded-block cache (store/shm.py, ISSUE 18):
        # the supervisor owns segment lifecycle — created here, name
        # handed to every worker cfg, swapped on a manifest reload
        # (stale epochs already read as misses; the swap just drops the
        # dead weight), unlinked at shutdown. None when disabled
        # (GAMESMAN_SHM_CACHE_MB=0) or no fleet DB has blocked levels
        # (v1 DBs mmap — there is nothing decoded to share).
        self._shm_seq = 0
        self._shm_backup = None  # pre-roll segment; guarded-by: _lock
        self._shm = self._create_shm()
        self._spawn = spawn or self._default_spawn
        self._spawn_mode = "fork" if self._use_fork() else "exec"
        self._sel = selectors.DefaultSelector()
        self._by_fd: dict = {}  # fd -> slot (scheduler thread only)
        self._wake_r, self._wake_w = os.pipe()
        os.set_blocking(self._wake_r, False)
        self._sel.register(self._wake_r, selectors.EVENT_READ, None)
        self._lock = threading.Lock()
        self._slots = [_Slot(i) for i in range(self.workers)]
        self._gen = 0
        # Signal-safe request flags: WRITTEN lock-free from signal
        # handlers / any thread (atomic attribute store), read by the
        # scheduler. Everything else below is lock-guarded.
        self._stop_requested = False
        self._reload_requested = False
        self._stopping = False  # guarded-by: _lock
        self._last_reload_error = None  # guarded-by: _lock
        self._roll_queue = None  # guarded-by: _lock
        self._roll_backup = None  # pre-roll (entries, readers); guarded-by: _lock
        self._rolling_back = False  # guarded-by: _lock
        self._reloads_done = 0  # guarded-by: _lock
        # Last registry sync report (POST /registry-sync from the pull
        # client, registry/pull.py): what epoch set the replica last
        # tried to land and whether the roll happened. None until a
        # sync ever reported.
        self._registry_sync = None  # guarded-by: _lock
        # Fleet-wide trace ring: workers tail-sample per-request traces
        # (obs/qtrace.py) and ship newly kept ones on heartbeat beats —
        # the only per-worker channel, since all workers share one
        # accept queue and cannot be HTTP-addressed individually. The
        # control port serves the aggregate at GET /traces.
        self._fleet_traces: collections.deque = collections.deque(
            maxlen=max(1, env_int("GAMESMAN_TRACE_FLEET_RING", 2048))
        )  # guarded-by: _lock
        self._thread = None
        self._control = None
        self._control_thread = None
        self.control_port = None
        if control_port is not None:
            self._control = _ControlServer((host, int(control_port)), self)
            self.control_port = self._control.server_address[1]

    # ------------------------------------------------------------- plumbing

    @staticmethod
    def _open_readers(entries):
        from gamesmanmpi_tpu.db import DbFormatError, DbReader

        readers: dict = {}
        entry = None
        try:
            for entry in entries:
                readers[entry.name] = DbReader(entry.db)
        except OSError as exc:
            _close_readers(readers)
            # An unreadable DB is a DB problem, not a bind problem: let
            # callers' DbFormatError handling attribute it correctly.
            raise DbFormatError(
                f"cannot open DB {entry.db}: {exc}"
            ) from exc
        except Exception:
            _close_readers(readers)
            raise
        return readers

    def _create_shm(self):
        """Create the fleet's shared decoded-block segment, sized from
        the manifests: one slot holds the largest decoded (keys, cells)
        block pair any routed DB can produce, and the
        ``GAMESMAN_SHM_CACHE_MB`` budget caps the whole segment. A
        creation failure (exhausted /dev/shm, tiny budget) degrades to
        per-worker private caches — never a refusal to serve."""
        budget_mb = env_int("GAMESMAN_SHM_CACHE_MB", 256)
        if budget_mb <= 0:
            return None
        from gamesmanmpi_tpu.db.format import level_is_blocked

        slot_bytes = 0
        for reader in self.readers.values():
            for rec in reader.manifest["levels"].values():
                if not level_is_blocked(rec):
                    continue
                nbytes = sum(
                    int(idx["block_positions"])
                    * np.dtype(idx["dtype"]).itemsize
                    for idx in (rec["keys_blocks"], rec["cells_blocks"])
                )
                slot_bytes = max(slot_bytes, nbytes)
        if slot_bytes == 0:
            return None
        from gamesmanmpi_tpu.store import ShmBlockCache

        self._shm_seq += 1
        name = f"gmshm-{os.getpid()}-{self._shm_seq}"
        try:
            shm = ShmBlockCache.create(
                name, slot_bytes=slot_bytes,
                budget_bytes=budget_mb << 20, registry=self.registry,
            )
        except (ValueError, OSError) as e:
            self._log({"phase": "serve_shm_disabled",
                       "error": f"{type(e).__name__}: {e}"[:300]})
            return None
        self._log({"phase": "serve_shm_created", "segment": name,
                   "nslots": shm.nslots, "slot_bytes": shm.slot_bytes})
        return shm

    @staticmethod
    def _unlink_shm(shm) -> None:
        if shm is not None:
            try:
                shm.unlink()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass

    @staticmethod
    def _use_fork() -> bool:
        """Fork only while this process has never initialized a jax
        backend: XLA's client owns thread pools and locks that do not
        survive fork, and a worker that inherits them deadlocks at its
        first kernel. After backend init, workers re-exec instead."""
        if not hasattr(os, "fork"):
            return False
        try:
            from jax._src import xla_bridge

            return not xla_bridge.backends_are_initialized()
        except Exception:  # noqa: BLE001 - jax internals moved: be safe
            return False

    def _log(self, record: dict) -> None:
        if self.logger is not None:
            self.logger.log(record)

    def _worker_cfg(self, slot) -> dict:
        cfg = {
            "worker_id": slot.idx,
            "entries": [[e.name, e.db] for e in self.entries],
            "heartbeat_secs": self.heartbeat_secs,
            **self.server_config,
        }
        if self.jsonl:
            cfg["jsonl"] = _worker_path(self.jsonl, slot.idx)
        if self._shm is not None:
            cfg["shm_segment"] = self._shm.name
        return cfg

    def _default_spawn(self, slot_idx: int, cfg: dict):
        """Spawn a worker process; returns (proc handle, pipe read fd)."""
        r, w = os.pipe()
        if self._spawn_mode == "fork":
            # Grab every fd the child must NOT keep before forking.
            other_fds = [s.fd for s in self._slots
                         if s.fd is not None] + [r, self._wake_r,
                                                 self._wake_w]
            control_fd = (self._control.fileno()
                          if self._control is not None else None)
            pid = os.fork()
            if pid == 0:
                from gamesmanmpi_tpu.serve.worker import EXIT_CRASH, run_worker

                code = EXIT_CRASH
                try:
                    for fd in other_fds:
                        try:
                            os.close(fd)
                        except OSError:
                            pass
                    if control_fd is not None:
                        try:
                            os.close(control_fd)
                        except OSError:
                            pass
                    code = run_worker(cfg, self._sock, w)
                except BaseException as e:  # noqa: BLE001 - report + die
                    sys.stderr.write(f"[worker {slot_idx}] crashed in "
                                     f"spawn: {e!r}\n")
                finally:
                    # Never run the supervisor's atexit/stack in a child.
                    os._exit(code)
            os.close(w)
            return _ForkProc(pid), r
        sock_fd = self._sock.fileno()
        child_cfg = dict(cfg, listen_fd=sock_fd, pipe_fd=w)
        proc = subprocess.Popen(
            [sys.executable, "-m", "gamesmanmpi_tpu.serve.worker",
             json.dumps(child_cfg)],
            pass_fds=(sock_fd, w),
        )
        os.close(w)
        return _ExecProc(proc), r

    def _wake(self) -> None:
        try:
            os.write(self._wake_w, b"x")
        except OSError:
            pass

    # ---------------------------------------------------------- public API

    def request_stop(self) -> None:
        # NO lock here: CPython runs signal handlers on the scheduler's
        # own main thread (the CLI path), so taking the non-reentrant
        # lock from a handler that interrupted a `with _lock:` block
        # would deadlock the supervisor. A plain attribute store and a
        # pipe write are both safe from a handler.
        self._stop_requested = True
        self._wake()

    def request_reload(self) -> None:
        """Ask the scheduler for a rolling reload (re-read the fleet
        manifest when one was given, then drain-and-replace one worker
        at a time). Safe from any thread / signal handler (lock-free —
        see request_stop)."""
        self._reload_requested = True
        self._wake()

    def note_registry_sync(self, info: dict) -> None:
        """Record a registry pull client's sync report (shown in
        /status as ``registry_sync``) — observability only; the roll
        itself arrives via the normal request_reload path."""
        keep = {
            k: info.get(k)
            for k in ("status", "epochs", "failed", "wall_time")
        }
        with self._lock:
            self._registry_sync = keep

    # wire: producer
    def status(self) -> dict:
        """Fleet-level health snapshot (the control /healthz payload)."""
        now = time.monotonic()
        with self._lock:
            workers = {}
            ready = 0
            for s in self._slots:
                if s.state == "ready":
                    ready += 1
                workers[str(s.idx)] = {
                    "state": s.state,
                    "pid": s.pid,
                    "health": s.health,
                    "restarts": s.restarts,
                    "breaker": "open" if s.state == "broken" else "ok",
                    "gen": s.gen,
                    "last_beat_age": round(now - s.last_msg, 3)
                    if s.last_msg else None,
                    "last_error": s.last_error,
                    "verified": s.ready_info.get("verified"),
                    "warmup_secs": s.ready_info.get("warmup_secs"),
                    "slo": s.slo or None,
                }
            degraded = any(
                s.state == "ready" and s.health not in ("ok", "unknown")
                for s in self._slots
            )
            if self._stopping:
                status = "draining"
            elif ready == self.workers and not degraded:
                status = "ok"
            elif ready > 0:
                status = "degraded"
            else:
                status = "down"
            return {
                "status": status,
                "workers": workers,
                "workers_total": self.workers,
                "ready": ready,
                "port": self.port,
                "control_port": self.control_port,
                "games": sorted(e.name or "default" for e in self.entries),
                "gen": self._gen,
                "reload_in_progress": self._roll_queue is not None,
                "reloads_done": self._reloads_done,
                "last_reload_error": self._last_reload_error,
                "spawn_mode": self._spawn_mode,
                "slo_fast_burn": any(
                    s.slo.get("fast_burn") for s in self._slots
                ),
                "registry_sync": self._registry_sync,
            }

    def traces(self) -> dict:
        """Fleet-wide sampled-trace snapshot (the control /traces
        payload): every tail-kept query trace workers shipped on their
        beats, oldest first, bounded by GAMESMAN_TRACE_FLEET_RING."""
        with self._lock:
            recs = list(self._fleet_traces)
        return {"kind": "qtrace_fleet", "count": len(recs), "traces": recs}

    def start(self):
        """Run the scheduler in a background thread (tests, benches)."""
        self._thread = threading.Thread(
            target=self.run, name="gamesman-supervisor", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 30.0) -> None:
        self.request_stop()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    def run(self) -> int:
        """The scheduler loop: spawn, supervise, roll, drain, exit 0."""
        if self._control is not None:
            self._control_thread = threading.Thread(
                target=self._control.serve_forever,
                name="gamesman-supervisor-control", daemon=True,
            )
            self._control_thread.start()
        try:
            while True:
                with self._lock:
                    if self._stop_requested:
                        break
                self._poll(0.25)
        finally:
            self._shutdown()
        return 0

    # ------------------------------------------------------- scheduler loop

    def _poll(self, max_wait: float) -> None:
        now = time.monotonic()
        self._spawn_due(now)
        self._handle_reload_request()
        self._advance_roll(now)
        deadline = self._earliest_deadline(now)
        wait = max(0.0, min(max_wait, deadline - now))
        self._dispatch_events(self._sel.select(wait))
        # A slow handler above (a _reap can block the scheduler for up
        # to ~2 s on a wedged teardown) leaves sibling beats unread in
        # their pipe buffers; judging silence on last_msg now would
        # SIGKILL healthy workers. Drain whatever is already readable
        # first (bounded passes — each consumes all that was ready).
        for _ in range(4):
            events = self._sel.select(0)
            if not events:
                break
            self._dispatch_events(events)
        self._check_liveness(time.monotonic())

    def _dispatch_events(self, events) -> None:
        for key, _ in events:
            if key.fd == self._wake_r:
                try:
                    os.read(self._wake_r, 4096)
                except OSError:
                    pass
                continue
            self._drain_pipe(key.fd)

    def _silence_allowance(self, slot) -> float:
        """Seconds of pipe silence this slot is allowed right now: the
        spawn grace until its FIRST byte (interpreter + jax import on a
        cold exec spawn), the beat deadline after."""
        return self.heartbeat_timeout if slot.heard else self.spawn_grace

    def _earliest_deadline(self, now: float) -> float:
        horizon = now + 60.0
        with self._lock:
            for s in self._slots:
                if s.next_spawn_at is not None and s.state in (
                        "restarting", "broken"):
                    horizon = min(horizon, s.next_spawn_at)
                if s.state in ("starting", "ready") and s.last_msg:
                    horizon = min(
                        horizon, s.last_msg + self._silence_allowance(s)
                    )
                if s.drain_deadline is not None:
                    horizon = min(horizon, s.drain_deadline)
        return horizon

    def _spawn_due(self, now: float) -> None:
        with self._lock:
            if self._stopping:
                return
            due = [
                s for s in self._slots
                if s.state in ("restarting", "broken")
                and s.next_spawn_at is not None and s.next_spawn_at <= now
            ]
        for slot in due:
            self._spawn_slot(slot, now)

    def _spawn_slot(self, slot, now: float) -> None:
        cfg = None
        with self._lock:
            was_broken = slot.state == "broken"
            slot.gen = self._gen
            cfg = self._worker_cfg(slot)
        try:
            proc, fd = self._spawn(slot.idx, cfg)
        except Exception as e:  # noqa: BLE001 - a failed spawn is a death
            with self._lock:
                slot.last_error = f"spawn failed: {e!r}"
            self._schedule_restart(slot, now, f"spawn failed: {e!r}")
            return
        os.set_blocking(fd, False)
        self._sel.register(fd, selectors.EVENT_READ, slot)
        self._by_fd[fd] = slot
        with self._lock:
            slot.proc = proc
            slot.fd = fd
            slot.buf = b""
            slot.state = "starting"
            slot.pid = proc.pid
            slot.health = "unknown"
            slot.heard = False
            slot.half_open = was_broken
            slot.last_msg = now
            slot.ready_info = {}
            slot.next_spawn_at = None
            slot.drain_deadline = None
        if was_broken:
            self.registry.gauge(
                "gamesman_serve_storm_breaker_open",
                "1 while a slot's restart-storm breaker is open",
                worker=str(slot.idx),
            ).set(0)
        self._log({"phase": "serve_worker_spawn", "worker": slot.idx,
                   "pid": proc.pid})

    def _drain_pipe(self, fd: int) -> None:
        slot = self._by_fd.get(fd)
        if slot is None:
            return
        eof = False
        chunks = []
        while True:
            try:
                data = os.read(fd, 65536)
            except BlockingIOError:
                break
            except OSError:
                eof = True
                break
            if not data:
                eof = True
                break
            chunks.append(data)
        now = time.monotonic()
        if chunks:
            with self._lock:
                slot.buf += b"".join(chunks)
                slot.heard = True
                slot.last_msg = now
                lines, _, slot.buf = slot.buf.rpartition(b"\n")
            for line in lines.splitlines():
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError:
                    continue
                self._on_msg(slot, msg, now)
        if eof:
            self._on_pipe_eof(slot, now)

    # wire: consumer
    def _on_msg(self, slot, msg: dict, now: float) -> None:
        kind = msg.get("type")
        if kind == "hello":
            with self._lock:
                slot.pid = msg.get("pid", slot.pid)
        elif kind == "ready":
            with self._lock:
                slot.state = "ready"
                slot.health = "ok"
                slot.ready_info = msg
                slot.backoff_n = 0
                slot.half_open = False  # the breaker's probe succeeded
                slot.last_error = None
            self.registry.gauge(
                "gamesman_serve_worker_up",
                "1 while this worker slot is in the ready set",
                worker=str(slot.idx),
            ).set(1)
            self._log({"phase": "serve_worker_ready", "worker": slot.idx,
                       "pid": slot.pid,
                       "warmup_secs": msg.get("warmup_secs")})
        elif kind == "beat":
            sampled = msg.get("traces") or ()
            with self._lock:
                slot.health = msg.get("status", "ok")
                slo = msg.get("slo")
                if isinstance(slo, dict):
                    slot.slo = slo
                for rec in sampled:
                    if isinstance(rec, dict):
                        rec.setdefault("worker", slot.idx)
                        self._fleet_traces.append(rec)
            self.registry.counter(
                "gamesman_serve_heartbeats_total",
                "worker heartbeats received by the supervisor",
                worker=str(slot.idx),
            ).inc()
            if sampled:
                self.registry.counter(
                    "gamesman_serve_traces_ingested_total",
                    "sampled query traces received on worker beats",
                    worker=str(slot.idx),
                ).inc(len(sampled))
        elif kind == "failed":
            with self._lock:
                slot.last_error = msg.get("error")
        elif kind == "draining":
            with self._lock:
                if slot.state != "draining":
                    slot.state = "draining"
                if slot.drain_deadline is None:
                    # An EXTERNAL SIGTERM (operator/process manager):
                    # the supervisor didn't start this drain, but it
                    # still owns the deadline — a teardown that wedges
                    # after announcing "draining" must not linger.
                    slot.drain_deadline = now + self.drain_grace

    def _on_pipe_eof(self, slot, now: float) -> None:
        if slot.fd is not None:
            try:
                self._sel.unregister(slot.fd)
            except (KeyError, ValueError):
                pass
            self._by_fd.pop(slot.fd, None)
            try:
                os.close(slot.fd)
            except OSError:
                pass
        rc = self._reap(slot)
        with self._lock:
            was = slot.state
            stopping = self._stopping
            slot.fd = None
            slot.proc = None
            slot.drain_deadline = None
        self.registry.gauge(
            "gamesman_serve_worker_up",
            "1 while this worker slot is in the ready set",
            worker=str(slot.idx),
        ).set(0)
        if stopping:
            with self._lock:
                slot.state = "stopped"
            return
        if was == "draining" and rc == 0:
            # A clean drained exit: the supervisor's own rolling
            # restart/reload, or an EXTERNAL SIGTERM (an operator or a
            # process manager poking one worker). Either way the slot
            # is replaced NOW, no backoff — the supervisor owns the
            # fleet size; only a whole-fleet stop parks slots.
            self._log({"phase": "serve_worker_drained",
                       "worker": slot.idx})
            self._spawn_slot(slot, now)
            return
        why = f"exit rc={rc}"
        with self._lock:
            if slot.last_error:
                why = f"{why} ({slot.last_error})"
        self._schedule_restart(slot, now, why)

    def _reap(self, slot):
        """Collect the dead worker's exit code; a process that outlives
        its own closed pipe (a wedged teardown) is SIGKILLed NOW — the
        scheduler thread must not wait it out, or sibling heartbeats sit
        unread long enough to read as stalls."""
        proc = slot.proc
        if proc is None:
            return None
        deadline = time.monotonic() + 0.1
        while time.monotonic() < deadline:
            rc = proc.poll()
            if rc is not None:
                return rc
            time.sleep(0.005)
        proc.kill(signal.SIGKILL)
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            rc = proc.poll()
            if rc is not None:
                return rc
            time.sleep(0.01)
        return None

    def _schedule_restart(self, slot, now: float, why: str) -> None:
        with self._lock:
            slot.restarts += 1
            slot.recent = [
                t for t in slot.recent if t > now - self.storm_secs
            ] + [now]
            # A dead half-open probe re-opens the breaker DIRECTLY: the
            # prior deaths aged out of the window during the hold-down,
            # and "half-opens with ONE spawn" means one — not a fresh
            # storm budget of crash-loops per window.
            storm = slot.half_open \
                or len(slot.recent) >= self.storm_restarts
            if storm:
                # Restart-storm breaker: crash-looping this fast means
                # the problem is not transient (a rotted DB fails every
                # warm-start verify identically) — hold the slot down
                # for a full storm window, then half-open with ONE try.
                slot.state = "broken"
                delay = self.storm_secs
                slot.backoff_n = 0
            else:
                slot.state = "restarting"
                delay = min(
                    self.restart_base * (2 ** slot.backoff_n),
                    self.restart_max,
                )
                slot.backoff_n += 1
            slot.next_spawn_at = now + delay
            slot.last_error = why
        self.registry.counter(
            "gamesman_serve_worker_restarts_total",
            "worker deaths that scheduled a supervisor restart",
            worker=str(slot.idx),
        ).inc()
        if storm:
            self.registry.gauge(
                "gamesman_serve_storm_breaker_open",
                "1 while a slot's restart-storm breaker is open",
                worker=str(slot.idx),
            ).set(1)
        self._log({
            "phase": "serve_worker_death", "worker": slot.idx,
            "why": why, "restart_in_secs": round(delay, 3),
            "breaker": "open" if storm else "ok",
        })

    def _check_liveness(self, now: float) -> None:
        hung = []
        with self._lock:
            for s in self._slots:
                allowance = self._silence_allowance(s)
                if s.state in ("starting", "ready") and s.last_msg and \
                        now - s.last_msg > allowance:
                    s.last_error = (
                        f"heartbeat stall ({now - s.last_msg:.1f}s "
                        f"> {allowance:g}s)"
                    )
                    hung.append(s)
                elif s.drain_deadline is not None and \
                        now > s.drain_deadline:
                    s.last_error = "drain deadline exceeded"
                    hung.append(s)
        for s in hung:
            # A hung worker cannot drain; SIGKILL turns it into an
            # ordinary death (pipe EOF -> backoff restart).
            self._log({"phase": "serve_worker_hang", "worker": s.idx,
                       "why": s.last_error})
            if s.proc is not None:
                s.proc.kill(signal.SIGKILL)

    # -------------------------------------------------- rolling restart/reload

    def _handle_reload_request(self) -> None:
        with self._lock:
            requested = self._reload_requested
            rolling = self._roll_queue is not None
            stopping = self._stopping
            # Consume the flag only when acting on it: a reload asked
            # for DURING a roll stays pending and starts the moment the
            # current roll finishes — never silently dropped.
            if requested and not rolling:
                self._reload_requested = False
        if not requested or rolling or stopping:
            return
        prev = (self.entries, self.readers)
        try:
            faults.fire("serve.reload")
            if self.manifest_path is not None:
                entries = load_fleet_manifest(self.manifest_path)
                # Open the NEW readers before touching fleet state: a
                # manifest pointing at a missing/corrupt DB must fail
                # the reload here, with every worker still serving the
                # old fleet untouched.
                readers = self._open_readers(entries)
                self.entries = entries
                self.readers = readers
                # New fleet config -> new shared segment (sized for the
                # new DBs); the old one keeps serving the old-gen
                # workers until the roll finishes ("done" unlinks it).
                # Correctness never depends on this swap — a reloaded
                # DB's epoch turns every old slot into a miss.
                with self._lock:
                    self._shm_backup, self._shm = self._shm, None
                self._shm = self._create_shm()
        except Exception as e:  # noqa: BLE001 - a failed reload must not
            # take the fleet down: report it and keep serving as-is.
            with self._lock:
                self._last_reload_error = f"{type(e).__name__}: {e}"
            self._log({"phase": "serve_reload_failed",
                       "error": str(e)[:300]})
            return
        with self._lock:
            self._gen += 1
            self._roll_queue = [s.idx for s in self._slots]
            self._roll_backup = prev  # for a mid-roll abort
            self._rolling_back = False
            self._last_reload_error = None
            gen = self._gen
        self._log({"phase": "serve_reload_started", "gen": gen})

    def _advance_roll(self, now: float) -> None:
        action = None  # "done" | ("drain", slot, proc) | ("abort", slot)
        with self._lock:
            if self._roll_queue is None:
                return
            if not self._roll_queue:
                self._roll_queue = None
                self._reloads_done += 1
                action = "done"
            else:
                slot = self._slots[self._roll_queue[0]]
                if slot.state == "broken" and slot.gen == self._gen:
                    # The replacement cannot pass warm start on the new
                    # config (a structurally-valid manifest whose DB is
                    # rotted passes the parent's checks but fails the
                    # worker's verify gate). Waiting would wedge the
                    # roll forever at N-1 capacity with every future
                    # reload blocked behind it.
                    action = ("abort", slot)
                elif slot.state == "ready" and slot.gen == self._gen:
                    # Replacement is serving: move on next poll.
                    self._roll_queue.pop(0)
                elif slot.state == "ready":
                    # Old-generation worker: drain it (ONE at a time —
                    # every other worker keeps accepting, so in-flight
                    # requests are never dropped by the roll).
                    slot.state = "draining"
                    slot.drain_deadline = now + self.drain_grace
                    action = ("drain", slot, slot.proc)
                # else starting/draining/restarting: wait for the slot
        if action == "done":
            with self._lock:
                self._rolling_back = False
                backup, self._roll_backup = self._roll_backup, None
                shm_old, self._shm_backup = self._shm_backup, None
            if shm_old is not None and shm_old is not self._shm:
                # Every worker is on the new generation now — nothing
                # can still be attached to the pre-roll segment.
                self._unlink_shm(shm_old)
            if backup is not None and backup[1] is not self.readers:
                # A manifest roll replaced the fleet config: the
                # pre-roll readers are dead weight now — close them
                # instead of leaving multi-GB mmaps to the GC's
                # schedule. (A plain rolling RESTART keeps the same
                # reader dict; the identity check protects it.)
                _close_readers(backup[1])
            self.registry.counter(
                "gamesman_serve_reloads_total",
                "rolling reload/restart cycles completed",
            ).inc()
            self._log({"phase": "serve_reload_done"})
        elif action is not None and action[0] == "abort":
            self._abort_roll(action[1], now)
        elif action is not None and action[0] == "drain":
            _, slot, proc = action
            if proc is not None:
                proc.kill(signal.SIGTERM)
            self._log({"phase": "serve_worker_drain_begin",
                       "worker": slot.idx})

    def _abort_roll(self, slot, now: float) -> None:
        """A roll whose replacement worker cannot warm-start is aborted,
        not waited out: revert to the pre-roll config and roll the fleet
        BACK, so a rotted new DB costs one slot's restart churn instead
        of wedging the fleet at N-1 with every future reload blocked."""
        dropped = None
        with self._lock:
            if self._rolling_back:
                # The rollback itself hit a broken replacement: the old
                # config is rotting too. Stop rolling; the breaker's
                # cool-off keeps probing the slot on its own.
                self._roll_queue = None
                self._rolling_back = False
                self._last_reload_error = (
                    f"reload rollback also failed on worker {slot.idx}; "
                    "roll stopped"
                )
            else:
                if self._roll_backup is not None:
                    if self._roll_backup[1] is not self.readers:
                        dropped = self.readers  # the failed new config's
                    self.entries, self.readers = self._roll_backup
                if self._shm_backup is not None:
                    # Rolling back to the old config: the old segment
                    # (still warm with the old epoch's blocks) becomes
                    # current again; the failed config's segment dies.
                    dropped_shm = self._shm
                    self._shm, self._shm_backup = self._shm_backup, None
                    if dropped_shm is not None \
                            and dropped_shm is not self._shm:
                        self._unlink_shm(dropped_shm)
                self._gen += 1
                self._roll_queue = [s.idx for s in self._slots]
                self._rolling_back = True
                self._last_reload_error = (
                    f"reload aborted: worker {slot.idx} failed warm "
                    "start on the new config; rolling back"
                )
                # The crash-loop evidence belongs to the FAILED config;
                # probe the reverted one immediately, not after the
                # breaker's full cool-off.
                slot.next_spawn_at = now
            err = self._last_reload_error
        if dropped is not None:
            _close_readers(dropped)
        self._log({"phase": "serve_reload_aborted", "error": err})

    # -------------------------------------------------------------- shutdown

    def _shutdown(self) -> None:
        with self._lock:
            self._stopping = True
            live = [s for s in self._slots if s.proc is not None]
            for s in live:
                if s.state not in ("draining",):
                    s.state = "draining"
        for s in live:
            s.proc.kill(signal.SIGTERM)
        deadline = time.monotonic() + self.drain_grace
        while time.monotonic() < deadline:
            with self._lock:
                if all(s.proc is None for s in self._slots):
                    break
            for key, _ in self._sel.select(0.1):
                if key.fd != self._wake_r:
                    self._drain_pipe(key.fd)
                else:
                    try:
                        os.read(self._wake_r, 4096)
                    except OSError:
                        pass
        with self._lock:
            stragglers = [s for s in self._slots if s.proc is not None]
        for s in stragglers:
            s.proc.kill(signal.SIGKILL)
            self._reap(s)
            with self._lock:
                s.proc = None
                s.state = "stopped"
        if self._control is not None:
            self._control.shutdown()
            self._control.server_close()
        try:
            self._sel.close()
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass
        for fd in (self._wake_r, self._wake_w):
            try:
                os.close(fd)
            except OSError:
                pass
        self._sock.close()
        _close_readers(self.readers)
        with self._lock:
            backup, self._roll_backup = self._roll_backup, None
            shm, self._shm = self._shm, None
            shm_backup, self._shm_backup = self._shm_backup, None
        self._unlink_shm(shm)
        if shm_backup is not None and shm_backup is not shm:
            self._unlink_shm(shm_backup)  # stop() arrived mid-roll
        if backup is not None and backup[1] is not self.readers:
            _close_readers(backup[1])  # stop() arrived mid-roll
        self._log({"phase": "serve_supervisor_stopped"})


def _close_readers(readers: dict) -> None:
    """Best-effort close of a reader dict (teardown / replaced config)."""
    for reader in readers.values():
        try:
            reader.close()
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass


def _worker_path(path: str, worker: int) -> str:
    """``serve.jsonl`` -> ``serve.worker0.jsonl``: the per-worker JSONL
    naming twin of the CLI's per-rank ``_rank_path``."""
    root, ext = os.path.splitext(path)
    return f"{root}.worker{worker}{ext}"


class _ControlHandler(BaseHTTPRequestHandler):
    server_version = "gamesman-supervisor/1"
    protocol_version = "HTTP/1.1"
    timeout = 30

    def log_message(self, fmt, *args):
        pass

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        try:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            self.close_connection = True

    def _send_json(self, code: int, payload: dict) -> None:
        self._send(code, json.dumps(payload).encode(), "application/json")

    def do_GET(self):  # noqa: N802 - http.server API
        sup = self.server.supervisor
        if self.path == "/healthz":
            self._send_json(200, sup.status())
        elif self.path == "/status":
            # Status parity with the solve stack's GAMESMAN_STATUS_PORT
            # endpoint (docs/OBSERVABILITY.md "Live status"): one URL
            # shape whether the process is a solver, a campaign, or
            # this serving fleet's supervisor.
            self._send_json(200, {"kind": "serve_fleet", **sup.status()})
        elif self.path == "/metrics":
            self._send(
                200, sup.registry.render_prometheus().encode(),
                PROMETHEUS_CONTENT_TYPE,
            )
        elif self.path == "/traces":
            self._send_json(200, sup.traces())
        else:
            self._send_json(404, {"error": f"no such path {self.path!r}"})

    def do_POST(self):  # noqa: N802 - http.server API
        sup = self.server.supervisor
        # Only /registry-sync reads a (bounded) body; every other POST
        # ignores it — so always drop the connection, and stray bytes
        # can't desync a keep-alive socket.
        self.close_connection = True
        if self.path == "/reload":
            sup.request_reload()
            self._send_json(202, {"ok": True, "status": "reload requested"})
        elif self.path == "/registry-sync":
            try:
                n = int(self.headers.get("Content-Length") or 0)
                if not 0 < n <= 1 << 20:
                    raise ValueError(f"bad Content-Length {n}")
                info = json.loads(self.rfile.read(n))
                if not isinstance(info, dict):
                    raise ValueError("body must be a JSON object")
            except (ValueError, OSError) as e:
                self._send_json(400, {"error": f"bad sync report: {e}"})
                return
            sup.note_registry_sync(info)
            self._send_json(200, {"ok": True})
        else:
            self._send_json(404, {"error": f"no such path {self.path!r}"})


class _ControlServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, addr, supervisor):
        super().__init__(addr, _ControlHandler)
        self.supervisor = supervisor
