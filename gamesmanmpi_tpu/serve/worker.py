"""Serving-fleet worker: one supervised process answering queries.

The body a `serve/supervisor.py` slot runs — forked from the supervisor
when the parent has never touched a jax backend (the CLI path: mmap
page cache and the imported interpreter come for free), or re-exec'd as
``python -m gamesmanmpi_tpu.serve.worker <config-json>`` when fork
would inherit a live backend (XLA's thread pools do not survive fork;
the supervisor picks the spawn mode, see ``ServeSupervisor._use_fork``).

Lifecycle (every transition reported on the heartbeat pipe as one JSON
line, which is the supervisor's only view of the worker):

1. ``hello`` — process is up; per-worker chaos re-armed from
   ``GAMESMAN_FAULTS_WORKER_<id>`` (the serving twin of the launcher's
   ``GAMESMAN_FAULTS_RANK_<i>``), then the ``serve.worker_spawn``
   fault point fires.
2. warm start — every routed DB passes the
   ``db.check.verify_for_serving`` gate (full check_db: checksums,
   sortedness, decided-ness; ``GAMESMAN_SERVE_VERIFY=0`` skips), then a
   ``QueryServer`` opens over the inherited listening socket and
   answers a self-probe (one real lookup per game — compiles the query
   kernels off the serving path). Warm start BEATS (``status:
   "starting"``) the whole way: re-hashing a multi-GB DB can take
   minutes and must not trip the supervisor's silence deadline; a
   wedged warm start is caught by the worker's own
   ``GAMESMAN_SERVE_WARMSTART_SECS`` deadline instead.
3. ``ready`` — the worker joins the ready set; only now does the
   supervisor count it toward fleet health.
4. ``beat`` every ``GAMESMAN_SERVE_HEARTBEAT_SECS`` carrying the
   worker's own health status; a stopped pipe (crash) or stalled beat
   (hang — the ``serve.heartbeat`` fault point injects one) is what the
   supervisor's liveness deadline catches.
5. SIGTERM -> ``draining``: stop accepting, flush in-flight batches,
   ``bye``, exit 0. Any other death is a crash the supervisor restarts
   with backoff.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import sys
import threading
import time

from gamesmanmpi_tpu.utils.env import env_float, env_opt

#: Worker exit codes the supervisor distinguishes: a warm-start refusal
#: (bad DB, failed self-probe) is a *config/storage* problem that will
#: recur on restart, so the supervisor's storm breaker sees it quickly.
EXIT_WARMSTART_FAILED = 3
EXIT_CRASH = 70


class _Pipe:
    """Line-oriented JSON writer over the supervisor's heartbeat pipe.

    A broken pipe means the supervisor is gone — the worker records it
    and the caller drains: an unsupervised fleet worker must not linger
    as an orphan accept()ing on a socket nobody owns.
    """

    def __init__(self, fd: int):
        self.fd = fd
        self.broken = False

    def send(self, **msg) -> bool:
        if self.broken:
            return False
        try:
            os.write(self.fd, (json.dumps(msg) + "\n").encode())
            return True
        except (BrokenPipeError, OSError):
            self.broken = True
            return False


def _build_server(cfg: dict, listen_sock, registry):
    from gamesmanmpi_tpu.db import DbReader
    from gamesmanmpi_tpu.serve.server import QueryServer

    # The supervisor-owned cross-worker decoded-block segment: attach
    # by name (works identically for fork and exec spawns — nothing fd
    # shaped to inherit). Attach failure degrades to the private cache:
    # a missing/raced segment must never refuse a warm start.
    shm = None
    if cfg.get("shm_segment"):
        from gamesmanmpi_tpu.store import ShmBlockCache

        try:
            shm = ShmBlockCache.attach(cfg["shm_segment"],
                                       registry=registry)
        except (FileNotFoundError, ValueError, OSError) as e:
            sys.stderr.write(
                f"[worker {cfg['worker_id']}] shm attach failed "
                f"({type(e).__name__}: {e}); using private cache only\n"
            )
    readers = {
        name: DbReader(db, registry=registry, shm=shm)
        for name, db in cfg["entries"]
    }
    return QueryServer(
        readers=readers,
        listen_sock=listen_sock,
        worker_id=int(cfg["worker_id"]),
        window=float(cfg.get("window", 0.002)),
        cache_size=int(cfg.get("cache_size", 65536)),
        max_queue=int(cfg.get("max_queue", 1024)),
        request_timeout=cfg.get("request_timeout"),
        logger=_build_logger(cfg),
        registry=registry,
    )


def _build_logger(cfg: dict):
    """Worker-stamped JSONL stream (``serve.worker0.jsonl`` — the
    supervisor already qualified the path): tools/obs_report.py merges
    the per-worker streams the way it merges per-rank solve streams."""
    if not cfg.get("jsonl"):
        return None
    from gamesmanmpi_tpu.utils.metrics import JsonlLogger, TagLogger

    return TagLogger(JsonlLogger(cfg["jsonl"]), worker=int(cfg["worker_id"]))


def _start_orphan_watch(wid: int) -> None:
    """Exit hard if this worker is ever reparented (supervisor died).

    The beat loop notices a dead supervisor through EPIPE on its next
    write — but WARM START writes nothing, so a worker wedged there
    (fork-from-a-threaded-parent is inherently racy: an inherited lock
    can deadlock the first kernel compile) would outlive a SIGKILLed
    supervisor forever, accept()ing on a socket nobody owns. Observed
    exactly once under the heartbeat chaos test before this watch.
    os._exit, not sys.exit: the wedge we are escaping could just as
    well hang a clean teardown.
    """
    ppid0 = os.getppid()

    def watch():
        while True:
            time.sleep(1.0)
            if os.getppid() != ppid0:
                sys.stderr.write(
                    f"[worker {wid}] supervisor died (reparented); "
                    "exiting\n"
                )
                os._exit(EXIT_CRASH)

    threading.Thread(
        target=watch, name="gamesman-orphan-watch", daemon=True
    ).start()


# wire: producer
def run_worker(cfg: dict, listen_sock, pipe_fd: int) -> int:
    """The worker body; returns the process exit code, never raises.
    Every ``pipe.send(...)`` keyword and beat-dict key here crosses the
    supervisor pipe as JSON, hence the producer annotation."""
    from gamesmanmpi_tpu.obs import MetricsRegistry
    from gamesmanmpi_tpu.resilience import faults

    wid = int(cfg["worker_id"])
    pipe = _Pipe(pipe_fd)
    drain = threading.Event()
    _start_orphan_watch(wid)

    def _on_term(signum, frame):
        drain.set()

    # Fork inherits the supervisor's handlers (which would re-enter the
    # SUPERVISOR's drain logic in this process) — install the worker's
    # own before anything can deliver a signal.
    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)

    # Per-worker chaos: a fleet-wide GAMESMAN_FAULTS arms every worker
    # identically, which is almost never what a worker-death scenario
    # wants — GAMESMAN_FAULTS_WORKER_<id> re-arms just this slot (every
    # respawn of the slot re-arms, so a spawn-death directive makes a
    # deterministic crash-looper for the storm-breaker tests).
    spec = env_opt(f"GAMESMAN_FAULTS_WORKER_{wid}")
    if spec is not None:
        faults.configure(spec)

    pipe.send(type="hello", pid=os.getpid())
    t_spawn = time.monotonic()
    beat_secs = max(0.05, float(cfg.get("heartbeat_secs", 1.0)))

    # Warm start must BEAT, not go silent: verifying a multi-GB DB can
    # legitimately take minutes, and the supervisor's liveness deadline
    # must not confuse that with a hang. Silence stays the hang signal;
    # a wedged warm start that still beats (a deadlocked compile thread
    # leaves the GIL free) is caught by the worker's own deadline.
    warm_deadline = env_float("GAMESMAN_SERVE_WARMSTART_SECS", 300.0)
    ready_evt = threading.Event()

    def _warm_beat():
        while not ready_evt.wait(beat_secs):
            if time.monotonic() - t_spawn > warm_deadline:
                pipe.send(type="failed",
                          error=f"warm start exceeded {warm_deadline:g}s")
                os._exit(EXIT_WARMSTART_FAILED)
            if not pipe.send(type="beat", status="starting"):
                os._exit(EXIT_CRASH)  # supervisor gone mid-warm-start

    threading.Thread(
        target=_warm_beat, name="gamesman-warm-beat", daemon=True
    ).start()
    server = None
    try:
        faults.fire("serve.worker_spawn", worker=wid)
        # A fresh registry (not the inherited process singleton): this
        # worker's /metrics must carry ITS serving series only, each
        # labeled worker=<id> — the per-rank labeling convention of
        # docs/OBSERVABILITY.md applied to the fleet.
        registry = MetricsRegistry()
        registry.set_constant_labels(worker=str(wid))
        from gamesmanmpi_tpu.db.check import verify_for_serving

        verified = {}
        for name, db in cfg["entries"]:
            verified[name or "default"] = verify_for_serving(db)
        server = _build_server(cfg, listen_sock, registry)
        server.start()
        server.self_probe()
        warmup = time.monotonic() - t_spawn
        registry.gauge(
            "gamesman_serve_warmup_seconds",
            "spawn-to-ready wall seconds of this worker "
            "(verify gate + open + self-probe + kernel compiles)",
        ).set(warmup)
        pipe.send(
            type="ready", pid=os.getpid(), verified=verified,
            warmup_secs=round(warmup, 3),
            games=sorted(n or "default" for n, _ in cfg["entries"]),
        )
    except Exception as e:  # noqa: BLE001 - report, then die visibly
        pipe.send(type="failed", error=f"{type(e).__name__}: {e}"[:500])
        if server is not None:
            try:
                server.stop()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        return EXIT_WARMSTART_FAILED
    finally:
        ready_evt.set()  # warm-start beats end; the ready loop's begin

    code = 0
    try:
        while not drain.wait(beat_secs):
            # The heartbeat IS the liveness signal: an injected delay
            # here (serve.heartbeat:delay=...) stalls the beats and the
            # supervisor's deadline turns the silent hang into a
            # SIGKILL + restart — exactly what a wedged worker gets.
            faults.fire("serve.heartbeat", worker=wid)
            beat = {
                "type": "beat",
                "status": server.healthz()["status"],
                "inflight": server.inflight,
                # The burn-rate snapshot rides every beat so the control
                # port's /status can show WHY a worker is degraded (which
                # route/objective is past fast-burn), not just that it is.
                "slo": server.slo.snapshot(),
            }
            # Newly tail-sampled traces ride the beat (bounded batch):
            # the workers share one accept queue, so the supervisor
            # cannot HTTP-address THIS worker's /traces — the heartbeat
            # pipe is the only per-worker channel, and it aggregates the
            # fleet ring the control port serves.
            sampled = server.trace_ring.drain_outbox(8)
            if sampled:
                beat["traces"] = sampled
            if not pipe.send(**beat):
                drain.set()  # supervisor gone: drain and exit
    except Exception as e:  # noqa: BLE001 - a faulted beat is a crash
        pipe.send(type="failed", error=f"{type(e).__name__}: {e}"[:500])
        code = EXIT_CRASH
    pipe.send(type="draining")
    try:
        server.stop()
    except Exception:  # noqa: BLE001 - teardown best-effort
        code = code or EXIT_CRASH
    pipe.send(type="bye", code=code)
    return code


def main(argv=None) -> int:
    """Exec-spawn entry: ``python -m gamesmanmpi_tpu.serve.worker
    '<config json>'`` with the listening socket and pipe inherited as
    the fd numbers named in the config."""
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: python -m gamesmanmpi_tpu.serve.worker CONFIG_JSON",
              file=sys.stderr)
        return 2
    cfg = json.loads(argv[0])
    from gamesmanmpi_tpu.utils.platform import apply_platform_env

    # Same platform policy as `cli serve`: the query kernels are
    # host-side by design; honor GAMESMAN_PLATFORM before backend init.
    apply_platform_env(default_fake_devices=1)
    listen_sock = socket.socket(fileno=int(cfg["listen_fd"]))
    return run_worker(cfg, listen_sock, int(cfg["pipe_fd"]))


if __name__ == "__main__":
    sys.exit(main())
