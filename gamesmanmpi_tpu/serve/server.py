"""Stdlib HTTP front-end over a solved-position database.

A `ThreadingHTTPServer` (one thread per connection — the stdlib answer,
no framework dependency, matching the repo's plain-npz/no-deps stance)
exposing:

    POST /query         {"positions": ["0x1b", 42, ...]} ->
                        per-position value / remoteness / best child
    GET  /healthz       liveness + DB identity
    GET  /metrics       Prometheus text exposition v0.0.4 (the process
                        metrics registry: request/batch/cache/db series);
                        answers JSON instead when the Accept header
                        prefers application/json
    GET  /metrics.json  the legacy JSON counter dict, retained verbatim
                        for existing consumers

Every request thread funnels through one serve/batcher.Batcher, so
concurrent requests coalesce into single vectorized DbReader probes; the
HTTP layer only parses, delegates, and formats. Positions echo back in
hex (the CLI's --query spelling) so responses are copy-pasteable into
`cli query` / `--query` for cross-checking.
"""

from __future__ import annotations

import json
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from gamesmanmpi_tpu.core.values import value_name
from gamesmanmpi_tpu.db.format import parse_position
from gamesmanmpi_tpu.obs import default_registry
from gamesmanmpi_tpu.serve.batcher import Batcher, BatcherUnavailable

#: Socket errors a disconnecting client inflicts on the handler's write
#: path. Counted (http_client_aborts), never a thread traceback: a
#: hung-up client is load, not a server bug.
CLIENT_ABORT_ERRORS = (BrokenPipeError, ConnectionResetError)

#: The exposition format version the /metrics endpoint speaks.
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# Refuse absurd request bodies before json.loads allocates for them.
_MAX_BODY_BYTES = 16 << 20
_MAX_POSITIONS_PER_REQUEST = 1 << 16


class _Handler(BaseHTTPRequestHandler):
    server_version = "gamesman-serve/1"
    protocol_version = "HTTP/1.1"
    # Socket timeout for blocking reads: a client that promises
    # Content-Length N and sends fewer bytes must not pin a handler
    # thread forever (slowloris); on timeout the connection is reaped.
    timeout = 30

    # self.server is the _QueryHTTPServer below.

    def _send_json(self, code: int, payload: dict, headers=None) -> int:
        return self._send_text(
            code, json.dumps(payload), "application/json", headers
        )

    def _send_text(self, code: int, text: str, content_type: str,
                   headers=None) -> int:
        body = text.encode()
        try:
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            if self.close_connection:
                # HTTP/1.1 defaults to keep-alive: a client must be TOLD the
                # connection is closing, or its next request hits a dead
                # socket (the early-400 path closes without draining).
                self.send_header("Connection", "close")
            self.end_headers()
            self.wfile.write(body)
        except CLIENT_ABORT_ERRORS:
            # The client hung up mid-response: count it and reap the
            # connection — the old behavior was a handler-thread
            # traceback per disconnect.
            self.server.note_client_abort()
            self.close_connection = True
        return code

    def log_message(self, fmt, *args):  # quiet by default; JSONL has it
        pass

    def _wants_json(self) -> bool:
        """Content negotiation for /metrics: Prometheus scrapers send no
        Accept (or */*) and get the text exposition; a client that asks
        for application/json gets the legacy JSON dict. The full q-value
        dance is not worth stdlib-reimplementing — naming application/
        json anywhere in Accept is the opt-in."""
        accept = self.headers.get("Accept", "")
        return "application/json" in accept.lower()

    def do_GET(self):  # noqa: N802 - http.server API
        srv = self.server
        if self.path == "/healthz":
            # Three states, one field: "ok" (serving normally),
            # "degraded" (reader circuit breaker open — misses answer
            # 503, cache hits still serve), "draining" (shutdown in
            # progress; stop routing here). Always 200: a load balancer
            # reads the body, an operator reads it too.
            self._send_json(
                200,
                {
                    "status": srv.health_status(),
                    "breaker": srv.batcher.state
                    if srv.batcher is not None else "ok",
                    "game": srv.reader.game.name,
                    "spec": srv.reader.manifest["spec"],
                    "positions": srv.reader.num_positions,
                    "levels": len(srv.reader.levels),
                },
            )
        elif self.path == "/metrics":
            if self._wants_json():
                self._send_json(200, srv.metrics())
            else:
                self._send_text(
                    200,
                    srv.registry.render_prometheus(),
                    PROMETHEUS_CONTENT_TYPE,
                )
        elif self.path == "/metrics.json":
            self._send_json(200, srv.metrics())
        else:
            self._send_json(404, {"error": f"no such path {self.path!r}"})

    def do_POST(self):  # noqa: N802 - http.server API
        # Every POST counts in /metrics, rejects included — an operator
        # watching the counters must see a server busy answering 400s as
        # busy, and http_errors makes the reject rate derivable.
        t0 = time.perf_counter()
        code = 500
        self.server.note_inflight(+1)
        try:
            code = self._handle_post()
        finally:
            self.server.note_inflight(-1)
            self.server.note_request(time.perf_counter() - t0, code)

    def _handle_post(self) -> int:
        srv = self.server
        if srv.draining:
            # Graceful shutdown: finish what is in flight, refuse new
            # work loudly so clients fail over instead of timing out.
            self.close_connection = True
            return self._send_json(
                503, {"error": "server is draining"},
                headers={"Retry-After": "1"},
            )
        if self.path != "/query":
            # The body (if any) is never read on this branch; its bytes
            # would desync the keep-alive socket (same guard as below).
            self.close_connection = True
            return self._send_json(
                404, {"error": f"no such path {self.path!r}"}
            )
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            length = -1
        if self.headers.get("Transfer-Encoding"):
            # Chunked bodies are not read; their bytes would desync the
            # keep-alive socket exactly like an undrained oversize body.
            length = -1
        if not 0 <= length <= _MAX_BODY_BYTES:
            # Refusing without reading the body leaves its bytes on the
            # keep-alive socket, where they would parse as the next
            # request line — drop the connection instead.
            self.close_connection = True
            return self._send_json(400, {"error": "bad Content-Length"})
        try:
            payload = json.loads(self.rfile.read(length) or b"{}")
            positions = payload["positions"]
            if not isinstance(positions, list):
                raise TypeError
        except (ValueError, KeyError, TypeError):
            # ValueError covers JSONDecodeError AND CPython's int-digit
            # limit on absurd JSON number literals — either way a 400,
            # never a handler traceback.
            return self._send_json(
                400,
                {"error": 'body must be {"positions": [int|"0x..", ...]}'},
            )
        if len(positions) > _MAX_POSITIONS_PER_REQUEST:
            return self._send_json(
                400,
                {"error": f"at most {_MAX_POSITIONS_PER_REQUEST} positions "
                          "per request"},
            )
        parsed: list = []  # (echo, packed int) or (echo, error string)
        for p in positions:
            try:
                parsed.append((p, parse_position(srv.reader.game, p)))
            except (ValueError, TypeError) as e:
                parsed.append((p, f"invalid position ({e})"))
        states = [s for _, s in parsed if isinstance(s, int)]
        try:
            answers = iter(srv.batcher.submit(states))
        except BatcherUnavailable as e:
            # Genuinely transient (shutdown, deadline, shed, breaker):
            # 503 + Retry-After so a well-behaved client backs off
            # instead of hammering a recovering server.
            return self._send_json(
                503, {"error": str(e)},
                headers={"Retry-After": str(e.retry_after)},
            )
        except Exception as e:  # noqa: BLE001 - reader faults re-raise in
            # submit (a truncated shard, an unreadable mmap): answer 500
            # rather than dropping the connection mid-response.
            return self._send_json(500, {"error": f"lookup failed: {e}"})
        results = []
        for echo, s in parsed:
            if not isinstance(s, int):
                results.append({"position": echo, "error": s})
                continue
            value, rem, found, best = next(answers)
            rec = {"position": hex(s), "found": found}
            if found:
                rec["value"] = value_name(value)
                rec["remoteness"] = rem
                rec["best"] = None if best is None else hex(best)
            results.append(rec)
        return self._send_json(
            200, {"game": srv.reader.game.name, "results": results}
        )


class _QueryHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    # The stdlib default accept backlog is 5; a barrier burst of clients
    # (exactly the traffic the micro-batcher coalesces) overflows it and
    # the overflow sees ECONNRESET. Observed under 8 synchronized clients.
    request_queue_size = 128

    def __init__(self, addr, reader, registry=None):
        super().__init__(addr, _Handler)
        self.reader = reader
        self.batcher = None  # attached by QueryServer AFTER the bind
        self.registry = registry or default_registry()
        #: flipped by QueryServer.begin_drain(): /healthz says so and new
        #: POST /query work answers 503 while in-flight requests finish.
        self.draining = False
        self._stats_lock = threading.Lock()
        self._t0 = time.time()
        self._http_requests = 0  # guarded-by: _stats_lock
        self._http_errors = 0  # guarded-by: _stats_lock
        self._http_client_aborts = 0  # guarded-by: _stats_lock
        # POSTs between entry and response written
        self._inflight = 0  # guarded-by: _stats_lock
        self._latency_total = 0.0  # guarded-by: _stats_lock
        self._latency_max = 0.0  # guarded-by: _stats_lock
        # server_start_time makes uptime derivable from any scrape
        # (time() - server_start_time), the Prometheus convention.
        self.registry.gauge(
            "gamesman_server_start_time_seconds",
            "unix time the query server bound its port",
        ).set(self._t0)
        self._m_requests = self.registry.counter(
            "gamesman_http_requests_total", "POST requests, rejects included"
        )
        self._m_errors = self.registry.counter(
            "gamesman_http_errors_total", "POST requests answered >= 400"
        )
        self._m_latency = self.registry.histogram(
            "gamesman_http_request_seconds",
            "wall seconds per POST request, parse to response",
        )
        self._m_client_aborts = self.registry.counter(
            "gamesman_http_client_aborts_total",
            "responses abandoned by a disconnecting client "
            "(BrokenPipe/ConnectionReset on the write path)",
        )

    def health_status(self) -> str:
        if self.draining:
            return "draining"
        if self.batcher is not None and self.batcher.state != "ok":
            return "degraded"
        return "ok"

    def note_client_abort(self) -> None:
        with self._stats_lock:
            self._http_client_aborts += 1
        self._m_client_aborts.inc()

    def note_inflight(self, delta: int) -> None:
        with self._stats_lock:
            self._inflight += delta

    @property
    def inflight(self) -> int:
        with self._stats_lock:
            return self._inflight

    def handle_error(self, request, client_address):
        """Client aborts escaping outside _send_text (e.g. during the
        request read) are counted, not dumped as thread tracebacks;
        everything else keeps the stdlib report."""
        exc = sys.exc_info()[1]
        if isinstance(exc, CLIENT_ABORT_ERRORS):
            self.note_client_abort()
            return
        super().handle_error(request, client_address)

    def note_request(self, secs: float, code: int) -> None:
        with self._stats_lock:
            self._http_requests += 1
            if code >= 400:
                self._http_errors += 1
            self._latency_total += secs
            self._latency_max = max(self._latency_max, secs)
        self._m_requests.inc()
        if code >= 400:
            self._m_errors.inc()
        self._m_latency.observe(secs)

    def metrics(self) -> dict:
        with self._stats_lock:
            n = self._http_requests
            errors = self._http_errors
            aborts = self._http_client_aborts
            mean = self._latency_total / max(n, 1)
            peak = self._latency_max
            uptime = time.time() - self._t0
        return {
            "server_start_time": self._t0,
            "uptime_secs": uptime,
            "status": self.health_status(),
            "http_requests": n,
            "http_errors": errors,
            "http_client_aborts": aborts,
            "latency_mean_ms": mean * 1e3,
            "latency_max_ms": peak * 1e3,
            **self.batcher.metrics(),
        }


class QueryServer:
    """Owns the HTTP server + batcher lifecycle.

    port=0 binds an ephemeral port (tests); `.port` reports the bound one.
    Use `.start()` for a background thread (in-process tests) or
    `.serve_forever()` to block (the CLI `serve` subcommand).
    """

    def __init__(self, reader, *, host: str = "127.0.0.1", port: int = 0,
                 window: float = 0.002, cache_size: int = 65536,
                 max_queue: int = 1024, request_timeout: float | None = None,
                 breaker_threshold: int = 3, breaker_cooldown: float = 5.0,
                 logger=None, registry=None):
        self.reader = reader
        self.logger = logger
        self.registry = registry or default_registry()
        # Bind FIRST: a bind failure (port in use) must raise before the
        # batcher spawns its worker thread, or every failed construction
        # would leak an unjoinable daemon thread.
        self._httpd = _QueryHTTPServer((host, port), reader, self.registry)
        self.batcher = Batcher(
            reader, window=window, cache_size=cache_size,
            max_queue=max_queue, request_timeout=request_timeout,
            breaker_threshold=breaker_threshold,
            breaker_cooldown=breaker_cooldown,
            logger=logger, registry=self.registry,
        )
        self._httpd.batcher = self.batcher
        self._thread: threading.Thread | None = None

    @property
    def host(self) -> str:
        return self._httpd.server_address[0]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="gamesman-serve",
            daemon=True,
        )
        self._thread.start()

    def serve_forever(self) -> None:
        self._httpd.serve_forever()

    def metrics(self) -> dict:
        return self._httpd.metrics()

    def begin_drain(self) -> None:
        """Flip /healthz to "draining" and 503 new queries while
        in-flight requests finish — the first half of a SIGTERM
        shutdown; stop() completes it."""
        self._httpd.draining = True

    def stop(self) -> None:
        self.begin_drain()
        self._httpd.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        # Requests already coalescing get one final flush (drain=True):
        # they arrived before the drain flip and deserve an answer.
        self.batcher.close(drain=True)
        # Handler threads are daemons ThreadingHTTPServer never joins: a
        # process exit right after this call would kill them mid-write,
        # truncating the very responses the drain flushed. Bounded wait
        # for the in-flight POSTs to finish writing (their batch answers
        # arrived in the close(drain=True) above, so this is socket-write
        # time — milliseconds; the deadline only guards a hung client).
        deadline = time.monotonic() + 5.0
        while self._httpd.inflight > 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        self._httpd.server_close()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
