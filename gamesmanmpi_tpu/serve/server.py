"""Stdlib HTTP front-end over one or many solved-position databases.

A `ThreadingHTTPServer` (one thread per connection — the stdlib answer,
no framework dependency, matching the repo's plain-npz/no-deps stance)
exposing:

    POST /query         {"positions": ["0x1b", 42, ...]} ->
                        per-position value / remoteness / best child
                        (the default route: a single-DB server, or a
                        fleet whose manifest has exactly one game)
    POST /query/<name>  the same against the fleet-manifest game <name>
    GET  /healthz       liveness + DB identity (+ per-game state when
                        the server routes a fleet)
    GET  /metrics       Prometheus text exposition v0.0.4 (the process
                        metrics registry: request/batch/cache/db series);
                        answers JSON instead when the Accept header
                        prefers application/json
    GET  /metrics.json  the legacy JSON counter dict, retained verbatim
                        for existing consumers

Every request thread funnels through one serve/batcher.Batcher per
routed game, so concurrent requests coalesce into single vectorized
DbReader probes; the HTTP layer only parses, delegates, and formats.
Positions echo back in hex (the CLI's --query spelling) so responses are
copy-pasteable into `cli query` / `--query` for cross-checking.

Fleet mode (docs/SERVING.md "Fleet serving"): a supervisor process binds
the listening socket ONCE and hands it to N forked workers
(`serve/supervisor.py`), each of which constructs a QueryServer over the
inherited socket (``listen_sock=``) — the kernel load-balances accepts
across the workers, and a worker that stops accepting (drain) simply
leaves the shared queue to its siblings. The per-worker breaker /
deadline / shed machinery is exactly the single-process one.
"""

from __future__ import annotations

import json
import socket
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit

import numpy as np

from gamesmanmpi_tpu.core.values import value_name
from gamesmanmpi_tpu.db.format import parse_position
from gamesmanmpi_tpu.obs import default_registry
from gamesmanmpi_tpu.obs.qtrace import (
    QueryTrace,
    TraceRing,
    activate,
    format_traceparent,
    qspan,
)
from gamesmanmpi_tpu.utils.env import env_int
from gamesmanmpi_tpu.obs.slo import SloEngine
from gamesmanmpi_tpu.serve.batcher import (
    Batcher,
    BatcherTripped,
    BatcherUnavailable,
)

#: Socket errors a disconnecting client inflicts on the handler's write
#: path. Counted (http_client_aborts), never a thread traceback: a
#: hung-up client is load, not a server bug.
CLIENT_ABORT_ERRORS = (BrokenPipeError, ConnectionResetError)

#: The exposition format version the /metrics endpoint speaks.
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

#: Served from /metrics only when the client's Accept names it — carries
#: histogram exemplars (trace ids of slow observations) + "# EOF".
OPENMETRICS_CONTENT_TYPE = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8"
)

# Refuse absurd request bodies before json.loads allocates for them.
_MAX_BODY_BYTES = 16 << 20
_MAX_POSITIONS_PER_REQUEST = 1 << 16

#: Accept backlog for the listening socket (also used by the supervisor
#: when it pre-binds): the stdlib default of 5 overflows under a barrier
#: burst of clients — observed as ECONNRESET under 8 synchronized
#: clients — and during a rolling restart the backlog is what holds
#: arriving connections while a replacement worker warms up.
LISTEN_BACKLOG = 128


class _Route:
    """One routed game: its reader and the batcher in front of it."""

    __slots__ = ("name", "reader", "batcher")

    def __init__(self, name: str, reader):
        self.name = name
        self.reader = reader
        self.batcher = None  # attached by QueryServer AFTER the bind


# wire: etag-cache-control, 503-retry-after, echo-traceparent
class _Handler(BaseHTTPRequestHandler):
    server_version = "gamesman-serve/1"
    protocol_version = "HTTP/1.1"
    # Socket timeout for blocking reads: a client that promises
    # Content-Length N and sends fewer bytes must not pin a handler
    # thread forever (slowloris); on timeout the connection is reaped.
    timeout = 30

    # self.server is the _QueryHTTPServer below.

    def setup(self):
        super().setup()
        # Register the connection so a drain can wake handler threads
        # parked in recv on idle keep-alive sockets (QueryServer.stop).
        self.server.conn_opened(self.connection)

    def finish(self):
        try:
            super().finish()
        finally:
            self.server.conn_closed(self.connection)

    def _send_json(self, code: int, payload: dict, headers=None) -> int:
        return self._send_text(
            code, json.dumps(payload), "application/json", headers
        )

    def _send_text(self, code: int, text: str, content_type: str,
                   headers=None) -> int:
        body = text.encode()
        try:
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            trace = getattr(self, "_qtrace", None)
            if trace is not None:
                # Echo the (possibly freshly minted) context so a client
                # that sent none can still join its record to the
                # server-side trace; rides a header, never the body —
                # response shapes are a compatibility surface.
                self.send_header(
                    "traceparent",
                    format_traceparent(trace.trace_id, trace.root_id),
                )
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            if self.close_connection:
                # HTTP/1.1 defaults to keep-alive: a client must be TOLD the
                # connection is closing, or its next request hits a dead
                # socket (the early-400 path closes without draining).
                self.send_header("Connection", "close")
            self.end_headers()
            self.wfile.write(body)
        except CLIENT_ABORT_ERRORS:
            # The client hung up mid-response: count it and reap the
            # connection — the old behavior was a handler-thread
            # traceback per disconnect.
            self.server.note_client_abort()
            self.close_connection = True
        return code

    def log_message(self, fmt, *args):  # quiet by default; JSONL has it
        pass

    def _wants_json(self) -> bool:
        """Content negotiation for /metrics: Prometheus scrapers send no
        Accept (or */*) and get the text exposition; a client that asks
        for application/json gets the legacy JSON dict. The full q-value
        dance is not worth stdlib-reimplementing — naming application/
        json anywhere in Accept is the opt-in."""
        accept = self.headers.get("Accept", "")
        return "application/json" in accept.lower()

    def _send_status(self, code: int, headers=None) -> int:
        """Header-only response (304: no body by definition)."""
        try:
            self.send_response(code)
            trace = getattr(self, "_qtrace", None)
            if trace is not None:
                self.send_header(
                    "traceparent",
                    format_traceparent(trace.trace_id, trace.root_id),
                )
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            if self.close_connection:
                self.send_header("Connection", "close")
            self.end_headers()
        except CLIENT_ABORT_ERRORS:
            self.server.note_client_abort()
            self.close_connection = True
        return code

    def do_GET(self):  # noqa: N802 - http.server API
        srv = self.server
        parts = urlsplit(self.path)
        if parts.path == "/query" or parts.path.startswith("/query/"):
            # The idempotent, edge-cacheable query form (ISSUE 18):
            # same trace/metrics/SLO bookkeeping as a POST — a CDN miss
            # that lands here is serving load like any other request.
            self._run_traced(lambda: self._handle_get_query(parts))
            return
        if self.path == "/healthz":
            self._send_json(200, srv.healthz())
        elif self.path == "/metrics":
            if self._wants_json():
                self._send_json(200, srv.metrics())
            elif "application/openmetrics-text" in (
                self.headers.get("Accept", "").lower()
            ):
                self._send_text(
                    200,
                    srv.registry.render_openmetrics(),
                    OPENMETRICS_CONTENT_TYPE,
                )
            else:
                self._send_text(
                    200,
                    srv.registry.render_prometheus(),
                    PROMETHEUS_CONTENT_TYPE,
                )
        elif self.path == "/metrics.json":
            self._send_json(200, srv.metrics())
        elif self.path == "/traces":
            self._send_json(200, srv.trace_ring.snapshot())
        else:
            self._send_json(404, {"error": f"no such path {self.path!r}"})

    def do_POST(self):  # noqa: N802 - http.server API
        # Every POST counts in /metrics, rejects included — an operator
        # watching the counters must see a server busy answering 400s as
        # busy, and http_errors makes the reject rate derivable.
        self._run_traced(self._handle_post)

    def _run_traced(self, handle) -> None:
        """The per-query-request bookkeeping shared by POST /query and
        GET /query: one trace per request (accept the client's
        traceparent or mint a root), inflight accounting, and the
        latency/SLO observation. The handler instance persists across
        keep-alive requests, so the attrs are (re)set per request and
        cleared in the finally (plain do_GET responses must never echo
        a stale trace)."""
        t0 = time.perf_counter()
        code = 500
        srv = self.server
        self._qtrace = (
            QueryTrace(
                traceparent=self.headers.get("traceparent"),
                worker=srv.worker_id,
            )
            if srv.trace_ring.enabled else None
        )
        self._route_name = ""
        self._shed_status = None  # "shed" | "tripped" when a 503 path
        srv.note_inflight(+1, self.connection)
        try:
            code = handle()
        finally:
            srv.note_inflight(-1, self.connection)
            secs = time.perf_counter() - t0
            trace = self._qtrace
            self._qtrace = None
            if trace is not None:
                status = (self._shed_status if self._shed_status
                          else ("error" if code >= 500 else "ok"))
                trace.route = self._route_name
                trace.finish(status=status, code=code)
                srv.trace_ring.offer(trace)
            srv.note_request(
                secs, code, route=self._route_name,
                shed=self._shed_status is not None, trace=trace,
            )

    def _resolve_route(self, path=None):
        """Route a query path: "/query" is the default route (single-DB
        servers and one-game fleets), "/query/<name>" a fleet game."""
        srv = self.server
        if path is None:
            path = self.path
        if path == "/query":
            if srv.default_route is not None:
                return srv.default_route
            return None
        if path.startswith("/query/"):
            return srv.routes.get(path[len("/query/"):])
        return None

    def _handle_get_query(self, parts) -> int:
        """GET /query[/<name>]?p=<pos>: one position, idempotent, with
        the edge-cache contract — ``ETag: "<epoch16>-<pos-hex>"`` +
        ``Cache-Control: public, max-age=...`` on every answer, and
        ``If-None-Match`` revalidation answered 304 with no lookup work
        at all. The ETag embeds the DB epoch (the manifest sha), so a
        rolling reload that swaps the DB flips every ETag at once: a
        CDN's cached body revalidates as stale and refetches — the
        response is immutable WHILE the epoch holds, never across it.
        """
        srv = self.server
        if srv.draining:
            self.close_connection = True
            self._shed_status = "shed"
            return self._send_json(
                503, {"error": "server is draining"},
                headers={"Retry-After": "1"},
            )
        route = self._resolve_route(parts.path)
        if route is not None:
            self._route_name = route.name or "default"
        if route is None:
            return self._send_json(
                404,
                {"error": f"no such path {parts.path!r}",
                 "games": sorted(n for n in srv.routes if n)},
            )
        raw = parse_qs(parts.query).get("p")
        if not raw or len(raw) != 1:
            return self._send_json(
                400, {"error": "GET /query needs exactly one "
                               "?p=<position>"},
            )
        reader = route.reader
        try:
            state = parse_position(reader.game, raw[0])
        except (ValueError, TypeError) as e:
            return self._send_json(400,
                                   {"error": f"invalid position ({e})"})
        # The validator: epoch prefix + the position in its one
        # canonical hex spelling (?p=12 and ?p=0xc revalidate the same
        # entry; distinct URLS may still cache distinct copies — the
        # body is identical, correctness never depends on the URL).
        etag = f'"{reader.epoch[:16]}-{state:x}"'
        cache_headers = {
            "ETag": etag,
            "Cache-Control": f"public, max-age={srv.query_max_age}",
        }
        inm = self.headers.get("If-None-Match", "")
        if inm.strip() == "*" or etag in inm:
            # Same epoch, same position: the client's copy is current.
            return self._send_status(304, cache_headers)
        answer = None
        with activate((self._qtrace,)):
            hit = srv.book_lookup(route, [state])
        if hit is not None and bool(hit[2][0]):
            bbest = int(hit[3][0])
            answer = (
                int(hit[0][0]), int(hit[1][0]), True,
                None if bbest == int(reader.game.sentinel) else bbest,
            )
        if answer is None:
            try:
                answer = route.batcher.submit(
                    [state], trace=self._qtrace
                )[0]
            except BatcherUnavailable as e:
                self._shed_status = (
                    "tripped" if isinstance(e, BatcherTripped) else "shed"
                )
                return self._send_json(
                    503, {"error": str(e)},
                    headers={"Retry-After": str(e.retry_after)},
                )
            except Exception as e:  # noqa: BLE001 - reader faults: 500,
                # uncached (no validator on an error body).
                return self._send_json(500,
                                       {"error": f"lookup failed: {e}"})
        value, rem, found, best = answer
        rec = {"position": hex(state), "found": bool(found)}
        if found:
            rec["value"] = value_name(value)
            rec["remoteness"] = int(rem)
            rec["best"] = None if best is None else hex(best)
        return self._send_json(
            200, {"game": reader.game.name, "results": [rec]},
            headers=cache_headers,
        )

    def _handle_post(self) -> int:
        srv = self.server
        if srv.draining:
            # Graceful shutdown: finish what is in flight, refuse new
            # work loudly so clients fail over instead of timing out.
            self.close_connection = True
            self._shed_status = "shed"
            return self._send_json(
                503, {"error": "server is draining"},
                headers={"Retry-After": "1"},
            )
        route = self._resolve_route()
        if route is not None:
            self._route_name = route.name or "default"
        if route is None:
            # The body (if any) is never read on this branch; its bytes
            # would desync the keep-alive socket (same guard as below).
            self.close_connection = True
            return self._send_json(
                404,
                {"error": f"no such path {self.path!r}",
                 "games": sorted(n for n in srv.routes if n)},
            )
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            length = -1
        if self.headers.get("Transfer-Encoding"):
            # Chunked bodies are not read; their bytes would desync the
            # keep-alive socket exactly like an undrained oversize body.
            length = -1
        if not 0 <= length <= _MAX_BODY_BYTES:
            # Refusing without reading the body leaves its bytes on the
            # keep-alive socket, where they would parse as the next
            # request line — drop the connection instead.
            self.close_connection = True
            return self._send_json(400, {"error": "bad Content-Length"})
        try:
            payload = json.loads(self.rfile.read(length) or b"{}")
            positions = payload["positions"]
            if not isinstance(positions, list):
                raise TypeError
        except (ValueError, KeyError, TypeError):
            # ValueError covers JSONDecodeError AND CPython's int-digit
            # limit on absurd JSON number literals — either way a 400,
            # never a handler traceback.
            return self._send_json(
                400,
                {"error": 'body must be {"positions": [int|"0x..", ...]}'},
            )
        if len(positions) > _MAX_POSITIONS_PER_REQUEST:
            return self._send_json(
                400,
                {"error": f"at most {_MAX_POSITIONS_PER_REQUEST} positions "
                          "per request"},
            )
        reader = route.reader
        parsed: list = []  # (echo, packed int) or (echo, error string)
        for p in positions:
            try:
                parsed.append((p, parse_position(reader.game, p)))
            except (ValueError, TypeError) as e:
                parsed.append((p, f"invalid position ({e})"))
        states = [s for _, s in parsed if isinstance(s, int)]
        # Resident-book short path: positions the opening book answers
        # never reach the batcher (no coalescing wait, no canonicalize,
        # no block decode); only the remainder is submitted.
        with activate((self._qtrace,)):
            book = srv.book_lookup(route, states)
        if book is not None:
            pending = [s for i, s in enumerate(states) if not book[2][i]]
        else:
            pending = states
        try:
            answers = iter(route.batcher.submit(pending,
                                                trace=self._qtrace))
        except BatcherUnavailable as e:
            # Genuinely transient (shutdown, deadline, shed, breaker):
            # 503 + Retry-After so a well-behaved client backs off
            # instead of hammering a recovering server.
            self._shed_status = (
                "tripped" if isinstance(e, BatcherTripped) else "shed"
            )
            return self._send_json(
                503, {"error": str(e)},
                headers={"Retry-After": str(e.retry_after)},
            )
        except Exception as e:  # noqa: BLE001 - reader faults re-raise in
            # submit (a truncated shard, an unreadable mmap): answer 500
            # rather than dropping the connection mid-response.
            return self._send_json(500, {"error": f"lookup failed: {e}"})
        sentinel = int(reader.game.sentinel)
        results = []
        j = 0  # index into states (and the book arrays)
        for echo, s in parsed:
            if not isinstance(s, int):
                results.append({"position": echo, "error": s})
                continue
            if book is not None and book[2][j]:
                value, rem, found = (
                    int(book[0][j]), int(book[1][j]), True,
                )
                best = int(book[3][j])
                best = None if best == sentinel else best
            else:
                value, rem, found, best = next(answers)
            j += 1
            rec = {"position": hex(s), "found": found}
            if found:
                rec["value"] = value_name(value)
                rec["remoteness"] = rem
                rec["best"] = None if best is None else hex(best)
            results.append(rec)
        return self._send_json(
            200, {"game": reader.game.name, "results": results}
        )


class _QueryHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    request_queue_size = LISTEN_BACKLOG

    def __init__(self, addr, routes, registry=None, listen_sock=None,
                 worker_id=None):
        if listen_sock is None:
            super().__init__(addr, _Handler)
        else:
            # Fleet worker: adopt the supervisor's pre-bound, already-
            # listening socket instead of binding — N workers share one
            # accept queue, so the kernel spreads connections across
            # them and a draining worker's unaccepted backlog is simply
            # picked up by its siblings.
            super().__init__(addr, _Handler, bind_and_activate=False)
            # TCPServer.__init__ unconditionally created a socket we
            # will never bind; close it rather than leak one fd per
            # worker for the process lifetime.
            self.socket.close()
            self.socket = listen_sock
            self.server_address = listen_sock.getsockname()
            # server_bind would also resolve these; it never ran.
            self.server_name = self.server_address[0]
            self.server_port = self.server_address[1]
        #: name -> _Route; "" is the default (bare /query) route.
        self.routes = dict(routes)
        self.default_route = (
            next(iter(self.routes.values()))
            if len(self.routes) == 1 else self.routes.get("")
        )
        self.worker_id = worker_id
        self.registry = registry or default_registry()
        #: flipped by QueryServer.begin_drain(): /healthz says so and new
        #: POST /query work answers 503 while in-flight requests finish.
        self.draining = False
        self._stats_lock = threading.Lock()
        self._t0 = time.time()
        self._http_requests = 0  # guarded-by: _stats_lock
        self._http_errors = 0  # guarded-by: _stats_lock
        self._http_client_aborts = 0  # guarded-by: _stats_lock
        # POSTs between entry and response written
        self._inflight = 0  # guarded-by: _stats_lock
        # Open connections -> POSTs in flight on each. Tracking them is
        # what lets stop() wake handler threads parked in recv on IDLE
        # keep-alive sockets instead of waiting out their 30 s socket
        # timeout one by one during a supervisor-initiated drain.
        self._conns = {}  # guarded-by: _stats_lock
        self._latency_total = 0.0  # guarded-by: _stats_lock
        self._latency_max = 0.0  # guarded-by: _stats_lock
        # server_start_time makes uptime derivable from any scrape
        # (time() - server_start_time), the Prometheus convention.
        self.registry.gauge(
            "gamesman_server_start_time_seconds",
            "unix time the query server bound its port",
        ).set(self._t0)
        self._m_requests = self.registry.counter(
            "gamesman_http_requests_total", "POST requests, rejects included"
        )
        self._m_errors = self.registry.counter(
            "gamesman_http_errors_total", "POST requests answered >= 400"
        )
        self._m_latency = self.registry.histogram(
            "gamesman_http_request_seconds",
            "wall seconds per POST request, parse to response",
        )
        self._m_client_aborts = self.registry.counter(
            "gamesman_http_client_aborts_total",
            "responses abandoned by a disconnecting client "
            "(BrokenPipe/ConnectionReset on the write path)",
        )
        #: Tail-sampled per-worker query traces (GET /traces) and the
        #: declared availability/latency objectives. Both read their
        #: knobs from GAMESMAN_TRACE_* / GAMESMAN_SLO_* env.
        self.trace_ring = TraceRing(registry=self.registry)
        self.slo = SloEngine(registry=self.registry)
        #: max-age of the GET /query edge-cache contract; the ETag's
        #: epoch prefix is what actually bounds staleness across a
        #: reload (docs/SERVING.md "Hot path").
        self.query_max_age = env_int("GAMESMAN_QUERY_MAX_AGE_SECS", 3600)
        #: route name -> gamesman_book_hits_total counter. Registry
        #: lookups validate the metric name per call; the book path is
        #: hot enough that we resolve each route's counter once.
        self._book_counters = {}

    def _book_counter(self, route):
        counter = self._book_counters.get(route.name)
        if counter is None:
            counter = self.registry.counter(
                "gamesman_book_hits_total",
                "queries answered from the resident opening book "
                "(no batcher, no canonicalize, no block decode)",
                route=route.name or "default",
            )
            self._book_counters[route.name] = counter
        return counter

    def book_lookup(self, route, states):
        """Probe a route's resident opening book (db/book.py) -> the
        (values, remoteness, found, best) arrays, or None when the
        route serves no book. Counted per route; the ``book`` span
        lands on whatever trace the caller has activated."""
        book = getattr(route.reader, "book", None)
        if book is None or not states:
            return None
        with qspan("book", queries=len(states)) as sp:
            out = book.lookup(np.asarray(
                states, dtype=route.reader.game.state_dtype
            ))
            hits = int(out[2].sum())
            if sp is not None:
                sp["hits"] = hits
        if hits:
            self._book_counter(route).inc(hits)
        return out

    # Single-DB back-compat aliases: most callers (tests, the batcher's
    # half-open probe wiring) speak "the reader"/"the batcher".
    @property
    def reader(self):
        route = self.default_route or next(iter(self.routes.values()))
        return route.reader

    @property
    def batcher(self):
        route = self.default_route or next(iter(self.routes.values()))
        return route.batcher

    def health_status(self) -> str:
        if self.draining:
            return "draining"
        for route in self.routes.values():
            if route.batcher is not None and route.batcher.state != "ok":
                return "degraded"
        if self.slo.fast_burning():
            # An SLO fast-burn is pre-emptive degradation: the error
            # budget is being spent ~14x faster than sustainable, so go
            # amber BEFORE it is gone. The fleet supervisor already
            # propagates a degraded worker beat into fleet /status.
            return "degraded"
        return "ok"

    # wire: producer
    def healthz(self) -> dict:
        """The /healthz payload. Three states, one field: "ok" (serving
        normally), "degraded" (some reader's circuit breaker open —
        misses answer 503, cache hits still serve), "draining" (shutdown
        in progress; stop routing here). Always 200: a load balancer
        reads the body, an operator reads it too. Single-DB servers keep
        the legacy flat identity fields; every server also carries the
        per-game "games" map (the fleet view)."""
        games = {}
        for name, route in self.routes.items():
            games[name or "default"] = {
                "game": route.reader.game.name,
                "spec": route.reader.manifest["spec"],
                "positions": route.reader.num_positions,
                "levels": len(route.reader.levels),
                "breaker": route.batcher.state
                if route.batcher is not None else "ok",
            }
        payload = {
            "status": self.health_status(),
            "games": games,
            "slo": self.slo.snapshot(),
        }
        if self.worker_id is not None:
            payload["worker"] = self.worker_id
        if self.default_route is not None:
            r = self.default_route
            payload.update({
                "breaker": r.batcher.state if r.batcher is not None
                else "ok",
                "game": r.reader.game.name,
                "spec": r.reader.manifest["spec"],
                "positions": r.reader.num_positions,
                "levels": len(r.reader.levels),
            })
        return payload

    def note_client_abort(self) -> None:
        with self._stats_lock:
            self._http_client_aborts += 1
        self._m_client_aborts.inc()

    def conn_opened(self, conn) -> None:
        with self._stats_lock:
            self._conns[conn] = 0

    def conn_closed(self, conn) -> None:
        with self._stats_lock:
            self._conns.pop(conn, None)

    def note_inflight(self, delta: int, conn=None) -> None:
        with self._stats_lock:
            self._inflight += delta
            if conn is not None and conn in self._conns:
                self._conns[conn] += delta

    def shutdown_idle_conns(self, force: bool = False) -> int:
        """Shut down tracked connections with no POST in flight (all of
        them when ``force``), waking their handler threads out of the
        blocking keep-alive read immediately. Returns how many were
        closed. A keep-alive client sees a clean connection close
        between requests — the normal HTTP/1.1 end-of-keep-alive, not a
        failed request."""
        with self._stats_lock:
            victims = [
                c for c, inflight in self._conns.items()
                if force or inflight == 0
            ]
        for conn in victims:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass  # already dying; the handler's read still returns
        return len(victims)

    @property
    def inflight(self) -> int:
        with self._stats_lock:
            return self._inflight

    def handle_error(self, request, client_address):
        """Client aborts escaping outside _send_text (e.g. during the
        request read) are counted, not dumped as thread tracebacks;
        everything else keeps the stdlib report."""
        exc = sys.exc_info()[1]
        if isinstance(exc, CLIENT_ABORT_ERRORS):
            self.note_client_abort()
            return
        super().handle_error(request, client_address)

    def note_request(self, secs: float, code: int, *, route: str = "",
                     shed: bool = False, trace=None) -> None:
        with self._stats_lock:
            self._http_requests += 1
            if code >= 400:
                self._http_errors += 1
            self._latency_total += secs
            self._latency_max = max(self._latency_max, secs)
        self._m_requests.inc()
        if code >= 400:
            self._m_errors.inc()
        # Exemplar: the trace id of the last SLOW observation rides the
        # histogram (OpenMetrics style) so a scrape's p99 bucket links
        # straight to a concrete kept trace.
        exemplar = None
        if trace is not None and secs * 1e3 >= self.trace_ring.slow_ms:
            exemplar = {"trace_id": trace.trace_id}
        self._m_latency.observe(secs, exemplar=exemplar)
        if route:
            self.registry.histogram(
                "gamesman_http_route_request_seconds",
                "wall seconds per POST request by route",
                route=route,
            ).observe(secs, exemplar=exemplar)
            self.slo.observe(route, secs, code, shed=shed)

    def metrics(self) -> dict:
        with self._stats_lock:
            n = self._http_requests
            errors = self._http_errors
            aborts = self._http_client_aborts
            mean = self._latency_total / max(n, 1)
            peak = self._latency_max
            uptime = time.time() - self._t0
        payload = {
            "server_start_time": self._t0,
            "uptime_secs": uptime,
            "status": self.health_status(),
            "http_requests": n,
            "http_errors": errors,
            "http_client_aborts": aborts,
            "latency_mean_ms": mean * 1e3,
            "latency_max_ms": peak * 1e3,
        }
        if self.worker_id is not None:
            payload["worker"] = self.worker_id
        if len(self.routes) == 1:
            # Legacy single-DB shape: batcher counters flat in the dict.
            payload.update(self.batcher.metrics())
        else:
            payload["games"] = {
                (name or "default"): route.batcher.metrics()
                for name, route in self.routes.items()
                if route.batcher is not None
            }
        return payload


class QueryServer:
    """Owns the HTTP server + per-game batcher lifecycle.

    One positional ``reader`` serves a single DB on the default route
    (unchanged contract); ``readers={name: DbReader}`` serves a fleet —
    each game gets its own coalescing batcher (and so its own circuit
    breaker: one rotting DB degrades one route, not the fleet).

    port=0 binds an ephemeral port (tests); `.port` reports the bound
    one. ``listen_sock`` adopts a pre-bound, already-listening socket
    instead of binding (the supervised-worker path). Use `.start()` for
    a background thread (in-process tests, workers) or
    `.serve_forever()` to block (the CLI `serve` subcommand).
    """

    def __init__(self, reader=None, *, readers=None,
                 host: str = "127.0.0.1", port: int = 0,
                 listen_sock=None, worker_id=None,
                 window: float = 0.002, cache_size: int = 65536,
                 max_queue: int = 1024, request_timeout: float | None = None,
                 breaker_threshold: int = 3, breaker_cooldown: float = 5.0,
                 logger=None, registry=None):
        if (reader is None) == (readers is None):
            raise ValueError("pass exactly one of reader= or readers=")
        routes = (
            {"": _Route("", reader)} if reader is not None
            else {name: _Route(name, r) for name, r in readers.items()}
        )
        if not routes:
            raise ValueError("readers= must name at least one DB")
        self.logger = logger
        self.registry = registry or default_registry()
        # Bind FIRST: a bind failure (port in use) must raise before any
        # batcher spawns its worker thread, or every failed construction
        # would leak unjoinable daemon threads.
        self._httpd = _QueryHTTPServer(
            (host, port), routes, self.registry,
            listen_sock=listen_sock, worker_id=worker_id,
        )
        for route in self._httpd.routes.values():
            route.batcher = Batcher(
                route.reader, window=window, cache_size=cache_size,
                max_queue=max_queue, request_timeout=request_timeout,
                breaker_threshold=breaker_threshold,
                breaker_cooldown=breaker_cooldown,
                logger=logger, registry=self.registry,
            )
        self._thread: threading.Thread | None = None

    @property
    def reader(self):
        return self._httpd.reader

    @property
    def batcher(self):
        return self._httpd.batcher

    @property
    def routes(self) -> dict:
        return self._httpd.routes

    @property
    def inflight(self) -> int:
        return self._httpd.inflight

    @property
    def trace_ring(self):
        return self._httpd.trace_ring

    @property
    def slo(self):
        return self._httpd.slo

    @property
    def host(self) -> str:
        return self._httpd.server_address[0]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="gamesman-serve",
            daemon=True,
        )
        self._thread.start()

    def serve_forever(self) -> None:
        self._httpd.serve_forever()

    def metrics(self) -> dict:
        return self._httpd.metrics()

    def healthz(self) -> dict:
        return self._httpd.healthz()

    def self_probe(self) -> None:
        """Warm-start self-probe: one REAL lookup of every routed game's
        initial position through the full batcher->reader path. Raises
        on any failure (a worker must not join the ready set answering
        from a path it has never exercised); as a side effect the
        canonicalize/expand kernels compile here, off the serving path,
        so the first client request never pays a cold compile."""
        for route in self._httpd.routes.values():
            out = route.batcher.submit(
                [int(route.reader.game.initial_state())]
            )
            if not out or not out[0][2]:
                raise RuntimeError(
                    f"self-probe: initial position of "
                    f"{route.reader.game.name!r} not found in its DB"
                )

    def begin_drain(self) -> None:
        """Flip /healthz to "draining" and 503 new queries while
        in-flight requests finish — the first half of a SIGTERM
        shutdown; stop() completes it."""
        self._httpd.draining = True

    def serve_stats(self) -> dict:
        """One summary record (phase ``serve_stats``): per-route
        estimated latency quantiles from the route histogram plus the
        SLO burn snapshot — the JSONL twin of /status, logged once at
        stop() and folded by tools/obs_report.py into the per-route
        serving table."""
        fam = self.registry.snapshot().get(
            "gamesman_http_route_request_seconds", {}
        )
        routes = {}
        for row in fam.get("values", ()):
            q = row.get("quantiles", {})
            routes[row["labels"].get("route", "default")] = {
                "count": row.get("count", 0),
                **{
                    f"{k}_ms": round(q[k] * 1e3, 3)
                    for k in ("p50", "p95", "p99")
                    if q.get(k) is not None
                },
            }
        slo = self.slo.snapshot()
        return {
            "phase": "serve_stats",
            "routes": routes,
            "slo": {
                "fast_burn": slo["fast_burn"],
                "p99_ms": slo["p99_ms"],
                "routes": slo["routes"],
            },
        }

    def stop(self) -> None:
        # Stop ACCEPTING first: a connection this server never accepted
        # is someone else's to answer (a fleet sibling's via the shared
        # accept queue; a load balancer's retry single-process). Flip
        # draining only AFTER the accepted requests got their grace —
        # a request the server chose to accept arrived before the drain
        # and deserves an answer, not a 503 from a batcher closed under
        # it (observed as rolling-reload request failures).
        self._httpd.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        # Grace: accepted requests reach and clear the still-open
        # batchers. inflight counts POSTs between entry and response
        # written; the settle re-check catches one accepted and parsed
        # but not yet counted. Keep-alive clients issuing NEW requests
        # during the grace are bounded by the deadline.
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if self._httpd.inflight == 0:
                time.sleep(0.05)
                if self._httpd.inflight == 0:
                    break
            else:
                time.sleep(0.01)
        self.begin_drain()
        # Requests still coalescing get one final flush (drain=True).
        for route in self._httpd.routes.values():
            route.batcher.close(drain=True)
        if self.logger is not None:
            # After the final flush so every answered request's latency
            # observation is in the histogram the quantiles summarize.
            self.logger.log(self.serve_stats())
        # Handler threads are daemons ThreadingHTTPServer never joins: a
        # process exit right after this call would kill them mid-write,
        # truncating the very responses the drain flushed. Two-step
        # teardown: (1) shut down IDLE keep-alive connections now —
        # their handler threads sit in a blocking recv waiting for a
        # next request that will never come, and without the nudge each
        # would pin the drain until its socket timeout; (2) bounded wait
        # for the in-flight POSTs to finish writing (their batch answers
        # arrived in the close(drain=True) above, so this is socket-
        # write time — milliseconds; the deadline only guards a hung
        # client), then force-close whatever remains.
        self._httpd.shutdown_idle_conns()
        deadline = time.monotonic() + 5.0
        while self._httpd.inflight > 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        self._httpd.shutdown_idle_conns(force=True)
        self._httpd.server_close()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
