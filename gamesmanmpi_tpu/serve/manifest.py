"""Fleet manifest: one supervisor routing many game databases.

A fleet manifest is a JSON file naming the solved-position DBs one
serving fleet answers for::

    {
      "version": 1,
      "games": [
        {"name": "c4_54", "db": "dbs/c4_54.db"},
        {"name": "ttt",   "db": "dbs/ttt.db"}
      ]
    }

``name`` is the URL routing key (``POST /query/<name>``) and must be a
single url-safe token; ``db`` is an export-db directory, resolved
relative to the manifest file's own directory so a manifest can ship
next to its DBs. Validation here is structural only (names unique and
well-formed, directories present) — DB *integrity* is the worker
warm-start gate's job (db/check.verify_for_serving), re-run by every
worker before it joins the ready set.
"""

from __future__ import annotations

import json
import pathlib
import re

from gamesmanmpi_tpu.db.format import MANIFEST_NAME

#: Routing keys must survive a URL path segment un-escaped.
_NAME_RE = re.compile(r"^[A-Za-z0-9_.-]+$")

FLEET_VERSION = 1


class FleetEntry:
    """One (routing name, DB directory) pair of a serving fleet."""

    __slots__ = ("name", "db")

    def __init__(self, name: str, db: str):
        self.name = name
        self.db = str(db)

    def __repr__(self) -> str:  # tests / log lines
        return f"FleetEntry(name={self.name!r}, db={self.db!r})"


def load_fleet_manifest(path) -> list[FleetEntry]:
    """Parse + validate a fleet manifest; raises ValueError on junk.

    A malformed manifest must fail the *reload/launch*, loudly, before
    any worker is restarted against it — a half-validated fleet config
    is how a rolling reload takes a healthy fleet down.
    """
    path = pathlib.Path(path)
    try:
        doc = json.loads(path.read_text())
    except OSError as e:
        raise ValueError(f"cannot read fleet manifest {path}: {e}") from None
    except json.JSONDecodeError as e:
        raise ValueError(f"fleet manifest {path} is not JSON: {e}") from None
    if not isinstance(doc, dict) or doc.get("version") != FLEET_VERSION:
        raise ValueError(
            f"fleet manifest {path}: expected "
            f'{{"version": {FLEET_VERSION}, "games": [...]}}'
        )
    games = doc.get("games")
    if not isinstance(games, list) or not games:
        raise ValueError(f"fleet manifest {path}: 'games' must be a "
                         "non-empty list")
    entries: list[FleetEntry] = []
    seen: set[str] = set()
    for i, rec in enumerate(games):
        if not isinstance(rec, dict) or not rec.get("name") \
                or not rec.get("db"):
            raise ValueError(
                f"fleet manifest {path}: games[{i}] needs 'name' and 'db'"
            )
        name = str(rec["name"])
        if not _NAME_RE.match(name):
            raise ValueError(
                f"fleet manifest {path}: game name {name!r} is not a "
                "url-safe token"
            )
        if name in seen:
            raise ValueError(
                f"fleet manifest {path}: duplicate game name {name!r}"
            )
        seen.add(name)
        db = pathlib.Path(rec["db"])
        if not db.is_absolute():
            db = path.parent / db
        if not db.is_dir():
            raise ValueError(
                f"fleet manifest {path}: games[{i}] ({name}): no such DB "
                f"directory {db}"
            )
        # A directory without a readable DB manifest is a half-landed
        # pull (or a typo'd path) — reject it HERE, naming the entry,
        # before any worker is drained against it. The full integrity
        # gate (db/check.verify_for_serving) still runs per worker;
        # this is the cheap fail-early half.
        dbm = db / MANIFEST_NAME
        try:
            present = dbm.is_file()
        except OSError as e:  # unreadable parent (perms, stale mount)
            raise ValueError(
                f"fleet manifest {path}: games[{i}] ({name}): DB "
                f"directory {db} is unreadable ({e})"
            ) from None
        if not present:
            raise ValueError(
                f"fleet manifest {path}: games[{i}] ({name}): {db} has "
                f"no {MANIFEST_NAME} — not a finalized DB (half-landed "
                "pull or export?)"
            )
        entries.append(FleetEntry(name, str(db)))
    return entries


def single_db_entries(db) -> list[FleetEntry]:
    """The degenerate fleet of a bare ``serve DB`` invocation: one DB on
    the default route (empty name — ``POST /query`` with no suffix)."""
    return [FleetEntry("", str(db))]
