"""Coalescing micro-batcher + LRU hot-position cache for query serving.

The DbReader's probe is vectorized: one searchsorted over a whole batch
costs barely more than over one key, and the canonicalize kernel is a
fixed-capacity program either way. So the server never probes per
request — concurrent requests park in a queue for a short coalescing
window (default 2 ms) and flush as ONE `DbReader.lookup_best` call. The
same shape as ML inference micro-batching, and the serving twin of the
engine's own design rule (bulk kernels, never per-position work).

In front of the batch sits an LRU cache keyed on the raw queried
position: real traffic is Zipf-ish (openings and famous positions
repeat), and a cache hit answers without touching the batcher at all.
Raw — not canonical — keys mean symmetric duplicates occupy separate
entries; that costs cache capacity, never correctness, and avoids paying
a canonicalize kernel call before the cache.

Degradation model (the resilience layer, docs/CONFIG.md): the batcher
never hangs a client and never dies with the reader. Every ``submit``
carries a deadline (``request_timeout``; expiry raises
:class:`BatcherTimeout` → HTTP 503 + Retry-After); a queue deeper than
``max_queue`` sheds new requests (:class:`BatcherOverloaded`); and
consecutive reader faults trip a circuit breaker
(:class:`BatcherTripped`) that fails misses fast — cache hits still
answer — while the worker re-probes the reader in the background
(half-open) and closes the circuit on the first success, no restart
needed. ``state`` reports ok/open/half_open; ``/healthz`` maps any
non-ok state to "degraded".

Counters are plain ints mutated under the one lock and snapshotted by
`metrics()` (the `/metrics.json` dict); per-batch records go to the
shared utils/metrics JSONL logger so serving latency lands in the same
stream as solve phases, and the obs registry carries the Prometheus
series (`gamesman_batch_queue_depth`, `gamesman_batch_size`,
`gamesman_batch_seconds`, cache hit/miss counters, shed/timeout/breaker
counters) that `/metrics` exposes.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict

import numpy as np

from gamesmanmpi_tpu.obs import default_registry
from gamesmanmpi_tpu.obs.qtrace import activate as _activate_traces
from gamesmanmpi_tpu.obs.registry import DEFAULT_SIZE_BUCKETS
from gamesmanmpi_tpu.resilience import faults
from gamesmanmpi_tpu.utils.env import env_float as _env_float


class BatcherUnavailable(RuntimeError):
    """Base of the *transient* submit failures (the HTTP layer answers
    503 + Retry-After for every subclass, 500 only for real reader
    faults — jaxlib's runtime errors subclass RuntimeError, so matching
    on RuntimeError would misclassify a broken DB as a recovering
    server). ``retry_after`` is the advisory client backoff in seconds.
    """

    retry_after = 1

    def __init__(self, msg: str, retry_after: int | None = None):
        super().__init__(msg)
        if retry_after is not None:
            self.retry_after = max(1, int(retry_after))


class BatcherClosed(BatcherUnavailable):
    """submit() after (or parked across) close(): server shutdown."""


class BatcherTimeout(BatcherUnavailable):
    """The per-request deadline expired before the batch flushed."""


class BatcherOverloaded(BatcherUnavailable):
    """Queue-depth load shedding: more parked requests than max_queue."""


class BatcherTripped(BatcherUnavailable):
    """Circuit breaker open after consecutive reader faults; misses
    fail fast until the background half-open re-probe succeeds."""


class _Request:
    """One submitter's slice of a coalesced batch."""

    __slots__ = ("states", "event", "out", "error", "trace", "enq")

    def __init__(self, states: np.ndarray, trace=None):
        self.states = states
        self.event = threading.Event()
        self.out = None
        self.error = None
        #: obs.qtrace.QueryTrace of the submitting request (or None).
        #: The flush attributes its queue wait and the coalesced probe's
        #: spans to every member trace.
        self.trace = trace
        self.enq = time.perf_counter()


class Batcher:
    """Thread-safe coalescing front-end over one DbReader.

    submit() blocks its calling thread until the worker flushes the
    window's batch (or its deadline expires); results come back per
    position as (value, remoteness, found, best) tuples of Python
    scalars.
    """

    def __init__(self, reader, *, window: float = 0.002,
                 cache_size: int = 65536, max_batch: int = 1 << 16,
                 max_queue: int = 1024, request_timeout: float | None = None,
                 breaker_threshold: int = 3, breaker_cooldown: float = 5.0,
                 logger=None, registry=None):
        self.reader = reader
        self.window = float(window)
        #: Flush threshold: a burst larger than this splits into several
        #: probes instead of one giant one — an unbounded coalesce would
        #: pad to a huge (possibly freshly-compiled) kernel capacity and
        #: stall every parked request behind a single oversized batch.
        self.max_batch = int(max_batch)
        #: Load-shed threshold: requests (not positions) parked at once.
        self.max_queue = max(1, int(max_queue))
        #: Per-request deadline in seconds (0 = wait forever). None reads
        #: GAMESMAN_REQUEST_TIMEOUT (default 30 — matches the handler's
        #: socket timeout, so the batcher always answers first).
        if request_timeout is None:
            request_timeout = _env_float("GAMESMAN_REQUEST_TIMEOUT", 30.0)
        self.request_timeout = float(request_timeout)
        #: Consecutive reader faults that open the circuit breaker, and
        #: how long it stays open before a half-open re-probe.
        self.breaker_threshold = max(1, int(breaker_threshold))
        self.breaker_cooldown = float(breaker_cooldown)
        self.logger = logger
        self._cache: OrderedDict = OrderedDict()  # guarded-by: _lock
        # Clamp: a negative size (the conventional "unlimited" spelling
        # elsewhere) would make the eviction loop pop an empty dict.
        self._cache_size = max(0, int(cache_size))
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending: list[_Request] = []  # guarded-by: _lock
        self._closed = False  # guarded-by: _lock
        #: breaker: "ok" | "open" | "half_open" (+ the fault streak and
        #: when the circuit opened), all mutated under the one lock.
        self._breaker = "ok"  # guarded-by: _lock
        self._consecutive_faults = 0  # guarded-by: _lock
        self._opened_at = 0.0  # guarded-by: _lock
        # guarded-by: _lock
        self.counters = {
            "requests": 0,
            "queries": 0,
            "cache_hits": 0,
            "cache_misses": 0,
            "batches": 0,
            "batched_queries": 0,
            "max_batch_size": 0,
            "batch_secs_total": 0.0,
            "timeouts": 0,
            "shed": 0,
            "dup_hits": 0,
            "reader_faults": 0,
            "breaker_opens": 0,
        }
        reg = registry or default_registry()
        self._m_queue_depth = reg.gauge(
            "gamesman_batch_queue_depth",
            "requests parked in the coalescing window right now",
        )
        self._m_batch_size = reg.histogram(
            "gamesman_batch_size", "positions per flushed probe batch",
            buckets=DEFAULT_SIZE_BUCKETS,
        )
        self._m_batch_secs = reg.histogram(
            "gamesman_batch_seconds", "wall seconds per flushed probe batch"
        )
        self._m_cache_hits = reg.counter(
            "gamesman_cache_hits_total", "positions answered from the LRU"
        )
        self._m_cache_misses = reg.counter(
            "gamesman_cache_misses_total", "positions that went to a probe"
        )
        self._m_timeouts = reg.counter(
            "gamesman_request_timeouts_total",
            "submits whose per-request deadline expired",
        )
        self._m_shed = reg.counter(
            "gamesman_requests_shed_total",
            "submits refused by load shedding or an open breaker",
        )
        self._m_dup_hits = reg.counter(
            "gamesman_batch_dup_hits_total",
            "positions coalesced away by in-flight dedup before the probe",
        )
        self._m_reader_faults = reg.counter(
            "gamesman_reader_faults_total",
            "probe batches that failed with a reader error",
        )
        self._m_breaker_state = reg.gauge(
            "gamesman_breaker_state",
            "reader circuit breaker: 0=ok, 1=half_open, 2=open",
        )
        self._m_breaker_state.set(0)
        self._worker = threading.Thread(
            target=self._loop, name="gamesman-batcher", daemon=True
        )
        self._worker.start()

    # ------------------------------------------------------------ client API

    @property
    def state(self) -> str:
        """Breaker state: "ok" | "open" | "half_open"."""
        with self._lock:
            return self._breaker

    def submit(self, positions,
               timeout: float | None = None, trace=None,
               ) -> list[tuple[int, int, bool, int | None]]:
        """Resolve a request's positions; blocks until the batch flushes
        or the deadline (``timeout``, default the batcher's
        ``request_timeout``; 0 = forever) expires.

        positions: iterable of ints (already range-validated by the
        caller). Returns one (value, remoteness, found, best_or_None)
        tuple per position, in order. Raises a
        :class:`BatcherUnavailable` subclass on shutdown, deadline,
        shedding, or an open breaker — cache hits are still served in
        every state, so a degraded server keeps answering its hot set.
        """
        positions = [int(p) for p in positions]
        results: list = [None] * len(positions)
        miss_idx: list[int] = []
        miss_pos: list[int] = []
        with self._lock:
            if self._closed:
                raise BatcherClosed("batcher is closed")
            self.counters["requests"] += 1
            self.counters["queries"] += len(positions)
            for i, p in enumerate(positions):
                hit = self._cache.get(p)
                if hit is not None:
                    self._cache.move_to_end(p)
                    self.counters["cache_hits"] += 1
                    results[i] = hit
                else:
                    self.counters["cache_misses"] += 1
                    miss_idx.append(i)
                    miss_pos.append(p)
        if len(positions) > len(miss_idx):
            self._m_cache_hits.inc(len(positions) - len(miss_idx))
        if miss_idx:
            self._m_cache_misses.inc(len(miss_idx))
        if not miss_idx:
            return results
        req = _Request(
            np.asarray(miss_pos, dtype=self.reader.game.state_dtype),
            trace=trace,
        )
        with self._cond:
            if self._closed:  # close() may have landed since the cache pass
                raise BatcherClosed("batcher is closed")
            if self._breaker != "ok":
                self.counters["shed"] += 1
                self._m_shed.inc()
                remaining = self._opened_at + self.breaker_cooldown \
                    - time.monotonic()
                raise BatcherTripped(
                    "reader circuit breaker is open",
                    retry_after=max(1, int(remaining) + 1),
                )
            if len(self._pending) >= self.max_queue:
                self.counters["shed"] += 1
                self._m_shed.inc()
                raise BatcherOverloaded(
                    f"query queue is full ({self.max_queue} requests parked)"
                )
            self._pending.append(req)
            self._m_queue_depth.set(len(self._pending))
            self._cond.notify_all()
        deadline = self.request_timeout if timeout is None else float(timeout)
        ok = req.event.wait(deadline if deadline > 0 else None)
        if not ok:
            with self._cond:
                if req in self._pending:
                    self._pending.remove(req)
                    self._m_queue_depth.set(len(self._pending))
                if req.event.is_set():
                    ok = True  # flushed while we raced the removal
                else:
                    self.counters["timeouts"] += 1
            if not ok:
                self._m_timeouts.inc()
                raise BatcherTimeout(
                    f"request deadline ({deadline:g}s) exceeded"
                )
        if req.error is not None:
            raise req.error
        with self._lock:
            for j, i in enumerate(miss_idx):
                results[i] = req.out[j]
                self._cache[miss_pos[j]] = req.out[j]
                self._cache.move_to_end(miss_pos[j])
            while len(self._cache) > self._cache_size:
                self._cache.popitem(last=False)
        return results

    def close(self, drain: bool = False) -> None:
        """Stop the batcher. Default: requests still parked in the
        coalescing window fail with BatcherClosed (→ 503; a client
        retries another replica) — they must never hang on an event
        nobody will set. ``drain=True`` (graceful shutdown, SIGTERM)
        flushes the parked requests through one last probe first."""
        with self._cond:
            self._closed = True
            if not drain:
                for r in self._pending:
                    r.error = BatcherClosed("batcher is closed")
                    r.event.set()
                self._pending.clear()
                self._m_queue_depth.set(0)
            self._cond.notify_all()
        self._worker.join(timeout=5)

    def metrics(self) -> dict:
        """Snapshot of the coalescing/cache counters (+ derived means)."""
        with self._lock:
            c = dict(self.counters)
            state = self._breaker
        batches = max(c["batches"], 1)
        lookups = c["cache_hits"] + c["cache_misses"]
        return {
            **c,
            "breaker_state": state,
            "mean_batch_size": c["batched_queries"] / batches,
            "mean_batch_secs": c["batch_secs_total"] / batches,
            "cache_hit_rate": c["cache_hits"] / max(lookups, 1),
        }

    # ------------------------------------------------------- circuit breaker

    def _note_reader_fault(self) -> None:
        with self._lock:
            self.counters["reader_faults"] += 1
            self._consecutive_faults += 1
            streak = self._consecutive_faults
            opened = (
                self._breaker == "ok"
                and streak >= self.breaker_threshold
            )
            if opened or self._breaker == "half_open":
                self._breaker = "open"
                self._opened_at = time.monotonic()
                if opened:
                    self.counters["breaker_opens"] += 1
        self._m_reader_faults.inc()
        if opened:
            self._m_breaker_state.set(2)
            if self.logger is not None:
                self.logger.log({
                    "phase": "breaker_open",
                    "consecutive_faults": streak,
                })
        elif self.state == "open":
            self._m_breaker_state.set(2)

    def _note_reader_ok(self) -> None:
        recovered = False
        with self._lock:
            self._consecutive_faults = 0
            if self._breaker != "ok":
                self._breaker = "ok"
                recovered = True
        if recovered:
            self._m_breaker_state.set(0)
            if self.logger is not None:
                self.logger.log({"phase": "breaker_closed"})

    # requires-lock: _lock
    def _breaker_wait(self) -> float | None:
        """Seconds the idle worker may sleep before it owes a half-open
        re-probe; None when the breaker is closed (sleep until work).
        Called with the lock held (from the worker's _cond wait loop)."""
        if self._breaker == "ok":
            return None
        return max(
            0.01, self._opened_at + self.breaker_cooldown - time.monotonic()
        )

    def _breaker_tick(self) -> None:
        """Half-open re-probe: after the cooldown, probe the reader with
        one real lookup (through the same faultable probe path) in the
        worker thread — no client request is spent on the experiment —
        and close the circuit on success."""
        with self._lock:
            if self._breaker == "ok":
                return
            if time.monotonic() < self._opened_at + self.breaker_cooldown:
                return
            self._breaker = "half_open"
        self._m_breaker_state.set(1)
        try:
            probe = np.asarray(
                [int(self.reader.game.initial_state())],
                dtype=self.reader.game.state_dtype,
            )
            self.reader.lookup_best(probe)
        except Exception:  # noqa: BLE001 - still broken: stay open
            self._note_reader_fault()
        else:
            self._note_reader_ok()

    # ---------------------------------------------------------------- worker

    def _drain_window(self) -> list[_Request]:
        """Wait for work, then collect what arrives in the window — up to
        max_batch queries; the remainder stays queued and the worker loops
        straight back into the next flush without waiting. With the
        breaker open the wait is bounded so the worker wakes for its
        half-open re-probe even with zero traffic."""
        with self._cond:
            while not self._pending and not self._closed:
                t = self._breaker_wait()
                self._cond.wait(t)
                if t is not None:
                    return []  # let _loop run the breaker tick
            if not self._pending:
                return []
            deadline = time.monotonic() + self.window
            while not self._closed:
                if (
                    sum(r.states.shape[0] for r in self._pending)
                    >= self.max_batch
                ):
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            batch: list[_Request] = []
            total = 0
            while self._pending:
                n = self._pending[0].states.shape[0]
                if batch and total + n > self.max_batch:
                    break
                batch.append(self._pending.pop(0))
                total += n
            self._m_queue_depth.set(len(self._pending))
            return batch

    def _loop(self) -> None:
        while True:
            self._breaker_tick()
            batch = self._drain_window()
            if not batch:
                with self._lock:
                    if self._closed and not self._pending:
                        return
                continue
            t0 = time.perf_counter()
            # Queue-wait span per member request: enqueue to flush start
            # (explicit timing — the wait already happened). Then the
            # coalesced probe runs with ALL member traces active, so the
            # reader/store spans below attribute one shared decode to
            # every request it served.
            traces = [r.trace for r in batch if r.trace is not None]
            for r in batch:
                if r.trace is not None:
                    r.trace.add_span(
                        "queue_wait", r.enq - r.trace._t0, t0 - r.enq,
                        batch=len(batch),
                    )
            try:
                # Everything that can fail lives inside this try: an escape
                # would kill the worker and leave every parked submitter
                # (and all future ones) blocked on events nobody will set.
                faults.fire("serve.flush", batch=len(batch))
                states = np.concatenate([r.states for r in batch])
                # In-flight dedup: a hot (zipf) workload coalesces many
                # requests for the SAME position into one window — probe
                # each distinct state once and fan the answer back out.
                uniq, inverse = np.unique(states, return_inverse=True)
                dup_hits = int(states.shape[0] - uniq.shape[0])
                with _activate_traces(traces):
                    values, rem, found, best = self.reader.lookup_best(
                        uniq
                    )
                values = values[inverse]
                rem = rem[inverse]
                found = found[inverse]
                best = best[inverse]
            except Exception as e:  # noqa: BLE001 - must unblock submitters
                for r in batch:
                    r.error = e
                    r.event.set()
                self._note_reader_fault()
                continue
            self._note_reader_ok()
            secs = time.perf_counter() - t0
            sentinel = int(self.reader.game.sentinel)
            with self._lock:
                self.counters["batches"] += 1
                self.counters["batched_queries"] += int(states.shape[0])
                self.counters["max_batch_size"] = max(
                    self.counters["max_batch_size"], int(states.shape[0])
                )
                self.counters["batch_secs_total"] += secs
                self.counters["dup_hits"] += dup_hits
            if dup_hits:
                self._m_dup_hits.inc(dup_hits)
            self._m_batch_size.observe(int(states.shape[0]))
            self._m_batch_secs.observe(secs)
            if self.logger is not None:
                record = {
                    "phase": "serve_batch",
                    "batch_size": int(states.shape[0]),
                    "requests": len(batch),
                    "dup_hits": dup_hits,
                    "secs": secs,
                }
                # getattr: chaos/unit tests drive the batcher with stub
                # readers that expose only lookup_best.
                stats_fn = getattr(self.reader, "cache_stats", None)
                db_cache = stats_fn() if stats_fn is not None else None
                if db_cache is not None:
                    # Compressed-DB route: cumulative hot-block cache
                    # counters ride every flush record, so the
                    # per-worker JSONL stream carries the hit-rate
                    # trajectory (tools/obs_report.py folds the final
                    # figures into its serve lines). The db name keeps
                    # routes separable in a multi-DB worker's one
                    # stream — without it the report could only keep
                    # the busiest route's counters.
                    record["db_cache_hits"] = db_cache["hits"]
                    record["db_cache_misses"] = db_cache["misses"]
                    # Resident decoded bytes in the backing store tier
                    # (ISSUE 11: shared across readers — the same figure
                    # every route reports, by design): lets obs_report
                    # square per-route hit rates against one budget.
                    if "bytes" in db_cache:
                        record["db_cache_bytes"] = db_cache["bytes"]
                    db_dir = getattr(self.reader, "dir", None)
                    if db_dir is not None:
                        record["db"] = db_dir.name
                self.logger.log(record)
            off = 0
            for r in batch:
                n = r.states.shape[0]
                r.out = [
                    (
                        int(values[off + j]),
                        int(rem[off + j]),
                        bool(found[off + j]),
                        None
                        if int(best[off + j]) == sentinel
                        else int(best[off + j]),
                    )
                    for j in range(n)
                ]
                off += n
                r.event.set()
