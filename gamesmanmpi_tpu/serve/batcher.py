"""Coalescing micro-batcher + LRU hot-position cache for query serving.

The DbReader's probe is vectorized: one searchsorted over a whole batch
costs barely more than over one key, and the canonicalize kernel is a
fixed-capacity program either way. So the server never probes per
request — concurrent requests park in a queue for a short coalescing
window (default 2 ms) and flush as ONE `DbReader.lookup_best` call. The
same shape as ML inference micro-batching, and the serving twin of the
engine's own design rule (bulk kernels, never per-position work).

In front of the batch sits an LRU cache keyed on the raw queried
position: real traffic is Zipf-ish (openings and famous positions
repeat), and a cache hit answers without touching the batcher at all.
Raw — not canonical — keys mean symmetric duplicates occupy separate
entries; that costs cache capacity, never correctness, and avoids paying
a canonicalize kernel call before the cache.

Counters are plain ints mutated under the one lock and snapshotted by
`metrics()` (the `/metrics.json` dict); per-batch records go to the
shared utils/metrics JSONL logger so serving latency lands in the same
stream as solve phases, and the obs registry carries the Prometheus
series (`gamesman_batch_queue_depth`, `gamesman_batch_size`,
`gamesman_batch_seconds`, cache hit/miss counters) that `/metrics`
exposes.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict

import numpy as np

from gamesmanmpi_tpu.obs import default_registry
from gamesmanmpi_tpu.obs.registry import DEFAULT_SIZE_BUCKETS


class BatcherClosed(RuntimeError):
    """submit() after close(): the one *transient* failure (server
    shutdown). A distinct type so the HTTP layer can answer 503 here and
    500 for real reader faults — jaxlib's runtime errors subclass
    RuntimeError, so matching on RuntimeError would misclassify a broken
    DB as a recovering server."""


class _Request:
    """One submitter's slice of a coalesced batch."""

    __slots__ = ("states", "event", "out", "error")

    def __init__(self, states: np.ndarray):
        self.states = states
        self.event = threading.Event()
        self.out = None
        self.error = None


class Batcher:
    """Thread-safe coalescing front-end over one DbReader.

    submit() blocks its calling thread until the worker flushes the
    window's batch; results come back per position as
    (value, remoteness, found, best) tuples of Python scalars.
    """

    def __init__(self, reader, *, window: float = 0.002,
                 cache_size: int = 65536, max_batch: int = 1 << 16,
                 logger=None, registry=None):
        self.reader = reader
        self.window = float(window)
        #: Flush threshold: a burst larger than this splits into several
        #: probes instead of one giant one — an unbounded coalesce would
        #: pad to a huge (possibly freshly-compiled) kernel capacity and
        #: stall every parked request behind a single oversized batch.
        self.max_batch = int(max_batch)
        self.logger = logger
        self._cache: OrderedDict = OrderedDict()
        # Clamp: a negative size (the conventional "unlimited" spelling
        # elsewhere) would make the eviction loop pop an empty dict.
        self._cache_size = max(0, int(cache_size))
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending: list[_Request] = []
        self._closed = False
        self.counters = {
            "requests": 0,
            "queries": 0,
            "cache_hits": 0,
            "cache_misses": 0,
            "batches": 0,
            "batched_queries": 0,
            "max_batch_size": 0,
            "batch_secs_total": 0.0,
        }
        reg = registry or default_registry()
        self._m_queue_depth = reg.gauge(
            "gamesman_batch_queue_depth",
            "requests parked in the coalescing window right now",
        )
        self._m_batch_size = reg.histogram(
            "gamesman_batch_size", "positions per flushed probe batch",
            buckets=DEFAULT_SIZE_BUCKETS,
        )
        self._m_batch_secs = reg.histogram(
            "gamesman_batch_seconds", "wall seconds per flushed probe batch"
        )
        self._m_cache_hits = reg.counter(
            "gamesman_cache_hits_total", "positions answered from the LRU"
        )
        self._m_cache_misses = reg.counter(
            "gamesman_cache_misses_total", "positions that went to a probe"
        )
        self._worker = threading.Thread(
            target=self._loop, name="gamesman-batcher", daemon=True
        )
        self._worker.start()

    # ------------------------------------------------------------ client API

    def submit(self, positions) -> list[tuple[int, int, bool, int | None]]:
        """Resolve a request's positions; blocks until the batch flushes.

        positions: iterable of ints (already range-validated by the
        caller). Returns one (value, remoteness, found, best_or_None)
        tuple per position, in order.
        """
        positions = [int(p) for p in positions]
        results: list = [None] * len(positions)
        miss_idx: list[int] = []
        miss_pos: list[int] = []
        with self._lock:
            if self._closed:
                raise BatcherClosed("batcher is closed")
            self.counters["requests"] += 1
            self.counters["queries"] += len(positions)
            for i, p in enumerate(positions):
                hit = self._cache.get(p)
                if hit is not None:
                    self._cache.move_to_end(p)
                    self.counters["cache_hits"] += 1
                    results[i] = hit
                else:
                    self.counters["cache_misses"] += 1
                    miss_idx.append(i)
                    miss_pos.append(p)
        if len(positions) > len(miss_idx):
            self._m_cache_hits.inc(len(positions) - len(miss_idx))
        if miss_idx:
            self._m_cache_misses.inc(len(miss_idx))
        if not miss_idx:
            return results
        req = _Request(
            np.asarray(miss_pos, dtype=self.reader.game.state_dtype)
        )
        with self._cond:
            if self._closed:  # close() may have landed since the cache pass
                raise BatcherClosed("batcher is closed")
            self._pending.append(req)
            self._m_queue_depth.set(len(self._pending))
            self._cond.notify_all()
        req.event.wait()
        if req.error is not None:
            raise req.error
        with self._lock:
            for j, i in enumerate(miss_idx):
                results[i] = req.out[j]
                self._cache[miss_pos[j]] = req.out[j]
                self._cache.move_to_end(miss_pos[j])
            while len(self._cache) > self._cache_size:
                self._cache.popitem(last=False)
        return results

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._worker.join(timeout=5)

    def metrics(self) -> dict:
        """Snapshot of the coalescing/cache counters (+ derived means)."""
        with self._lock:
            c = dict(self.counters)
        batches = max(c["batches"], 1)
        lookups = c["cache_hits"] + c["cache_misses"]
        return {
            **c,
            "mean_batch_size": c["batched_queries"] / batches,
            "mean_batch_secs": c["batch_secs_total"] / batches,
            "cache_hit_rate": c["cache_hits"] / max(lookups, 1),
        }

    # ---------------------------------------------------------------- worker

    def _drain_window(self) -> list[_Request]:
        """Wait for work, then collect what arrives in the window — up to
        max_batch queries; the remainder stays queued and the worker loops
        straight back into the next flush without waiting."""
        with self._cond:
            while not self._pending and not self._closed:
                self._cond.wait()
            if not self._pending:
                return []
            deadline = time.monotonic() + self.window
            while not self._closed:
                if (
                    sum(r.states.shape[0] for r in self._pending)
                    >= self.max_batch
                ):
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            batch: list[_Request] = []
            total = 0
            while self._pending:
                n = self._pending[0].states.shape[0]
                if batch and total + n > self.max_batch:
                    break
                batch.append(self._pending.pop(0))
                total += n
            self._m_queue_depth.set(len(self._pending))
            return batch

    def _loop(self) -> None:
        while True:
            batch = self._drain_window()
            if not batch:
                with self._lock:
                    if self._closed and not self._pending:
                        return
                continue
            t0 = time.perf_counter()
            try:
                # Everything that can fail lives inside this try: an escape
                # would kill the worker and leave every parked submitter
                # (and all future ones) blocked on events nobody will set.
                states = np.concatenate([r.states for r in batch])
                values, rem, found, best = self.reader.lookup_best(states)
            except Exception as e:  # noqa: BLE001 - must unblock submitters
                for r in batch:
                    r.error = e
                    r.event.set()
                continue
            secs = time.perf_counter() - t0
            sentinel = int(self.reader.game.sentinel)
            with self._lock:
                self.counters["batches"] += 1
                self.counters["batched_queries"] += int(states.shape[0])
                self.counters["max_batch_size"] = max(
                    self.counters["max_batch_size"], int(states.shape[0])
                )
                self.counters["batch_secs_total"] += secs
            self._m_batch_size.observe(int(states.shape[0]))
            self._m_batch_secs.observe(secs)
            if self.logger is not None:
                self.logger.log(
                    {
                        "phase": "serve_batch",
                        "batch_size": int(states.shape[0]),
                        "requests": len(batch),
                        "secs": secs,
                    }
                )
            off = 0
            for r in batch:
                n = r.states.shape[0]
                r.out = [
                    (
                        int(values[off + j]),
                        int(rem[off + j]),
                        bool(found[off + j]),
                        None
                        if int(best[off + j]) == sentinel
                        else int(best[off + j]),
                    )
                    for j in range(n)
                ]
                off += n
                r.event.set()
