"""CLI: the rebuild of the reference's solver_launcher.py (SURVEY.md §2.2, §3.1).

The reference is launched as
    mpirun -np N python solver_launcher.py games/tictactoe.py
and prints the solved value + remoteness of the initial position (plus elapsed
time) from rank 0. Here there is no mpirun: device parallelism comes from the
JAX mesh, so the same solve is
    python solve_launcher.py tictactoe
    python solve_launcher.py connect4:w=5,h=4 --devices 4
    python solve_launcher.py path/to/ref_style_game.py      (compat path)

A file path argument is the reference's dynamic game-module import: the module
is loaded, validated for the 4-function API, and solved unmodified via the
compat layer. Built-in tensorized games are selected by spec string
(gamesmanmpi_tpu.games.get_game).

Three serving subcommands ride in front of the flat solve CLI (which is
unchanged — any first argument that is not a subcommand name parses exactly
as before):

    python -m gamesmanmpi_tpu.cli export-db GAME --out DB [--from-checkpoint D]
    python -m gamesmanmpi_tpu.cli serve DB [--port N] [--batch-window-ms MS]
    python -m gamesmanmpi_tpu.cli query DB POS [POS ...]

export-db builds the immutable solved-position database (db/) from a fresh
solve (streamed level-by-level through the engine's level_sink hook) or from
an existing --checkpoint-dir; serve answers batched POST /query over it
(serve/); query probes it offline. docs/SERVING.md is the full spec.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from gamesmanmpi_tpu.utils.env import env_float, env_opt, env_str


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="solve_launcher",
        description="Strongly solve a two-player abstract game (value + remoteness).",
    )
    p.add_argument(
        "game",
        nargs="?",
        default=None,
        help="built-in game spec (e.g. tictactoe, connect4:w=5,h=4, nim:heaps=3-4-5), "
        "a path to a declarative GameSpec .json file (docs/GAMEDSL.md), "
        "or a path to a reference-style game module file; omit when "
        "--spec is given",
    )
    p.add_argument(
        "--spec",
        default=None,
        metavar="SPEC.json",
        help="declarative GameSpec file compiled by gamedsl "
        "(docs/GAMEDSL.md) — equivalent to passing the path as GAME",
    )
    p.add_argument(
        "--devices",
        type=int,
        default=1,
        help="number of devices to shard the solve over (1 = single device)",
    )
    p.add_argument(
        "--paranoid",
        action="store_true",
        help="enable internal consistency re-verification (SURVEY.md §5.2)",
    )
    p.add_argument(
        "--jsonl",
        default=None,
        help="write per-level structured metrics to this JSONL file (§5.5)",
    )
    p.add_argument(
        "-v",
        "--verbose",
        action="store_true",
        help="print per-level progress lines to stderr (the reference's "
        "debug-print flag analog, SURVEY.md §5.5)",
    )
    p.add_argument(
        "--checkpoint-dir",
        default=None,
        help="save per-level solved tables for restart-from-level (§5.4)",
    )
    p.add_argument(
        "--profile-dir",
        default=None,
        help="capture a jax.profiler trace of the solve into this dir (§5.1)",
    )
    # Observability layer (docs/OBSERVABILITY.md): phase spans + metrics
    # registry, alongside the low-level JAX trace above.
    p.add_argument(
        "--trace-events",
        default=None,
        metavar="OUT.json",
        help="dump Chrome trace-event JSON of the solver's phase spans "
        "(forward/dedup/backward/checkpoint/db_export) — loads in "
        "chrome://tracing / Perfetto",
    )
    p.add_argument(
        "--metrics-out",
        default=None,
        metavar="OUT.json",
        help="dump the process metrics-registry snapshot (span histograms, "
        "solve counters) as JSON when the solve finishes",
    )
    p.add_argument(
        "--heartbeat-secs",
        type=float,
        default=None,
        metavar="S",
        help="emit a heartbeat record (phase/level progress, RSS, device "
        "memory) every S seconds so long solves are diagnosable "
        "mid-flight (env GAMESMAN_HEARTBEAT_SECS; 0 = off)",
    )
    p.add_argument(
        "--status-port",
        type=int,
        default=None,
        metavar="PORT",
        help="serve read-only live solve status on this port: GET "
        "/status (phase/level, positions solved, per-level progress "
        "model with ETA, fleet-merged per-rank view on rank 0) and GET "
        "/metrics (Prometheus text). 0 = ephemeral port (published via "
        "GAMESMAN_STATUS_ADDR_FILE); env GAMESMAN_STATUS_PORT; unset = "
        "off (docs/OBSERVABILITY.md \"Live status\")",
    )
    p.add_argument(
        "--watchdog-secs",
        type=float,
        default=None,
        metavar="S",
        help="abort (exit 124, diagnostics dumped, checkpoint prefix "
        "intact) when a level stalls longer than "
        "max(S, GAMESMAN_WATCHDOG_FACTOR x slowest recent level) — "
        "turns a wedged backend into a resumable death (env "
        "GAMESMAN_WATCHDOG_SECS; 0 = off)",
    )
    p.add_argument(
        "--table-out",
        default=None,
        help="dump the full solved table as .npz (packed cells per level)",
    )
    p.add_argument(
        "--no-tables",
        action="store_true",
        help="big-run mode: materialize only the root level's table on host "
        "(positions are still counted; combine with --checkpoint-dir to "
        "persist full tables level-by-level instead)",
    )
    p.add_argument(
        "--engine",
        choices=("auto", "classic", "dense", "hybrid"),
        default="auto",
        help="solver engine: 'classic' = level-BFS discovery (all games); "
        "'dense' = class-partitioned perfect-indexing engine (Connect-4 "
        "family, single device, sym=0 — no sorts, 1 byte/position); "
        "'hybrid' = dense below --hybrid-cutover, BFS above (giant "
        "boards, same eligibility as dense); 'auto' picks dense when "
        "eligible",
    )
    p.add_argument(
        "--hybrid-cutover",
        type=int,
        default=None,
        metavar="K",
        help="last dense level of --engine hybrid (default: 2/3 of the "
        "board's cells; see solve/hybrid.py)",
    )
    p.add_argument(
        "--query",
        action="append",
        default=None,
        metavar="POS",
        help="after solving, also print the value/remoteness of this packed "
        "position (decimal or 0x-hex; repeatable). Queries are "
        "canonicalized, so symmetry-reduced solves answer for any class "
        "member",
    )
    # Capacity knobs (CLI spellings of the GAMESMAN_* env vars; the flag
    # wins when both are set). docs/ARCHITECTURE.md capacity plan.
    p.add_argument(
        "--backward-block",
        type=int,
        default=None,
        metavar="POSITIONS",
        help="resolve levels in column blocks of this many positions "
        "(bounds backward temporaries; 0 = never block; env "
        "GAMESMAN_BACKWARD_BLOCK)",
    )
    p.add_argument(
        "--window-block",
        type=int,
        default=None,
        metavar="POSITIONS",
        help="sharded: spill window levels wider than this (per shard) to "
        "host and stream them back through HBM in blocks (0 = never "
        "spill; env GAMESMAN_WINDOW_BLOCK)",
    )
    p.add_argument(
        "--device-store-mb",
        type=int,
        default=None,
        metavar="MB",
        help="device-resident budget for discovered levels + provenance "
        "between forward and backward; excess spills to host (env "
        "GAMESMAN_DEVICE_STORE_MB)",
    )
    p.add_argument(
        "--backward",
        choices=("edges", "lookup"),
        default=None,
        help="sharded backward strategy: 'edges' (default) resolves each "
        "level from the forward pass's stored edge indices — gathers + "
        "collectives, no search, no re-expansion — falling back to "
        "'lookup' per level where edges are missing (pre-edge "
        "checkpoints, multi-jump games); 'lookup' forces the owner-"
        "routed search join everywhere (env GAMESMAN_BACKWARD)",
    )
    # Multi-host bring-up (SURVEY.md §5.8 control plane): one process per
    # host, jax.distributed over DCN, mesh over all addressable devices.
    # docs/ARCHITECTURE.md "Multi-host launch" shows a v4-32 example.
    p.add_argument(
        "--coordinator",
        default=None,
        help="coordinator address host:port for multi-host runs "
        "(jax.distributed.initialize over DCN)",
    )
    p.add_argument(
        "--num-processes",
        type=int,
        default=None,
        help="total number of processes in the multi-host run",
    )
    p.add_argument(
        "--process-id",
        type=int,
        default=None,
        help="this process's index in [0, num-processes)",
    )
    return p


def _lookup_checkpoint(game, checkpointer, state):
    """(value, remoteness) of one position from a checkpoint directory, or
    None. Dense directories (manifest "dense_levels") locate the cell by
    perfect index in one dense_NNNN.npz; classic directories canonicalize
    and level the query exactly like the engine, then read one
    (level, shard) npz (LevelCheckpointer.lookup_level_state).

    Never raises: the solve already succeeded, so a missing shard file (a
    multi-host run's remote shard, a torn write) degrades this one query
    to unanswerable — it must not abort the report or the remaining
    queries."""
    try:
        dense_levels = checkpointer.load_manifest().get("dense_levels")
        if dense_levels:
            from gamesmanmpi_tpu.solve.dense import tables_for

            t = tables_for(game.width, game.height, game.connect)
            level, row, rank = t.locate(int(state))
            if (level not in dense_levels
                    or t.current_player_has_line(level, row, rank)):
                # Never solved (interrupted run) / fabricated class (the
                # player to move already has a line: its cell is a
                # placeholder, same refusal as DenseSolveResult.lookup).
                return None
            cache = getattr(checkpointer, "_dense_query_cache", None)
            if cache is not None and cache[0] == level:
                cells = cache[1]
            else:
                # Memoize the last-loaded level: batched queries cluster,
                # and at big-run scale one level file is a large read.
                cells = checkpointer.load_dense_level(level)
                checkpointer._dense_query_cache = (level, cells)
            cell = int(cells[row * t.class_size[level] + rank])
            return cell & 3, cell >> 2
        from gamesmanmpi_tpu.solve.engine import canonical_scalar

        canon, level = canonical_scalar(game, state)
        return checkpointer.lookup_level_state(level, int(canon))
    except Exception as e:  # noqa: BLE001 - per-query degradation
        print(f"warning: checkpoint query failed ({e!r})", file=sys.stderr)
        return None


def _report(result, devices: int, elapsed: float, args) -> None:
    """The rank-0 output block (SURVEY.md §2.1.4), shared by every engine
    path: value + remoteness + elapsed, optional table dump."""
    from gamesmanmpi_tpu.core.values import value_name

    print(f"game: {result.game.name}")
    print(f"devices: {devices}")
    print(f"positions: {result.num_positions}")
    print(f"value: {value_name(result.value)}")
    print(f"remoteness: {result.remoteness}")
    print(f"elapsed: {elapsed:.3f}s")
    print(
        f"throughput: {result.stats['positions_per_sec']:.0f} positions/sec"
    )
    if args.table_out:
        from gamesmanmpi_tpu.utils.checkpoint import save_result_npz

        save_result_npz(args.table_out, result)
        print(f"table written: {args.table_out}")
    ckpt = None
    if args.query and getattr(args, "checkpoint_dir", None):
        from gamesmanmpi_tpu.utils import LevelCheckpointer

        ckpt = LevelCheckpointer(args.checkpoint_dir)
    for q in args.query or ():
        # The reference prints only the root; point queries answer for any
        # reachable position from the solved table (SolveResult.lookup
        # canonicalizes, so sym=1 tables answer for all class members). In
        # big-run mode (--no-tables) the in-memory result holds only the
        # root level, but a --checkpoint-dir run has every solved cell on
        # disk — serve the query from the per-(level, shard) npz instead
        # of declaring it unreachable (SURVEY.md §1's by-product contract).
        try:
            state = int(q, 0)
            try:
                value, rem = result.lookup(state)
            except KeyError:
                hit = (
                    _lookup_checkpoint(result.game, ckpt, state)
                    if ckpt is not None else None
                )
                if hit is None:
                    print(f"query {q}: not reachable")
                    continue
                value, rem = hit
            print(f"query {q}: value={value_name(value)} remoteness={rem}")
        except (ValueError, OverflowError) as e:
            # Bad literal / doesn't fit the game's state dtype — report per
            # query; the solve itself already succeeded.
            print(f"query {q}: invalid position ({e})")


def _dump_flightrec(reason: str) -> None:
    """Leave the flight recorder's post-mortem (recent spans/levels/
    retries/faults + in-flight spans) on every abnormal solve exit —
    the file lands in GAMESMAN_FLIGHTREC_DIR, which main() defaults to
    the checkpoint directory. Never raises: the post-mortem writer must
    not add its own failure to the one it records."""
    from gamesmanmpi_tpu.obs import flightrec

    flightrec.dump(reason)


#: Serving subcommands dispatched ahead of the flat solve parser. A game
#: spec can never collide: specs are lowercase single tokens already taken
#: by the registry, and module paths contain a '.' or '/'.
_DB_COMMANDS = ("export-db", "serve", "query", "registry")


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # `gamesman solve ...` reads symmetrically with export-db/serve/query;
    # the flat grammar (game spec first) stays the default. No game is
    # named "solve", so the token is unambiguous.
    if argv and argv[0] == "solve":
        argv = argv[1:]
    if argv and argv[0] in _DB_COMMANDS:
        return _db_main(argv)
    args = build_parser().parse_args(argv)
    if args.spec is not None:
        if args.game is not None:
            print(
                "error: pass either GAME or --spec, not both",
                file=sys.stderr,
            )
            return 2
        args.game = args.spec
    elif args.game is None:
        print(
            "error: a game is required: GAME or --spec SPEC.json",
            file=sys.stderr,
        )
        return 2
    # Capacity flags are CLI spellings of the env knobs the engines read at
    # construction; set them before any solver is built, and restore on
    # exit so programmatic main() calls don't leak config to the next one.
    saved_env = {}
    # Flight-recorder dumps land next to the checkpoints by default: a
    # checkpointed solve's post-mortems (crash, watchdog, preemption
    # deadline, level-boundary snapshots) belong with the tree they
    # describe. An explicit GAMESMAN_FLIGHTREC_DIR wins.
    flightrec_dir = (
        args.checkpoint_dir
        if args.checkpoint_dir and not env_opt("GAMESMAN_FLIGHTREC_DIR")
        else None
    )
    for flag, env in (
        (args.backward_block, "GAMESMAN_BACKWARD_BLOCK"),
        (args.window_block, "GAMESMAN_WINDOW_BLOCK"),
        (args.device_store_mb, "GAMESMAN_DEVICE_STORE_MB"),
        (args.heartbeat_secs, "GAMESMAN_HEARTBEAT_SECS"),
        (args.watchdog_secs, "GAMESMAN_WATCHDOG_SECS"),
        (args.backward, "GAMESMAN_BACKWARD"),
        (args.status_port, "GAMESMAN_STATUS_PORT"),
        (flightrec_dir, "GAMESMAN_FLIGHTREC_DIR"),
    ):
        if flag is not None:
            saved_env[env] = env_opt(env)
            os.environ[env] = str(flag)
    try:
        return _main(args)
    finally:
        for env, old in saved_env.items():
            if old is None:
                os.environ.pop(env, None)
            else:
                os.environ[env] = old


def _maybe_probe_backend() -> bool:
    """Bench-style fail-fast platform probe (VERDICT r5).

    The bare CLI used to wedge >300 s at first backend touch when the
    axon relay was dead — no error, no output. When the backend about to
    initialize is a non-CPU plugin and nothing has pinned the platform,
    probe it in a throwaway subprocess under a deadline
    (GAMESMAN_PROBE_TIMEOUT, default 120 s) and fail with a clear message
    instead. Returns False when the backend is dead (caller exits).
    Skipped when: probing is disabled (GAMESMAN_PROBE=0), the platform is
    explicitly pinned (GAMESMAN_PLATFORM — the user chose), backends are
    already initialized in this process (too late to help), or the first
    platform to initialize is the CPU (cannot wedge on a relay).
    """
    if env_str("GAMESMAN_PROBE", "auto") in ("0", "off", "false"):
        return True
    if env_opt("GAMESMAN_PLATFORM"):
        return True
    import jax
    from jax._src import xla_bridge

    if xla_bridge.backends_are_initialized():
        return True
    # Probe only when a non-CPU platform is explicitly first in line (the
    # plugin-pinned container's sitecustomize sets jax_platforms=
    # "axon,cpu"): plain auto-detect environments and CPU pins cannot
    # wedge on a dead relay, and the probe would cost them a jax-import
    # subprocess per solve for nothing.
    first_cfg = str(getattr(jax.config, "jax_platforms", None) or "") \
        .split(",")[0].strip().lower()
    first_env = env_str("JAX_PLATFORMS", "") \
        .split(",")[0].strip().lower()
    if first_cfg in ("", "cpu") and first_env in ("", "cpu"):
        return True
    from gamesmanmpi_tpu.utils.platform import probe_backend

    timeout = env_float("GAMESMAN_PROBE_TIMEOUT", 120.0)
    if probe_backend(timeout) is not None:
        return True
    print(
        f"error: accelerator backend failed to initialize within "
        f"{timeout:.0f}s (dead relay?). Set GAMESMAN_PLATFORM=cpu to "
        "solve on the CPU, GAMESMAN_PROBE_TIMEOUT to wait longer, or "
        "GAMESMAN_PROBE=0 to skip this check.",
        file=sys.stderr,
    )
    return False


def _main(args) -> int:
    from gamesmanmpi_tpu.utils.platform import apply_platform_env

    # Honor GAMESMAN_PLATFORM=cpu|tpu|axon (and GAMESMAN_FAKE_DEVICES) before
    # any backend init; --devices N on a faked-CPU run needs >= N devices.
    apply_platform_env(default_fake_devices=max(args.devices, 1))
    if not _maybe_probe_backend():
        return 3
    # Multi-host identity: the flags win, the env (GAMESMAN_COORDINATOR /
    # GAMESMAN_NUM_PROCESSES / GAMESMAN_PROCESS_ID — how tools/
    # launch_multihost.py configures its children) fills the gaps.
    coordinator = args.coordinator or env_opt("GAMESMAN_COORDINATOR")
    if coordinator:
        # Must run before the first backend touch so every process joins the
        # same PJRT world; the mesh then spans all addressable devices.
        # Either spelling needs the full identity triple: without it,
        # init_distributed's env_int defaults (1 process, rank 0) would
        # quietly give every host its own one-process world all claiming
        # rank 0 — an obscure bind/handshake failure instead of this.
        if (args.num_processes is None
                and env_opt("GAMESMAN_NUM_PROCESSES") is None) or (
                args.process_id is None
                and env_opt("GAMESMAN_PROCESS_ID") is None):
            print(
                "error: a coordinator (--coordinator / "
                "GAMESMAN_COORDINATOR) requires --num-processes and "
                "--process-id (or their GAMESMAN_* env twins)",
                file=sys.stderr,
            )
            return 2
        from gamesmanmpi_tpu.parallel.mesh import init_distributed

        init_distributed(
            coordinator_address=coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )
        _configure_rank_env(coordinator, args)
    t0 = time.perf_counter()

    logger = _build_logger(args)
    if logger is not None and coordinator:
        import jax

        if jax.process_count() > 1:
            from gamesmanmpi_tpu.utils.metrics import RankLogger

            logger = RankLogger(logger, jax.process_index())
    # Loggers are context managers: the JSONL handle closes even when a
    # solve aborts mid-level (partial metrics beat a lost buffered tail).
    # The obs scope nests inside so both artifacts (--trace-events,
    # --metrics-out) are written even when the solve itself raises.
    with _logger_scope(logger):
        with _obs_scope(args):
            return _solve_main(args, t0, logger)


def _rank_path(path: str, rank: int) -> str:
    """``out.jsonl`` -> ``out.rank0.jsonl``: per-rank artifact names.

    N processes handed one ``--jsonl``/``--metrics-out`` path must not
    race each other onto the same file; rank-qualified siblings keep
    every rank's stream intact and tools/obs_report.py merges them.
    """
    root, ext = os.path.splitext(path)
    return f"{root}.rank{rank}{ext}"


def _configure_rank_env(coordinator: str, args) -> None:
    """Post-initialize rank plumbing for a multi-process run.

    * ``GAMESMAN_COORD_ADDR`` (the retry-consensus coordinator,
      resilience/coordination.py) defaults to the jax coordinator's host
      at port+1 so a bare two-flag launch gets coordinated retry for
      free; an explicit env value wins.
    * Every ``gamesman_*`` series and JSONL record this process emits
      gains a ``rank`` label (docs/OBSERVABILITY.md) — without it the
      per-rank metrics of an N-process run are indistinguishable.
    * File artifacts (--jsonl/--metrics-out/--trace-events/--table-out)
      become rank-qualified siblings so ranks never race onto one path.
    """
    import jax

    if jax.process_count() <= 1:
        return
    rank = jax.process_index()
    for field in ("jsonl", "metrics_out", "trace_events", "table_out"):
        val = getattr(args, field, None)
        if val:
            setattr(args, field, _rank_path(val, rank))
    if not env_opt("GAMESMAN_COORD_ADDR"):
        host, _, port = coordinator.rpartition(":")
        try:
            os.environ["GAMESMAN_COORD_ADDR"] = (
                f"{host or '127.0.0.1'}:{int(port) + 1}"
            )
        except ValueError:
            pass  # unparsable port: coordination stays unconfigured
    from gamesmanmpi_tpu.obs import default_registry

    default_registry().set_constant_labels(rank=str(jax.process_index()))


def _solve_main(args, t0: float, logger) -> int:
    import pathlib

    from gamesmanmpi_tpu.core.values import value_name
    from gamesmanmpi_tpu.utils.profiling import maybe_profile

    checkpointer = None
    if args.checkpoint_dir:
        from gamesmanmpi_tpu.utils.checkpoint import LevelCheckpointer

        checkpointer = LevelCheckpointer(args.checkpoint_dir)

    # A .json file is a declarative GameSpec, not a compat module: it
    # compiles through get_game below and drives the real engine.
    if (pathlib.Path(args.game).is_file()
            and not args.game.lower().endswith(".json")):
        if args.engine in ("dense", "hybrid"):
            # The validation below never runs on the compat path; without
            # this, --engine dense/hybrid would be silently ignored here.
            print(
                f"error: --engine {args.engine} applies to the built-in "
                "Connect-4 family, not compat game modules",
                file=sys.stderr,
            )
            return 2
        # Reference-style plugin module: runs unmodified (compat path).
        from gamesmanmpi_tpu.compat import load_game_module, solve_module

        try:
            module = load_game_module(args.game)
        except (AttributeError, ImportError) as e:
            # Module validation, solver_launcher.py-style (SURVEY.md §3.1).
            print(f"error: invalid game module {args.game!r}: {e}", file=sys.stderr)
            return 2
        engine_capable = hasattr(module, "level_of")
        for flag, name in (
            (args.devices > 1, "--devices"),
            (args.paranoid, "--paranoid"),
            (args.checkpoint_dir, "--checkpoint-dir"),
            (args.backward_block is not None, "--backward-block"),
            (args.window_block is not None, "--window-block"),
            (args.device_store_mb is not None, "--device-store-mb"),
        ):
            if flag and not engine_capable:
                print(
                    f"warning: {name} needs the tensorized compat path and "
                    "is ignored on the host solve; define level_of(pos) in "
                    "the module (max_moves is auto-derived) to drive the "
                    "TPU engine",
                    file=sys.stderr,
                )
        if engine_capable:
            # Modules that declare a topological level function are lifted
            # onto the batched protocol and driven by the real engine —
            # all solver flags work, including --devices (host callbacks
            # run per shard-batch). max_moves is taken from the module or
            # auto-derived with grow-and-retry (compat.solve_module_jitted).
            from gamesmanmpi_tpu.compat import solve_module_jitted

            with maybe_profile(args.profile_dir):
                result = solve_module_jitted(
                    module,
                    devices=args.devices,
                    paranoid=args.paranoid,
                    logger=logger,
                    checkpointer=checkpointer,
                    store_tables=not args.no_tables,
                )
            _report(result, args.devices, time.perf_counter() - t0, args)
            return 0
        else:
            with maybe_profile(args.profile_dir):
                value, remoteness, table = solve_module(module)
            elapsed = time.perf_counter() - t0
            print(f"game: {pathlib.Path(args.game).stem} (compat module)")
            print(f"positions: {len(table)}")
            print(f"value: {value_name(value)}")
            print(f"remoteness: {remoteness}")
            print(f"elapsed: {elapsed:.3f}s")
            if args.table_out:
                from gamesmanmpi_tpu.utils.checkpoint import save_table_npz

                save_table_npz(args.table_out, table)
                print(f"table written: {args.table_out}")
            for q in args.query or ():
                try:
                    hit = table.get(int(q, 0))
                except ValueError as e:
                    print(f"query {q}: invalid position ({e})")
                    continue
                if hit is None:
                    print(f"query {q}: not reachable")
                else:
                    print(
                        f"query {q}: value={value_name(hit[0])} "
                        f"remoteness={hit[1]}"
                    )
            if logger is not None:
                logger.log(
                    {
                        "phase": "done",
                        "game": pathlib.Path(args.game).stem,
                        "compat": True,
                        "positions": len(table),
                        "secs_total": elapsed,
                    }
                )
            return 0
    else:
        from gamesmanmpi_tpu.games import get_game

        try:
            game = get_game(args.game)
        except (KeyError, ValueError) as e:
            print(f"error: {e.args[0] if e.args else e}", file=sys.stderr)
            print(
                "known games: tictactoe[:m=,n=,k=,sym=], "
                "connect4[:w=,h=,k=,sym=], subtract[:total=,moves=,misere=], "
                "nim[:heaps=,misere=], chomp[:w=,h=] — or a path to a "
                "reference-style game module file (sym=1 enables "
                "board-symmetry reduction)",
                file=sys.stderr,
            )
            return 2
    from gamesmanmpi_tpu.games.connect4 import Connect4

    family_base = (
        isinstance(game, Connect4) and not game.sym
        and not args.paranoid and not args.table_out
    )
    family_ok = family_base and not args.checkpoint_dir
    # devices > 1 partitions the dense level kernels over the mesh by rank
    # (DenseSolver devices=N); the hybrid's dense region stays
    # single-device while its BFS region shards. An EXPLICIT --engine
    # dense also accepts --checkpoint-dir (per-level cell restart); auto
    # keeps routing checkpointed runs to the classic engine, whose
    # checkpoints don't pay a per-level device download.
    dense_eligible = family_base if args.engine == "dense" else family_ok
    if args.engine == "dense" and not dense_eligible:
        print(
            "error: --engine dense needs a Connect-4-family game "
            "with sym=0 and no --paranoid/--table-out "
            "(those live in the classic engine)",
            file=sys.stderr,
        )
        return 2
    # The hybrid accepts sym=1 (r5): its BFS region keeps the mirror
    # reduction and the dense region runs a sym-free twin — see
    # solve/hybrid.py.
    hybrid_ok = (
        isinstance(game, Connect4)
        and not args.paranoid and not args.table_out
        and not args.checkpoint_dir
    )
    if args.engine == "hybrid" and not hybrid_ok:
        print(
            "error: --engine hybrid needs a Connect-4-family game "
            "and no --checkpoint-dir/--paranoid/--table-out "
            "(those live in the classic engine)",
            file=sys.stderr,
        )
        return 2
    if args.engine == "auto" and dense_eligible:
        # Same platform policy as bench.py: the dense engine's rank loops
        # are shaped for the TPU's vector units; on CPU the classic engine
        # measured faster. An explicit --engine dense still forces it.
        import jax

        if jax.devices()[0].platform == "cpu":
            dense_eligible = False
        if args.devices > 1:
            # auto + a mesh keeps the OLD routing (owner-sharded BFS,
            # which shards MEMORY): the mesh dense engine re-replicates
            # each level, so it only fits boards whose peak level fits one
            # device — a policy the user opts into with --engine dense.
            dense_eligible = False
    if args.engine == "hybrid":
        from gamesmanmpi_tpu.solve.hybrid import HybridSolver

        try:
            solver = HybridSolver(
                game,
                cutover=args.hybrid_cutover,
                store_tables=not args.no_tables,
                logger=logger,
                devices=args.devices,
            )
        except ValueError as e:
            # Bad --hybrid-cutover / GAMESMAN_HYBRID_CUTOVER: CLI misuse
            # exits 2 with a message, like every other argument error.
            print(f"error: {e}", file=sys.stderr)
            return 2
    elif args.engine != "classic" and dense_eligible:
        from gamesmanmpi_tpu.solve.dense import DenseSolver

        try:
            solver = DenseSolver(
                game,
                store_tables=not args.no_tables,
                logger=logger,
                devices=args.devices,
                checkpointer=checkpointer,
            )
        except ValueError as e:  # bad --devices: CLI misuse exits 2
            print(f"error: {e}", file=sys.stderr)
            return 2
    elif args.devices > 1:
        from gamesmanmpi_tpu.parallel import ShardedSolver

        solver = ShardedSolver(
            game,
            num_shards=args.devices,
            paranoid=args.paranoid,
            logger=logger,
            checkpointer=checkpointer,
            store_tables=not args.no_tables,
        )
    else:
        from gamesmanmpi_tpu.solve import Solver

        solver = Solver(
            game,
            paranoid=args.paranoid,
            logger=logger,
            checkpointer=checkpointer,
            store_tables=not args.no_tables,
        )
    from gamesmanmpi_tpu.resilience.coordination import CoordinatedAbort
    from gamesmanmpi_tpu.resilience.preempt import (
        GRACE_EXIT_CODE,
        PreemptionRequested,
        install_grace_handler,
    )

    # Preemption grace (docs/DISTRIBUTED.md "Campaigns"): SIGTERM/SIGUSR1
    # drain the solve to the next level boundary — everything complete is
    # sealed by the solve's own teardown — and exit 75 (resumable). Only
    # a CHECKPOINTED solve gets the handlers: exit 75 promises "restart
    # me against the same checkpoint directory", and a solve with
    # nothing to seal should keep dying promptly on SIGTERM (systemd /
    # k8s stop) instead of computing to the next boundary for a lie.
    restore_grace = (
        install_grace_handler() if checkpointer is not None
        else (lambda: None)
    )
    try:
        with maybe_profile(args.profile_dir):
            result = solver.solve()
    except PreemptionRequested as e:
        _dump_flightrec("preempted")
        progress = getattr(solver, "progress", {})
        print(f"preempted: {e}\nprogress: {progress}", file=sys.stderr)
        sys.stderr.flush()
        if logger is not None:
            logger.log({"phase": "preempted", "detail": str(e)[:200],
                        **{("in_phase" if k == "phase" else k): v
                           for k, v in progress.items()
                           if isinstance(v, (int, str, float))}})
            logger.close()
        import jax

        if jax.process_count() > 1:
            # Same contract as the coordinated abort below: a clean exit
            # would block in jax's distributed-shutdown barrier when a
            # peer is already gone.
            os._exit(GRACE_EXIT_CODE)
        return GRACE_EXIT_CODE
    except MemoryError as e:
        _dump_flightrec("oom")
        # Host allocator exhaustion — the guard's HostMemoryExceeded at
        # a level boundary, or a real MemoryError mid-level. Either way
        # the sealed prefix is intact (atomic payload writes, atomic
        # seals) and the death must CLASSIFY: the "out of memory" /
        # RESOURCE_EXHAUSTED diagnostics below are what the campaign's
        # log-tail classifier reads as `oom` before answering with
        # geometry escalation (docs/DISTRIBUTED.md "Elastic resume").
        progress = getattr(solver, "progress", {})
        print(f"out of memory: {e}\nprogress: {progress}",
              file=sys.stderr)
        sys.stderr.flush()
        if logger is not None:
            logger.log({"phase": "oom", "error": str(e)[:200],
                        **{("in_phase" if k == "phase" else k): v
                           for k, v in progress.items()
                           if isinstance(v, (int, str, float))}})
            logger.close()
        import jax

        if jax.process_count() > 1:
            # Clean exit would block in jax's shutdown barrier while
            # peers are unwinding through the collective deadline.
            os._exit(1)
        return 1
    except CoordinatedAbort as e:
        import jax

        if jax.process_count() <= 1:
            # Multi-process ranks must NOT pay the dump's file I/O here:
            # jax's coordination service is already racing to SIGABRT
            # this process over the dead peer, and losing that race
            # turns the contractual exit 124 into -6 (observed in the
            # 2-process kill-resume chaos test). Their post-mortems come
            # from the level-boundary ring checkpoints and the
            # collective-deadline dump, which runs before the race
            # starts.
            _dump_flightrec("coordinated_abort")
        # The fleet agreed to stop (a peer died, diverged, or timed out):
        # same resumable-abort contract as the watchdog — diagnostics to
        # stderr, exit 124, checkpoint prefix intact, restart resumes.
        from gamesmanmpi_tpu.resilience.supervisor import WATCHDOG_EXIT_CODE

        progress = getattr(solver, "progress", {})
        print(f"coordinated abort: {e}\nprogress: {progress}",
              file=sys.stderr)
        sys.stderr.flush()
        if logger is not None:
            # progress carries its own "phase" (forward/backward) — keep
            # it under a different key or this record masquerades as a
            # normal level row in obs_report's table.
            logger.log({"phase": "coordinated_abort", "error": str(e)[:200],
                        **{("in_phase" if k == "phase" else k): v
                           for k, v in progress.items()
                           if isinstance(v, (int, str, float))}})
            logger.close()
        # os._exit, not return: a clean interpreter exit would run jax's
        # distributed-shutdown barrier, which blocks on the dead peer
        # until the coordination service SIGABRTs this process ~100 s
        # later — the watchdog contract is "gone within the deadline".
        os._exit(WATCHDOG_EXIT_CODE)
    except Exception:
        # The crash handler: any other death leaves the flight
        # recorder's post-mortem (last completed level, in-flight
        # spans) before the traceback propagates — exactly the cases
        # that used to need a rerun under instrumentation.
        _dump_flightrec("crash")
        raise
    finally:
        restore_grace()
    _report(result, args.devices, time.perf_counter() - t0, args)
    return 0


# --------------------------------------------------------------- serving CLI


def _db_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="gamesman-db",
        description="Solved-position database: export, serve, query "
        "(docs/SERVING.md).",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    pe = sub.add_parser(
        "export-db",
        help="build an immutable DB from a fresh solve or a checkpoint dir",
    )
    pe.add_argument("game", nargs="?", default=None,
                    help="built-in game spec, or a GameSpec .json file "
                    "(the manifest embeds the canonical spec document, so "
                    "the DB stays self-describing); omit when --spec is "
                    "given")
    pe.add_argument("--spec", default=None, metavar="SPEC.json",
                    help="declarative GameSpec file (docs/GAMEDSL.md) — "
                    "equivalent to passing the path as GAME")
    pe.add_argument("--out", required=True, help="DB output directory")
    pe.add_argument(
        "--from-checkpoint",
        default=None,
        metavar="DIR",
        help="convert an existing --checkpoint-dir instead of re-solving "
        "(classic-engine checkpoints, global or sharded)",
    )
    pe.add_argument("--overwrite", action="store_true",
                    help="replace an existing DB in --out")
    pe.add_argument(
        "--compress",
        action="store_true",
        default=None,
        help="write format v2: block-compressed levels (compress/ — "
        "entropy-coded keys/cells in independently-decodable blocks, "
        "per-block index in the manifest; the reader decodes only "
        "probed blocks through a hot-block cache). Default from "
        "GAMESMAN_DB_COMPRESS; v1 DBs stay readable forever",
    )
    pe.add_argument(
        "--book-plies", type=int, default=None, metavar="N",
        help="also build the resident opening book: every position "
        "within N plies of the initial position, scored through the "
        "finished DB and sealed as book.gmb in the manifest — the "
        "serving hot path answers book hits from RAM (docs/SERVING.md "
        "\"Hot path\"). Default from GAMESMAN_BOOK_PLIES; 0 = no book",
    )
    pe.add_argument("--jsonl", default=None,
                    help="write per-level export metrics to this JSONL file")
    pe.add_argument("-v", "--verbose", action="store_true",
                    help="print per-level progress to stderr")

    ps = sub.add_parser(
        "serve", help="serve POST /query, GET /healthz, GET /metrics"
    )
    ps.add_argument("db", nargs="?", default=None,
                    help="DB directory (from export-db); omit when "
                    "--fleet-manifest names the DBs")
    ps.add_argument("--host", default="127.0.0.1")
    ps.add_argument("--port", type=int, default=8947,
                    help="0 = ephemeral (the bound port is printed)")
    ps.add_argument(
        "--workers",
        type=int,
        default=None,
        metavar="N",
        help="fleet mode: supervise N worker processes sharing this "
        "port's accept queue (forked after DbReader open — mmap pages "
        "shared; heartbeat liveness, backoff restart, rolling reload; "
        "docs/SERVING.md). 0/unset = single in-process server (env "
        "GAMESMAN_SERVE_WORKERS)",
    )
    ps.add_argument(
        "--fleet-manifest",
        default=None,
        metavar="FILE",
        help="route multiple game DBs from one fleet manifest JSON "
        '({"version": 1, "games": [{"name": ..., "db": ...}]}); '
        "POST /query/<name> selects the game. Implies fleet mode; "
        "SIGHUP or POST /reload on the control port rolls the fleet "
        "onto a re-read manifest",
    )
    ps.add_argument(
        "--control-port",
        type=int,
        default=0,
        metavar="P",
        help="fleet mode: supervisor control endpoint port (fleet-level "
        "GET /healthz aggregating per-worker state, GET /metrics, "
        "POST /reload); 0 = ephemeral (printed in the banner)",
    )
    ps.add_argument(
        "--batch-window-ms",
        type=float,
        default=2.0,
        help="micro-batch coalescing window: concurrent requests arriving "
        "within it flush as ONE vectorized DB probe",
    )
    ps.add_argument("--cache-size", type=int, default=65536,
                    help="LRU hot-position cache entries (0 disables)")
    ps.add_argument(
        "--request-timeout-ms",
        type=float,
        default=None,
        help="per-request deadline on the batcher: a request not "
        "answered within it gets 503 + Retry-After instead of hanging "
        "(env GAMESMAN_REQUEST_TIMEOUT in seconds; 0 = no deadline)",
    )
    ps.add_argument(
        "--max-queue",
        type=int,
        default=1024,
        help="load shedding: refuse (503) new queries when this many "
        "requests are already parked in the coalescing queue",
    )
    ps.add_argument("--jsonl", default=None,
                    help="write per-batch serving metrics to this JSONL file")
    ps.add_argument(
        "--no-trace",
        action="store_true",
        help="disable query-path tracing + tail sampling (sets "
        "GAMESMAN_TRACE=0 for this process and, in fleet mode, every "
        "worker; GET /traces then serves an empty ring). Tracing is on "
        "by default — its off-path cost is one attribute fetch per span "
        "site (docs/OBSERVABILITY.md \"Query tracing & SLOs\")",
    )
    ps.add_argument("-v", "--verbose", action="store_true")

    pq = sub.add_parser("query", help="probe a DB offline (no server)")
    pq.add_argument("db", help="DB directory (from export-db)")
    pq.add_argument("positions", nargs="+",
                    help="packed positions, decimal or 0x-hex")

    pr = sub.add_parser(
        "registry",
        help="DB registry: publish epochs, serve the catalog, run "
        "solve-on-demand jobs (docs/SERVING.md)",
    )
    rsub = pr.add_subparsers(dest="registry_cmd", required=True)

    rserve = rsub.add_parser(
        "serve",
        help="serve the sha256-sealed catalog + blob streams over HTTP",
    )
    rserve.add_argument("--root", required=True,
                        help="registry root directory (catalog.json + dbs/)")
    rserve.add_argument("--host", default="127.0.0.1")
    rserve.add_argument("--port", type=int, default=0,
                        help="0 = ephemeral (the bound port is printed)")
    rserve.add_argument(
        "--jobs", action="store_true",
        help="accept POST /solve: unregistered-game queries become "
        "durable solve-on-demand jobs in <root>/jobs.jsonl",
    )

    rpub = rsub.add_parser(
        "publish",
        help="copy a DB into the registry and seal a new catalog epoch",
    )
    rpub.add_argument("db", help="DB directory (from export-db)")
    rpub.add_argument("--root", required=True, help="registry root directory")
    rpub.add_argument("--name", default=None,
                      help="catalog name (default: the DB's game name)")

    rrun = rsub.add_parser(
        "run-jobs",
        help="claim queued solve-on-demand jobs and drive each through "
        "campaign solve -> export-db -> publish",
    )
    rrun.add_argument("--root", required=True, help="registry root directory")
    rrun.add_argument("--work-dir", default=None,
                      help="checkpoint/export scratch (default <root>/work)")
    rrun.add_argument("--book-plies", type=int, default=0, metavar="N",
                      help="also build an N-ply opening book before publish")
    rrun.add_argument("--once", action="store_true",
                      help="run at most one job, then exit")
    return p


def _build_logger(args):
    """The --jsonl/--verbose TeeLogger every command shares (solve path
    and serving subcommands build it identically; one place to wire a
    new sink). None when neither flag is set."""
    from gamesmanmpi_tpu.utils.metrics import JsonlLogger, StdoutLogger, TeeLogger

    if not (args.jsonl or args.verbose):
        return None
    return TeeLogger(
        JsonlLogger(args.jsonl) if args.jsonl else None,
        StdoutLogger() if args.verbose else None,
    )


def _logger_scope(logger):
    """Context that closes `logger` on exit (loggers are context
    managers), or a no-op when logging is off."""
    import contextlib

    return logger if logger is not None else contextlib.nullcontext()


def _obs_scope(args):
    """--trace-events / --metrics-out lifetime: install a trace sink for
    the solve and write both artifacts on exit, aborts included (a
    partial trace of a dead solve is exactly when it is most wanted)."""
    import contextlib

    @contextlib.contextmanager
    def scope():
        from gamesmanmpi_tpu.obs import default_registry
        from gamesmanmpi_tpu.obs.tracing import trace_events_scope

        with trace_events_scope(getattr(args, "trace_events", None)):
            try:
                yield
            finally:
                out = getattr(args, "metrics_out", None)
                if out:
                    with open(out, "w") as fh:
                        json.dump(
                            default_registry().snapshot(), fh, indent=1
                        )

    return scope()


def _cmd_export_db(args) -> int:
    from gamesmanmpi_tpu.db import DbFormatError, DbWriter, export_checkpoint
    from gamesmanmpi_tpu.games import get_game
    from gamesmanmpi_tpu.utils.env import env_bool, env_int

    if args.spec is not None:
        if args.game is not None:
            print("error: pass either GAME or --spec, not both",
                  file=sys.stderr)
            return 2
        args.game = args.spec
    elif args.game is None:
        print("error: a game is required: GAME or --spec SPEC.json",
              file=sys.stderr)
        return 2
    try:
        game = get_game(args.game)
    except (KeyError, ValueError) as e:
        print(f"error: {e.args[0] if e.args else e}", file=sys.stderr)
        return 2
    compress = (
        env_bool("GAMESMAN_DB_COMPRESS", False)
        if args.compress is None
        else bool(args.compress)
    )
    t0 = time.time()
    logger = _build_logger(args)
    with _logger_scope(logger):
        try:
            if args.from_checkpoint:
                import pathlib

                from gamesmanmpi_tpu.utils.checkpoint import LevelCheckpointer

                if not pathlib.Path(args.from_checkpoint).is_dir():
                    # Check BEFORE LevelCheckpointer: its constructor
                    # mkdirs, so a typo'd path would be created on disk
                    # and misreported as "no completed levels".
                    print(
                        f"error: no such checkpoint directory: "
                        f"{args.from_checkpoint}",
                        file=sys.stderr,
                    )
                    return 2
                manifest = export_checkpoint(
                    LevelCheckpointer(args.from_checkpoint),
                    game,
                    args.game,
                    args.out,
                    overwrite=args.overwrite,
                    logger=logger,
                    compress=compress,
                )
            else:
                # Fresh solve, streamed: each level flows into the writer as
                # the backward pass resolves it (level_sink), so the export
                # never holds the full table in host memory.
                from gamesmanmpi_tpu.solve import Solver

                writer = DbWriter(
                    args.out, game, args.game, overwrite=args.overwrite,
                    compress=compress,
                )
                try:
                    Solver(
                        game,
                        logger=logger,
                        store_tables=False,
                        level_sink=writer.add_level_table,
                    ).solve()
                    manifest = writer.finalize()
                except BaseException:  # incl. Ctrl-C mid-solve: the old
                    writer.abort()     # DB keeps serving, staging is gone
                    raise
        except (DbFormatError, FileNotFoundError) as e:
            # FileNotFoundError: a torn checkpoint (manifest-listed shard
            # file deleted) — a usage-visible input problem, not a crash.
            print(f"error: {e}", file=sys.stderr)
            return 2
        book_plies = (
            env_int("GAMESMAN_BOOK_PLIES", 0)
            if args.book_plies is None else int(args.book_plies)
        )
        if book_plies > 0:
            # After finalize on purpose: the book is scored through a
            # real reader over the sealed DB, and sealing it rewrites
            # the manifest (new DB epoch) exactly once more.
            from gamesmanmpi_tpu.db.book import build_book

            manifest["book"] = build_book(args.out, book_plies, game=game)
    print(f"database written: {args.out}")
    print(f"game: {manifest['game']}")
    print(f"levels: {len(manifest['levels'])}")
    print(f"positions: {manifest['num_positions']}")
    book_rec = manifest.get("book")
    if book_rec:
        print(
            f"opening book: {book_rec['count']} entries to "
            f"{book_rec['plies']} plies"
        )
    comp = manifest.get("compression")
    if comp:
        ratio = comp["raw_bytes"] / max(comp["stored_bytes"], 1)
        print(
            f"compressed: {comp['stored_bytes']} bytes "
            f"({ratio:.2f}x vs raw cells)"
        )
    print(f"elapsed: {time.time() - t0:.3f}s")
    return 0


def _cmd_serve(args) -> int:
    import signal
    import threading

    from gamesmanmpi_tpu.db import DbFormatError, DbReader
    from gamesmanmpi_tpu.serve import QueryServer
    from gamesmanmpi_tpu.utils.env import env_int

    workers = (
        env_int("GAMESMAN_SERVE_WORKERS", 0)
        if args.workers is None else args.workers
    )
    if args.no_trace:
        # Env, not a constructor knob: workers (fork AND exec spawn
        # modes) inherit the environment, so one setting covers the
        # whole fleet and every TraceRing/SloEngine built under it.
        os.environ["GAMESMAN_TRACE"] = "0"
    if args.db is None and not args.fleet_manifest:
        print("error: serve needs a DB directory (or --fleet-manifest)",
              file=sys.stderr)
        return 2
    if workers > 0 or args.fleet_manifest:
        return _cmd_serve_fleet(args, max(1, workers))
    try:
        reader = DbReader(args.db)
    except DbFormatError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    logger = _build_logger(args)
    with _logger_scope(logger):
        try:
            server = QueryServer(
                reader,
                host=args.host,
                port=args.port,
                window=args.batch_window_ms / 1e3,
                cache_size=args.cache_size,
                max_queue=args.max_queue,
                request_timeout=(
                    args.request_timeout_ms / 1e3
                    if args.request_timeout_ms is not None else None
                ),
                logger=logger,
            )
        except OSError as e:  # port in use / unbindable host
            print(
                f"error: cannot bind {args.host}:{args.port} ({e})",
                file=sys.stderr,
            )
            return 2
        print(
            f"serving {reader.game.name} ({reader.num_positions} positions) "
            f"on http://{args.host}:{server.port} "
            f"(POST /query, GET /healthz, GET /metrics, GET /traces)",
            flush=True,  # a supervisor tailing the pipe needs the banner NOW
        )
        # Graceful shutdown: SIGINT/SIGTERM flip /healthz to "draining"
        # (new queries 503 so a load balancer fails over), let in-flight
        # requests and the coalescing batch finish, then tear down — the
        # JSONL logger closes via the surrounding scope either way. The
        # old path was a bare serve_forever(): SIGTERM tore down nothing.
        stop = threading.Event()

        def _on_signal(signum, frame):
            stop.set()

        previous = {}
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                previous[sig] = signal.signal(sig, _on_signal)
            except ValueError:  # not the main thread (programmatic use)
                pass
        server.start()
        try:
            stop.wait()
            print("draining: refusing new queries, flushing in-flight "
                  "batches", file=sys.stderr)
            server.begin_drain()
        finally:
            server.stop()
            for sig, handler in previous.items():
                signal.signal(sig, handler)
    return 0


def _cmd_serve_fleet(args, workers: int) -> int:
    """`serve --workers N [--fleet-manifest F]`: the supervised
    multi-worker fleet (docs/SERVING.md "Fleet serving").

    The supervisor binds the socket, opens every DB's reader, then
    forks the workers — this parent deliberately never touches a jax
    backend, which is what keeps the fork spawn path legal (see
    serve/supervisor.ServeSupervisor._use_fork). SIGTERM/SIGINT drain
    the whole fleet; SIGHUP rolls it onto a re-read manifest.
    """
    import signal

    from gamesmanmpi_tpu.db import DbFormatError
    from gamesmanmpi_tpu.serve import (
        ServeSupervisor,
        load_fleet_manifest,
        single_db_entries,
    )

    if args.fleet_manifest and args.db:
        print("error: pass a DB directory or --fleet-manifest, not both",
              file=sys.stderr)
        return 2
    logger = _build_logger(args)
    with _logger_scope(logger):
        try:
            entries = (
                load_fleet_manifest(args.fleet_manifest)
                if args.fleet_manifest else single_db_entries(args.db)
            )
            supervisor = ServeSupervisor(
                entries,
                workers=workers,
                host=args.host,
                port=args.port,
                control_port=args.control_port,
                manifest_path=args.fleet_manifest,
                server_config={
                    "window": args.batch_window_ms / 1e3,
                    "cache_size": args.cache_size,
                    "max_queue": args.max_queue,
                    "request_timeout": (
                        args.request_timeout_ms / 1e3
                        if args.request_timeout_ms is not None else None
                    ),
                },
                jsonl=args.jsonl,
                logger=logger,
            )
        except (ValueError, DbFormatError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        except OSError as e:  # port in use / unbindable host
            print(
                f"error: cannot bind {args.host}:{args.port} ({e})",
                file=sys.stderr,
            )
            return 2
        games = ", ".join(e.name or "default" for e in entries)
        print(
            f"serving fleet [{games}] on "
            f"http://{args.host}:{supervisor.port} with {workers} "
            f"worker(s) "
            f"(control http://{args.host}:{supervisor.control_port} — "
            "GET /healthz, GET /metrics, GET /traces, POST /reload)",
            flush=True,  # a harness tailing the pipe needs the banner NOW
        )
        previous = {}

        def _on_stop(signum, frame):
            supervisor.request_stop()

        def _on_hup(signum, frame):
            supervisor.request_reload()

        for sig, handler in ((signal.SIGINT, _on_stop),
                             (signal.SIGTERM, _on_stop),
                             (signal.SIGHUP, _on_hup)):
            try:
                previous[sig] = signal.signal(sig, handler)
            except ValueError:  # not the main thread (programmatic use)
                pass
        try:
            return supervisor.run()
        finally:
            for sig, handler in previous.items():
                signal.signal(sig, handler)


def _cmd_query(args) -> int:
    from gamesmanmpi_tpu.core.values import value_name
    from gamesmanmpi_tpu.db import DbFormatError, DbReader

    try:
        reader = DbReader(args.db)
    except DbFormatError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    from gamesmanmpi_tpu.db.format import parse_position

    states = []
    order = []  # (query string, packed state or None)
    for q in args.positions:
        try:
            state = parse_position(reader.game, q)
            order.append((q, len(states)))
            states.append(state)
        except ValueError as e:
            order.append((q, None))
            print(f"query {q}: invalid position ({e})")
    if states:
        values, rem, found, best = reader.lookup_best(states)
        sentinel = int(reader.game.sentinel)
        for q, i in order:
            if i is None:
                continue
            if not found[i]:
                print(f"query {q}: not in database")
                continue
            line = (
                f"query {q}: value={value_name(values[i])} "
                f"remoteness={int(rem[i])}"
            )
            if int(best[i]) != sentinel:
                line += f" best={hex(int(best[i]))}"
            print(line)
    return 0


def _cmd_registry(args) -> int:
    import pathlib
    import signal
    import threading

    from gamesmanmpi_tpu.db.format import DbFormatError, read_manifest
    from gamesmanmpi_tpu.registry.jobs import JobQueue, run_pending
    from gamesmanmpi_tpu.registry.server import RegistryServer, publish_db

    root = pathlib.Path(args.root)
    if args.registry_cmd == "publish":
        try:
            name = args.name or str(read_manifest(args.db)["game"])
            record = publish_db(root, name, args.db)
        except (DbFormatError, ValueError, OSError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        print(json.dumps({"name": name, "epoch": record["epoch"],
                          "files": len(record["files"])}))
        return 0

    if args.registry_cmd == "run-jobs":
        queue = JobQueue(root / "jobs.jsonl")
        work = pathlib.Path(args.work_dir) if args.work_dir else root / "work"
        results = run_pending(queue, root, work,
                              book_plies=args.book_plies, once=args.once,
                              log=_jsonl_stderr)
        print(json.dumps({"ran": len(results), "results": results},
                         default=str))
        return 0 if all(r["ok"] for r in results) else 1

    # registry serve
    queue = JobQueue(root / "jobs.jsonl") if args.jobs else None
    srv = RegistryServer(root, host=args.host, port=args.port, queue=queue)
    print(
        f"registry [{root}] on {srv.url} "
        f"({'with' if queue else 'no'} solve-on-demand queue)",
        flush=True,
    )
    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    srv.start()
    try:
        stop.wait()
    finally:
        srv.stop()
    return 0


def _jsonl_stderr(record):
    sys.stderr.write(json.dumps(record, default=str) + "\n")
    sys.stderr.flush()


def _db_main(argv) -> int:
    from gamesmanmpi_tpu.utils.platform import apply_platform_env

    args = _db_parser().parse_args(argv)
    # Same platform policy as the solve path: honor GAMESMAN_PLATFORM
    # before the first backend touch (serving wants the CPU backend — the
    # reader's canonicalize kernels are host-side by design).
    apply_platform_env(default_fake_devices=1)
    if args.cmd == "export-db":
        return _cmd_export_db(args)
    if args.cmd == "serve":
        return _cmd_serve(args)
    if args.cmd == "registry":
        return _cmd_registry(args)
    return _cmd_query(args)


if __name__ == "__main__":
    sys.exit(main())
