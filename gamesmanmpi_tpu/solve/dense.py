"""Dense class-partitioned retrograde engine for the Connect-4 family.

The level-BFS engine (solve/engine.py) discovers reachable positions by
expand + sort-unique and joins parents to children through the dedup sort's
provenance. Its warm profile on the v5e is sort-bound forward and
gather-bound backward (docs/ARCHITECTURE.md "Where the time went"). This
module removes the sorts — and the forward pass, and the stored states —
entirely, for games with Connect-4's "cells fill one column at a time"
structure, by indexing positions *perfectly* instead of discovering them:

- A **class** is a column-height profile (h_0..h_{w-1}); its positions are
  the ways to color the sum(h)=L filled cells with the two players' stones.
  Turn parity fixes player 1's stone count n1 = ceil(L/2), so EVERY class
  at level L has exactly C(L, n1) positions — a level is one rectangular
  [num_profiles, C(L, n1)] array. This is the Pentago solver's "sections"
  idea (PAPERS.md: arXiv 1404.0743 partitions by per-quadrant stone
  counts) applied to columns.
- Within a class, a position's index is the **combinadic rank** of its
  player-1 cell set (colex: rank = sum_i C(s_i, i) over set positions
  s_1<...<s_n). rank/unrank are short static loops over the board's cells —
  pure VPU work, no memory traffic.
- The solve is ONE backward sweep over levels; no forward discovery exists
  because the classes and their sizes are closed-form. Per level: unrank →
  primitive test (bitboard fold) → per-move child rank → gather the child's
  packed (value, remoteness) byte → negamax/remoteness combine
  (ops/combine.py, same rules as every other engine here).
- Tables store ONE byte per position (2-bit value + 6-bit remoteness;
  remoteness <= w*h = 42 < 64) and no states at all — vs 13 B/pos in the
  BFS engine. States are recomputed from ranks when needed.

The price is solving a *superset*: every colorable cell assignment, not
just reachable positions. Measured blowups (encodable / reachable):
5x4 1.42x, 6x4 1.68x, 5x5 2.47x, 6x5 ~2.2x — cheap against eliminating
the sort pipeline. The near-full levels of 6x6/7x6 blow up 10-16x, so the
giant boards stay on the sharded BFS engine (parallel/sharded.py); this
engine's domain is the single-chip boards (BASELINE.md configs #3 ladder),
where it also makes 6x5 fit one chip (~1.3 GB peak level vs ~12 GB with
stored uint64 states).

Garbage positions (the unreachable part of the superset) can never
contaminate real values: a reachable non-primitive position has no line
for either player, hence all its children are positions a real game could
contain, hence the combine only ever reads real cells. Positions where the
player to move already has a line are marked terminal without expansion,
so they cost a primitive test, not a gather fan-out.

**Counting** is separate from solving. The benchmark metric and the parity
suite count *reachable* positions (= Tromp's published "legal" counts,
which the BFS engine's discovery matches). Reachability is NOT locally
decidable from a position's stones alone — a no-line position with correct
stone parity can still be unreachable because the within-column color
stacks must admit an alternating global move order — so the exact count
comes from a dense **reachability sweep**: forward over levels,
reach(child) = OR over columns [top stone is the mover's color AND the
unmoved parent is reachable AND the parent was not terminal]. The sweep
reuses the rank machinery with "unmove" tables and costs about as much as
the backward solve; it runs once per board per process and is cached, so
warm benchmark runs measure the solve alone.

Reference parity: same Game-module semantics as the reference solver
(SURVEY.md §2.1 — value algebra §2.1.2, remoteness §2.1.3), same outputs
(root value + remoteness, per-position queries). The reference's
src/process.py discovers positions dynamically; perfect indexing is the
TPU-native replacement, trading a bounded superset for static shapes and
zero sort/shuffle traffic.
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp

from gamesmanmpi_tpu.core.values import LOSE, TIE
from gamesmanmpi_tpu.games.connect4 import Connect4
from gamesmanmpi_tpu.ops.combine import combine_children
from gamesmanmpi_tpu.solve.engine import get_kernel, schedule_kernel
from gamesmanmpi_tpu.solve.precompile import sds
from gamesmanmpi_tpu.utils.env import env_int, env_opt, env_str
from gamesmanmpi_tpu.utils.platform import backend_epoch, platform_auto_flag


def _profiles_for_level(width: int, height: int, level: int) -> np.ndarray:
    """All column-height profiles summing to `level`, lexicographic.

    Returns [P, width] int8. Lexicographic order is the class-row order
    everywhere (tables, move maps, checkpoints).
    """
    out = []

    def rec(prefix, remaining, cols_left):
        if cols_left == 0:
            if remaining == 0:
                out.append(prefix)
            return
        # Feasibility pruning keeps this linear in the output size.
        if remaining > cols_left * height:
            return
        for v in range(min(height, remaining) + 1):
            rec(prefix + [v], remaining - v, cols_left - 1)

    rec([], level, width)
    return np.array(out, dtype=np.int8).reshape(len(out), width)


def n1_of_level(level: int) -> int:
    """Player-1 stones after `level` plies (player 1 moves first)."""
    return (level + 1) // 2


class DenseTables:
    """Host-side class machinery for one board: profiles, cell indexing,
    move maps, binomials. Everything here is numpy; device constants are
    uploaded per level by the solver."""

    def __init__(self, width: int, height: int, connect: int = 4):
        self.width, self.height, self.connect = width, height, connect
        self.ncells = width * height
        self.h1 = height + 1
        # Board bitboard layout matches games/connect4.py: cell (c, r) at
        # bit c*(h+1)+r, guard slots (r == h) always zero here. Reusing the
        # layout keeps the win fold identical and the spare bit per column
        # stops cross-column wraps in the stride-1 (vertical) direction.
        if self.h1 * width > 63:
            raise ValueError("board too large for uint64 bitboards")
        self.bits_dtype = np.uint64 if self.h1 * width > 31 else np.uint32

        # Global cell slots: j = c*height + r, (c asc, r asc). A class's
        # cells, walked in ascending j, get consecutive within-class
        # indices — the combinadic positions.
        self.bitpos = np.array(
            [c * self.h1 + r for c in range(width) for r in range(height)],
            dtype=np.int32,
        )

        # binom[k][i] = C(k, i); k = within-class cell index (0..ncells-1),
        # i = stone ordinal (0..n1max+1). uint64 covers C(42, 21).
        n1max = n1_of_level(self.ncells)
        self.n1_width = n1max + 2
        self.binom = np.zeros((self.ncells + 1, self.n1_width), np.uint64)
        for k in range(self.ncells + 1):
            for i in range(self.n1_width):
                self.binom[k, i] = math.comb(k, i) if i <= k else 0

        self.profiles: list[np.ndarray] = []
        self.row_of: list[dict] = []
        self.class_size: list[int] = []
        for L in range(self.ncells + 1):
            p = _profiles_for_level(width, height, L)
            self.profiles.append(p)
            self.row_of.append(
                {tuple(int(v) for v in row): i for i, row in enumerate(p)}
            )
            self.class_size.append(math.comb(L, n1_of_level(L)))

        self._level_consts: dict[int, dict] = {}
        self._cellidx: dict[int, np.ndarray] = {}
        # Device-side caches (filled by DenseSolver._upload_consts; shared
        # across solver instances of the same board so warm repeats skip
        # re-upload as well as re-derivation). Invalidated when
        # force_platform clears backends (the arrays' devices die with
        # them) — see drop_stale_device_caches.
        self._dev_consts: dict = {}
        self._dev_binom = None
        self._dev_epoch = backend_epoch()

    def drop_stale_device_caches(self) -> None:
        """Drop device arrays uploaded before a backend clear."""
        epoch = backend_epoch()
        if epoch != self._dev_epoch:
            self._dev_consts = {}
            self._dev_binom = None
            self._dev_epoch = epoch

    # -- per-level constants ------------------------------------------------

    def col_base(self, level: int) -> np.ndarray:
        """[P, w] int32: within-class index of each column's FIRST cell
        (= cells in lower-numbered columns). The one definition of the
        class cell ordering — cellidx_rows and snapk both derive from it.
        """
        prof = self.profiles[level].astype(np.int32)
        return np.concatenate(
            [np.zeros((prof.shape[0], 1), np.int32),
             np.cumsum(prof, axis=1)[:, :-1]], axis=1
        )

    def cellidx_rows(self, level: int) -> np.ndarray:
        """[P, ncells] int16: within-class index of global slot j, -1 if the
        cell is above the column height (absent)."""
        if level in self._cellidx:
            return self._cellidx[level]
        prof = self.profiles[level].astype(np.int32)  # [P, w]
        w, h = self.width, self.height
        base = self.col_base(level)  # [P, w] cells before column c
        r = np.tile(np.arange(h, dtype=np.int32), w)  # [ncells]
        c = np.repeat(np.arange(w, dtype=np.int32), h)
        idx = base[:, c] + r[None, :]  # [P, ncells]
        absent = r[None, :] >= prof[:, c]
        out = np.where(absent, np.int16(-1), idx.astype(np.int16))
        self._cellidx[level] = out
        return out

    def level_consts(self, level: int) -> dict:
        """All device-constant arrays for one level's kernels (host numpy)."""
        if level in self._level_consts:
            return self._level_consts[level]
        w, h, h1 = self.width, self.height, self.h1
        prof = self.profiles[level].astype(np.int64)  # [P, w]
        P = prof.shape[0]
        dt = self.bits_dtype

        filled = np.zeros(P, np.uint64)
        # Guard bits of the game's packed encoding (one 1 per column at its
        # height): packed state = current-player stones | guards. The
        # hybrid engine's boundary kernels build/emit packed states from
        # dense (row, rank) coordinates with these.
        guards = np.zeros(P, np.uint64)
        for c in range(w):
            col = (np.uint64(1) << prof[:, c].astype(np.uint64)) - np.uint64(1)
            filled |= col << np.uint64(c * h1)
            guards |= np.uint64(1) << (prof[:, c] + c * h1).astype(np.uint64)

        newbit = np.zeros((P, w), np.uint64)   # cell (c, h_c): the drop target
        topstone = np.zeros((P, w), np.uint64)  # cell (c, h_c - 1): last drop
        valid = prof < h
        for c in range(w):
            hc = prof[:, c]
            newbit[:, c] = np.where(
                valid[:, c], np.uint64(1) << (hc + c * h1).astype(np.uint64), 0
            )
            topstone[:, c] = np.where(
                hc > 0,
                np.uint64(1) << np.maximum(hc - 1 + c * h1, 0).astype(np.uint64),
                0,
            )

        move_row = np.full((P, w), -1, np.int32)
        if level < self.ncells:
            nxt = self.row_of[level + 1]
            for c in range(w):
                for p in range(P):
                    if valid[p, c]:
                        key = list(prof[p])
                        key[c] += 1
                        move_row[p, c] = nxt[tuple(int(v) for v in key)]
        # move_row[:, c] is STRICTLY increasing over valid rows: profiles
        # are lexicographic and adding e_c to two profiles preserves their
        # lex order. With ranks monotone per row, the flat child index
        # vector is globally non-decreasing once invalid rows are filled
        # with the previous valid row's LAST slot — which lets the gather
        # carry XLA's indices_are_sorted hint (GAMESMAN_DENSE_GATHER).
        move_fill = np.maximum.accumulate(
            np.where(valid, move_row, -1), axis=0
        ).astype(np.int32)

        # Unmove: the parent one ply earlier, per column (for the
        # reachability sweep). parent_row[p, c] = -1 when column c is empty.
        parent_row = np.full((P, w), -1, np.int32)
        if level > 0:
            prv = self.row_of[level - 1]
            for c in range(w):
                for p in range(P):
                    if prof[p, c] > 0:
                        key = list(prof[p])
                        key[c] -= 1
                        parent_row[p, c] = prv[tuple(int(v) for v in key)]

        # Fused-rank snapshot slots: snapk[p, j] = the within-CHILD-class
        # index the new cell of column j//h would get (= parent cells
        # before that slot), at the one slot per column where r == h_c;
        # -1 elsewhere. See _rank_all_moves_fused.
        base = self.col_base(level).astype(np.int64)
        snapk = np.full((P, self.ncells), -1, np.int32)
        for c in range(w):
            hc = prof[:, c]
            rows = np.arange(P)
            ok = hc < h
            snapk[rows[ok], (c * h + hc[ok]).astype(np.int64)] = (
                base[ok, c] + hc[ok]
            )

        cellidx = self.cellidx_rows(level)
        child_cellidx = np.full((P, w, self.ncells), -1, np.int16)
        if level < self.ncells:
            rows = self.cellidx_rows(level + 1)  # [P', ncells]
            for c in range(w):
                ok = move_row[:, c] >= 0
                child_cellidx[ok, c, :] = rows[move_row[ok, c]]
        parent_cellidx = np.full((P, w, self.ncells), -1, np.int16)
        if level > 0:
            rows = self.cellidx_rows(level - 1)
            for c in range(w):
                ok = parent_row[:, c] >= 0
                parent_cellidx[ok, c, :] = rows[parent_row[ok, c]]

        consts = {
            "filled": filled.astype(dt),
            "guards": guards.astype(dt),
            "newbit": newbit.astype(dt),
            "topstone": topstone.astype(dt),
            "valid": valid,
            "move_row": move_row,
            "move_fill": move_fill,
            "parent_row": parent_row,
            "cellidx": cellidx,
            "child_cellidx": child_cellidx,
            "parent_cellidx": parent_cellidx,
            "snapk": snapk,
        }
        self._level_consts[level] = consts
        return consts

    # -- host (numpy / python-int) rank machinery ---------------------------

    def rank_np(self, level: int, row: int, p1_bits: int) -> int:
        """Combinadic rank of a position's player-1 cell set (host scalar)."""
        cellidx = self.cellidx_rows(level)[row]
        rank, seen = 0, 0
        for j in range(self.ncells):
            k = int(cellidx[j])
            if k < 0:
                continue
            if (p1_bits >> int(self.bitpos[j])) & 1:
                seen += 1
                rank += math.comb(k, seen)
        return rank

    def unrank_np(self, level: int, row: int, rank: int) -> int:
        """Inverse of rank_np: player-1 bitboard (host scalar)."""
        cellidx = self.cellidx_rows(level)[row]
        order = [(int(cellidx[j]), int(self.bitpos[j]))
                 for j in range(self.ncells) if cellidx[j] >= 0]
        order.sort(reverse=True)  # descending within-class index
        bits, i = 0, n1_of_level(level)
        for k, bp in order:
            if i > 0 and math.comb(k, i) <= rank:
                rank -= math.comb(k, i)
                bits |= 1 << bp
                i -= 1
        return bits

    def locate(self, state: int) -> tuple[int, int, int]:
        """Guard-encoded state (games/connect4.py) -> (level, row, rank)."""
        w, h1 = self.width, self.h1
        heights = []
        current = 0
        for c in range(w):
            col = (state >> (c * h1)) & ((1 << h1) - 1)
            hc = col.bit_length() - 1
            if hc < 0:
                raise ValueError(f"column {c} has no guard bit: {state:#x}")
            heights.append(hc)
            current |= (col ^ (1 << hc)) << (c * h1)
        level = sum(heights)
        row = self.row_of[level].get(tuple(heights))
        if row is None:
            raise ValueError(f"impossible height profile {heights}")
        filled = 0
        for c in range(w):
            filled |= ((1 << heights[c]) - 1) << (c * h1)
        # The guard encoding stores the CURRENT player's stones; player 1 is
        # the current player at even levels.
        p1 = current if level % 2 == 0 else (filled ^ current)
        return level, row, self.rank_np(level, row, p1)

    def _connected_np(self, stones: int) -> bool:
        """Host twin of the device win fold, on a python-int bitboard."""
        h = self.height
        for d in (1, h, h + 1, h + 2):
            x = stones
            for i in range(1, self.connect):
                x &= stones >> (i * d)
            if x:
                return True
        return False

    def current_player_has_line(self, level: int, row: int,
                                rank: int) -> bool:
        """True for the garbage class: the player to move already won."""
        p1 = self.unrank_np(level, row, rank)
        prof = self.profiles[level][row]
        filled = 0
        for c in range(self.width):
            filled |= ((1 << int(prof[c])) - 1) << (c * self.h1)
        current = p1 if level % 2 == 0 else (filled ^ p1)
        return self._connected_np(current)


# ---------------------------------------------------------------------------
# Device kernels


def _connected_fold(stones, h: int, connect: int, dt):
    """Any `connect`-in-a-row in a guard-layout bitboard (no guard bits set).

    Same four directions as games/connect4.py: vertical 1, diag-down h,
    horizontal h+1, diag-up h+2.
    """
    won = jnp.zeros(stones.shape, bool)
    for d in (1, h, h + 1, h + 2):
        x = stones
        for i in range(1, connect):
            x = x & (stones >> dt(i * d))
        won = won | (x != 0)
    return won


def _binom_lookup(brow, i, use_onehot: bool):
    """C(k, i) where brow[...] = binom[k] ([..., K] rank-dtype) and i is a
    per-element ordinal in [0, K). Two lowerings: take_along_axis (a small
    batched gather) or a one-hot select tree (pure VPU, K-1 selects)."""
    if not use_onehot:
        return jnp.take_along_axis(brow, i, axis=-1)
    out = jnp.zeros(i.shape, brow.dtype)
    for k in range(brow.shape[-1]):
        out = jnp.where(i == k, brow[..., k : k + 1], out)
    return out


def _unrank_bits(ranks, n1, binom, cellidx, bitpos, dt, rank_dtype,
                 use_onehot):
    """[P, cb] combinadic ranks -> player-1 bitboards, via a descending walk
    over the global cells. binom is the [ncells+1, K] table; cellidx[j] is
    each class's within-class index for global cell j ([ncells, P] i32,
    -1 marking an absent cell). The binom rows are gathered per step ON
    DEVICE (a [P]-gather from a tiny table) instead of being prebuilt on
    host — at 6x5 the prebuilt [ncells, P, w, K] arrays would cost 1-2 s
    PER LEVEL just to upload through the 30-60 MB/s relay.

    fori_loop, not an unrolled Python loop: ncells * (1 + max_moves) cell
    steps per level step unrolled was ~100 gather blocks of HLO, taking
    2.5-11 s to COMPILE per level on CPU (measured); the rolled form
    compiles in well under a second and the per-iteration work is a handful
    of fused elementwise ops on [P, cb]."""
    ncells, P = cellidx.shape
    cb = ranks.shape[1]
    masks = jnp.asarray([1 << int(b) for b in bitpos], dt)

    def body(t, carry):
        bits, rem, r = carry
        j = ncells - 1 - t
        kj = jax.lax.dynamic_index_in_dim(cellidx, j, 0, keepdims=False)
        exists = (kj >= 0)[:, None]  # [P, 1]
        brow = binom[jnp.clip(kj, 0, binom.shape[0] - 1)]  # [P, K]
        cki = _binom_lookup(brow[:, None, :], rem[..., None],
                            use_onehot)[..., 0]  # [P, cb] C(k_j, rem)
        # C(k, rem) == 0 (k < rem) means every remaining cell MUST be a
        # stone — 0 <= r always holds, so `take` fires as required.
        take = exists & (rem > 0) & (cki <= r)
        r = jnp.where(take, r - cki, r)
        rem = jnp.where(take, rem - 1, rem)
        bits = jnp.where(take, bits | masks[j], bits)
        return bits, rem, r

    bits = jnp.zeros((P, cb), dt)
    rem = jnp.full((P, cb), n1, jnp.int32)
    r = ranks + jnp.zeros((P, 1), rank_dtype)
    bits, _, _ = jax.lax.fori_loop(0, ncells, body, (bits, rem, r))
    return bits


def _rank_bits(bits, binom, cellidx_c, bitpos, dt, rank_dtype, use_onehot):
    """[P, cb] stone bitboards -> combinadic ranks under the cell indexing
    given by cellidx_c ([ncells, P] i32, the TARGET class per row)."""
    ncells, P = cellidx_c.shape
    cb = bits.shape[1]
    masks = jnp.asarray([1 << int(b) for b in bitpos], bits.dtype)

    def body(j, carry):
        acc, seen = carry
        kj = jax.lax.dynamic_index_in_dim(cellidx_c, j, 0, keepdims=False)
        exists = (kj >= 0)[:, None]
        brow = binom[jnp.clip(kj, 0, binom.shape[0] - 1)]  # [P, K]
        bset = (bits & masks[j]) != 0
        take = exists & bset
        seen_n = jnp.where(take, seen + 1, seen)
        ck = _binom_lookup(brow[:, None, :], seen_n[..., None],
                           use_onehot)[..., 0]
        acc = jnp.where(take, acc + ck, acc)
        return acc, seen_n

    acc = jnp.zeros((P, cb), rank_dtype)
    seen = jnp.zeros((P, cb), jnp.int32)
    acc, _ = jax.lax.fori_loop(0, ncells, body, (acc, seen))
    return acc


def _rank_all_moves_fused(bits, binom, cellidx, snapk, bitpos, rank_dtype,
                          use_onehot, p1_moves: bool, w: int, h: int):
    """All w child ranks in ONE walk over the parent's cells.

    The per-move walk in _rank_bits re-reads every cell w times. But the
    child class for move c differs from the parent only by inserting one
    cell at within-child index t_c, so (colex combinadics):

      p2 move:  child_rank(c) = A(t_c) + [S1 - S1(t_c)]
      p1 move:  child_rank(c) = A(t_c) + C(t_c, seen(t_c)+1)
                                + [S2 - S2(t_c)]

    where A(t)   = sum of C(k, i) over set cells with parent index < t
          S1(t)  = same prefix of C(k+1, i)     (cells shift up past t)
          S2(t)  = same prefix of C(k+1, i+1)   (ordinals also shift: the
                                                 new stone sits below)
          seen(t)= set cells before t.

    One walk maintains (A, S_shift, seen) and snapshots A - S_shift
    (+ the new-stone term) at each column's insertion slot (snapk) —
    2-3 binom lookups per cell instead of w, the dominant VPU cost of the
    backward step under the one-hot lowering. Returns cranks [w, P, cb].
    """
    ncells, P = cellidx.shape
    cb = bits.shape[1]
    masks = jnp.asarray([1 << int(b) for b in bitpos], bits.dtype)
    shift_ord = 1 if p1_moves else 0
    kmax = binom.shape[0] - 1

    def body(j, carry):
        acc_par, acc_sh, seen, snaps = carry
        kj = jax.lax.dynamic_index_in_dim(cellidx, j, 0, keepdims=False)
        skj = jax.lax.dynamic_index_in_dim(snapk, j, 0, keepdims=False)
        exists = (kj >= 0)[:, None]
        bset = (bits & masks[j]) != 0
        take = exists & bset
        seen_n = jnp.where(take, seen + 1, seen)
        browk = binom[jnp.clip(kj, 0, kmax)]
        browk1 = binom[jnp.clip(kj + 1, 0, kmax)]
        cpar = _binom_lookup(browk[:, None, :], seen_n[..., None],
                             use_onehot)[..., 0]
        csh = _binom_lookup(browk1[:, None, :],
                            (seen_n + shift_ord)[..., None],
                            use_onehot)[..., 0]
        acc_par = jnp.where(take, acc_par + cpar, acc_par)
        acc_sh = jnp.where(take, acc_sh + csh, acc_sh)
        # Snapshot for the move of this step's column. The insertion slot
        # is ABSENT in the parent (it sits above the column height), so
        # take is False on snap rows and pre/post-step accumulators agree.
        is_snap = (skj >= 0)[None, :, None]  # [1, P, 1]
        snap_val = acc_par - acc_sh
        if p1_moves:
            brows = binom[jnp.clip(skj, 0, kmax)]
            snap_val = snap_val + _binom_lookup(
                brows[:, None, :], (seen_n + 1)[..., None], use_onehot
            )[..., 0]
        col = j // h
        c_onehot = (jax.lax.iota(jnp.int32, w) == col)[:, None, None]
        snaps = jnp.where(c_onehot & is_snap, snap_val[None], snaps)
        return acc_par, acc_sh, seen_n, snaps

    acc_par = jnp.zeros((P, cb), rank_dtype)
    acc_sh = jnp.zeros((P, cb), rank_dtype)
    seen = jnp.zeros((P, cb), jnp.int32)
    snaps = jnp.zeros((w, P, cb), rank_dtype)
    acc_par, acc_sh, seen, snaps = jax.lax.fori_loop(
        0, ncells, body, (acc_par, acc_sh, seen, snaps)
    )
    return snaps + acc_sh[None]


# Kernel block / window for gather_mode="pallas". block=2048 divides every
# _cblock (which rounds to a PALLAS_BLOCK multiple), so no kernel block
# straddles a profile row; window=4*block covers child-rank spans up to a
# ~4x per-level expansion ratio (near-full levels, where the time is, are
# close to 1x). Blocks that still miss (tiny early levels can expand
# faster) fall back per-call via lax.cond.
PALLAS_BLOCK = 2048
PALLAS_WINDOW = 8192


def build_dense_step(tables: DenseTables, level: int, cblock: int,
                     rank_dtype, flat_dtype, use_onehot: bool,
                     fused_rank: bool = False,
                     gather_mode: str = "plain"):
    """Build the backward step for one level at one block width.

    Returned fn:
      (rank0 rank_dtype scalar, child_cells [flat] u8 (dummy at the top
       level),
       binom [ncells+1, K], cellidx [ncells, P] i32, filled [P],
       newbit [P, w], valid [P, w] bool, move_row [P, w] i32,
       move_fill [P, w] i32, child_cellidx [ncells, P, w] i32,
       snapk [ncells, P] i32)
      -> cells [P, cblock] u8

    gather_mode picks the child-cell gather lowering; results are
    identical in all three (tests pin it):
      "plain"  — clip + XLA gather (measured fastest XLA form on-chip);
      "sorted" — invalid rows' flat indices get a monotone fill (see
                 level_consts move_fill) and the gather carries
                 indices_are_sorted=True (measured: the hint buys
                 nothing, chip session r04);
      "pallas" — the same monotone fill feeds the Pallas monotone-window
                 gather (ops/pallas_gather.py), which streams window
                 tiles through VMEM instead of issuing per-element HBM
                 transactions. A block whose child-rank span exceeds the
                 window misses; nmiss>0 falls back to the sorted-hint
                 XLA gather for that call via lax.cond. Blocks never
                 straddle profile rows (_cblock rounds to the kernel
                 block), so spans are bounded by the per-level child
                 expansion ratio and the big near-full levels — where
                 the time is — run miss-free.

    fused_rank picks the single-walk child ranking
    (_rank_all_moves_fused) over the per-move walks; results are
    identical (tests pin it) — it is a lowering choice, keyed into the
    kernel cache.

    All shape-static; one compiled program per (level-shape, block width).
    """
    # Resolved at build time (kernels are built per backend epoch): the
    # Pallas kernel runs in interpret mode on CPU so the parity tests and
    # the fake-mesh suite exercise the exact same program structure.
    pallas_interpret = (gather_mode == "pallas"
                        and jax.default_backend() == "cpu")
    w, h, connect = tables.width, tables.height, tables.connect
    ncells = tables.ncells
    dt = jnp.uint64 if tables.bits_dtype == np.uint64 else jnp.uint32
    n1 = n1_of_level(level)
    C = tables.class_size[level]
    Cc = tables.class_size[level + 1] if level < ncells else 1
    is_top = level == ncells
    p1_moves = level % 2 == 0   # the player moving OUT of this level
    mover_is_p1 = level % 2 == 1  # the player who made the ply INTO it
    bitpos = [int(b) for b in tables.bitpos]

    def step(rank0, child_cells, binom, cellidx, filled, newbit,
             valid, move_row, move_fill, child_cellidx, snapk):
        P = filled.shape[0]
        ranks = (rank0.astype(rank_dtype)
                 + jax.lax.iota(rank_dtype, cblock)[None, :])  # [1, cb]

        p1 = _unrank_bits(ranks, n1, binom, cellidx, bitpos, dt, rank_dtype,
                          use_onehot)
        p2 = filled[:, None] ^ p1
        mover = p1 if mover_is_p1 else p2
        current = p2 if mover_is_p1 else p1

        mover_line = _connected_fold(mover, h, connect, dt)
        current_line = _connected_fold(current, h, connect, dt)

        # mover_line: the player to move already lost. current_line without
        # mover_line: unreachable garbage — terminal-ize it so it never
        # fans out gathers (value is arbitrary; nothing real reads it).
        # Full board without lines: TIE.
        if is_top:
            return jnp.where(
                mover_line | current_line, jnp.uint8(LOSE), jnp.uint8(TIE)
            )  # remoteness 0 everywhere at the top level
        prim_mask = mover_line | current_line

        if fused_rank:
            cranks = _rank_all_moves_fused(
                p1, binom, cellidx, snapk, bitpos, rank_dtype, use_onehot,
                p1_moves, w, h,
            )
        if gather_mode == "pallas":
            # Window-pad the child table ONCE per step so the kernel's
            # internal pad (a full-table copy) is a no-op for all w move
            # gathers. The XLA fallback keeps the unpadded table.
            from gamesmanmpi_tpu.ops.pallas_gather import padded_table_len

            m = child_cells.shape[0]
            tpad = padded_table_len(m, PALLAS_WINDOW) - m
            child_cells_pal = (
                jnp.concatenate(
                    [child_cells, jnp.zeros((tpad,), child_cells.dtype)]
                ) if tpad else child_cells
            )
        child_vals = []
        child_rems = []
        masks = []
        for c in range(w):
            if fused_rank:
                crank = cranks[c]
            else:
                cbits = (p1 | newbit[:, c : c + 1]) if p1_moves else p1
                crank = _rank_bits(cbits, binom, child_cellidx[:, :, c],
                                   bitpos, dt, rank_dtype, use_onehot)
            flat = (move_row[:, c : c + 1].astype(flat_dtype)
                    * flat_dtype(Cc) + crank.astype(flat_dtype))
            ok = valid[:, c : c + 1] & jnp.ones((1, cblock), bool)
            if gather_mode in ("sorted", "pallas"):
                # Invalid rows and pad lanes (rank >= C in the last block,
                # whose unranked bits are garbage) get a monotone fill —
                # invalid rows the previous valid row's LAST slot (or 0
                # before any valid row), pad lanes their own row's last
                # slot — keeping the flat vector globally non-decreasing
                # so the gather may stream instead of scattering reads.
                in_range = ranks < rank_dtype(C)  # [1, cb]
                fillr = jnp.where(
                    valid[:, c : c + 1],
                    move_row[:, c : c + 1],
                    move_fill[:, c : c + 1],
                ).astype(flat_dtype)
                fill = jnp.where(
                    fillr < 0, flat_dtype(0),
                    fillr * flat_dtype(Cc) + flat_dtype(Cc - 1),
                )
                flat = jnp.where(ok & in_range, flat, fill)

                def _xla_sorted(f=flat):
                    return child_cells.at[f.reshape(-1)].get(
                        indices_are_sorted=True, mode="clip"
                    ).reshape(f.shape)

                if gather_mode == "pallas":
                    from gamesmanmpi_tpu.ops.pallas_gather import (
                        monotone_window_gather,
                    )

                    # flat stays in flat_dtype (int64 for 6x6+): the
                    # kernel wrapper derives block-local int32 offsets
                    # outside Mosaic.
                    out, nmiss = monotone_window_gather(
                        child_cells_pal, flat.reshape(-1),
                        block=PALLAS_BLOCK, window=PALLAS_WINDOW,
                        interpret=pallas_interpret,
                    )
                    cell = jax.lax.cond(
                        nmiss == jnp.int32(0),
                        lambda: out.reshape(flat.shape),
                        _xla_sorted,
                    )
                else:
                    cell = _xla_sorted()
            else:
                cell = child_cells[
                    jnp.clip(flat, 0, child_cells.shape[0] - 1)
                ]
            child_vals.append(cell & jnp.uint8(3))
            child_rems.append((cell >> jnp.uint8(2)).astype(jnp.int32))
            masks.append(ok)

        cv = jnp.stack(child_vals, axis=-1).reshape(P * cblock, w)
        cr = jnp.stack(child_rems, axis=-1).reshape(P * cblock, w)
        mk = (jnp.stack(masks, axis=-1)
              & ~prim_mask[..., None]).reshape(P * cblock, w)
        values, rem_out = combine_children(cv, cr, mk)
        values = values.reshape(P, cblock)
        rem_out = rem_out.reshape(P, cblock)

        values = jnp.where(prim_mask, jnp.uint8(LOSE), values)
        rem_out = jnp.where(prim_mask, 0, rem_out)
        return values | (jnp.clip(rem_out, 0, 63).astype(jnp.uint8)
                         << jnp.uint8(2))

    # Not jitted here: engine.get_kernel / schedule_kernel jit the builder's
    # return value themselves.
    return step


def build_reach_step(tables: DenseTables, level: int, cblock: int,
                     rank_dtype, flat_dtype, use_onehot: bool,
                     fused_rank: bool = False, gather_mode: str = "plain"):
    """Build the reachability-sweep step for one level (level >= 1).

    fused_rank/gather_mode are accepted for builder-signature uniformity
    and ignored: the sweep's one-rank-per-column walk has no per-move
    fan-out to fuse (each column ranks a DIFFERENT parent bit pattern).

    reach(y) = OR over columns c of y's class: the top stone of column c
    belongs to the player who made ply `level` AND the position with that
    stone removed is reachable AND was not terminal (its own last mover had
    no line). Level counting is the exact Tromp-legal/reachable count the
    BFS engine discovers — validated against it in the parity tests.

    Returned fn:
      (rank0 rank_dtype scalar, parent_reach [flat] u8,
       binom [ncells+1, K], cellidx [ncells, P] i32, filled [P],
       topstone [P, w], parent_row [P, w] i32,
       parent_cellidx [ncells, P, w] i32)
      -> (reach [P, cblock] u8, count i64)
    """
    w, h, connect = tables.width, tables.height, tables.connect
    ncells = tables.ncells
    dt = jnp.uint64 if tables.bits_dtype == np.uint64 else jnp.uint32
    n1 = n1_of_level(level)
    C = tables.class_size[level]
    Cp = tables.class_size[level - 1]
    mover_is_p1 = level % 2 == 1           # who made ply `level`
    parent_mover_is_p1 = (level - 1) % 2 == 1  # who made the ply before
    bitpos = [int(b) for b in tables.bitpos]

    def step(rank0, parent_reach, binom, cellidx, filled, topstone,
             parent_row, parent_cellidx):
        P = filled.shape[0]
        ranks = (rank0.astype(rank_dtype)
                 + jax.lax.iota(rank_dtype, cblock)[None, :])
        in_range = ranks < rank_dtype(C)

        p1 = _unrank_bits(ranks, n1, binom, cellidx, bitpos, dt, rank_dtype,
                          use_onehot)

        reach = jnp.zeros((P, cblock), bool)
        for c in range(w):
            ts = topstone[:, c : c + 1]  # [P, 1]; 0 for empty columns
            stone_is_p1 = (p1 & ts) != 0
            color_ok = (ts != 0) & (
                stone_is_p1 if mover_is_p1 else ~stone_is_p1
            )
            parent_p1 = (p1 ^ ts) if mover_is_p1 else p1
            parent_filled = filled[:, None] ^ ts
            parent_mover = (parent_p1 if parent_mover_is_p1
                            else parent_filled ^ parent_p1)
            parent_live = ~_connected_fold(parent_mover, h, connect, dt)
            prank = _rank_bits(parent_p1, binom, parent_cellidx[:, :, c],
                               bitpos, dt, rank_dtype, use_onehot)
            flat = (parent_row[:, c : c + 1].astype(flat_dtype)
                    * flat_dtype(Cp) + prank.astype(flat_dtype))
            pr = parent_reach[
                jnp.clip(flat, 0, parent_reach.shape[0] - 1)
            ] != 0
            reach = reach | (color_ok & parent_live & pr
                             & (parent_row[:, c : c + 1] >= 0))
        count = jnp.sum((reach & in_range).astype(jnp.int64))
        return reach.astype(jnp.uint8), count

    return step


# ---------------------------------------------------------------------------


class DenseSolveResult:
    """Duck-typed SolveResult for the dense engine (CLI/bench compatible)."""

    def __init__(self, game: Connect4, tables: DenseTables, value: int,
                 remoteness: int, cells: Optional[Dict[int, np.ndarray]],
                 stats: dict):
        self.game = game
        self._tables = tables
        self.value = int(value)
        self.remoteness = int(remoteness)
        self.cells = cells  # level -> [P, C] u8, or None in no-tables mode
        self.stats = stats

    @property
    def num_positions(self) -> int:
        return self.stats["positions"]

    def lookup(self, state) -> tuple[int, int]:
        """(value, remoteness) of any guard-encoded position, O(1).

        Scope differs from the BFS engine's lookup: dense tables answer for
        every VALID board configuration (the encodable superset), not just
        game-reachable positions — the negamax value of a no-line
        configuration is well-defined whether or not alternating play can
        produce it. The one class whose stored cells are fabricated —
        positions where the player to move already completed a line (the
        solver terminal-izes them without expansion) — raises KeyError.
        """
        if self.cells is None:
            raise KeyError("solved in no-tables mode; re-run with tables")
        level, row, rank = self._tables.locate(int(state))
        if self._tables.current_player_has_line(level, row, rank):
            raise KeyError(
                f"state {int(state):#x} is not a position (the player to "
                "move already has a line); its table cell is a placeholder"
            )
        cell = int(self.cells[level][row, rank])
        return cell & 3, cell >> 2


# Reachable-position counts are a property of the board, not the solve;
# one sweep per process per board and every later solve reuses the result
# (the benchmark's warm repeats must measure the solve, not the count).
# A small JSON sidecar (next to the package, same place as the compile
# cache; GAMESMAN_DENSE_COUNTS_FILE overrides, "0" disables) carries the
# counts across processes — fresh bench invocations then skip the sweep
# entirely. Safe to cache durably: the sweep's totals are pinned against
# the BFS engine and Tromp's published counts in tests.
_REACH_COUNTS: Dict[tuple, Dict[int, int]] = {}


def _counts_file() -> Optional[str]:
    path = env_opt("GAMESMAN_DENSE_COUNTS_FILE")
    if path == "0":
        return None
    if path:
        return path
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(pkg_root, ".dense_counts.json")


# Bump when the sweep's semantics change (what a "reachable count" means);
# stamped into every sidecar record so a stale file from an older engine —
# or a hand-edited one — cannot silently feed the benchmark numerator.
_COUNTS_SCHEMA_VERSION = 2


def _counts_tag(board_key: tuple) -> str:
    return "x".join(str(k) for k in board_key)


def _load_cached_counts(board_key: tuple) -> Optional[Dict[int, int]]:
    path = _counts_file()
    if path is None or not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            data = json.load(f)
        rec = data.get(_counts_tag(board_key))
        # Stamp check: version + board echo (unstamped = pre-stamp file or
        # hand edit -> one re-sweep, not a silently-wrong headline metric).
        if (
            not isinstance(rec, dict)
            or rec.get("version") != _COUNTS_SCHEMA_VERSION
            or rec.get("board") != _counts_tag(board_key)
            or not isinstance(rec.get("counts"), dict)
        ):
            return None
        counts = {int(k): int(v) for k, v in rec["counts"].items()}
        # Cheap invariants of any valid sweep: one empty board at level 0,
        # non-negative counts, levels within the cell count.
        w, h = board_key[0], board_key[1]
        if counts.get(0) != 1 or any(
            v < 0 or k < 0 or k > w * h for k, v in counts.items()
        ):
            return None
        return counts
    except (OSError, ValueError):
        return None


def _store_cached_counts(board_key: tuple, counts: Dict[int, int]) -> None:
    path = _counts_file()
    if path is None:
        return
    try:
        import contextlib

        try:
            import fcntl
        except ImportError:  # pragma: no cover - non-POSIX: lockless
            fcntl = None

        with contextlib.ExitStack() as stack:
            # Serialize load-merge-replace across writer processes: two
            # boards finishing sweeps concurrently must not drop each
            # other's fresh entry (last-replace-wins on the merged dict).
            try:
                if fcntl is not None:
                    lockf = stack.enter_context(open(f"{path}.lock", "w"))
                    fcntl.flock(lockf, fcntl.LOCK_EX)
            except OSError:  # pragma: no cover - lockless best effort
                pass
            data = {}
            if os.path.exists(path):
                try:
                    with open(path) as f:
                        data = json.load(f)
                except ValueError:
                    # Corrupt file (torn write, manual edit): overwrite
                    # rather than silently abandoning the cache forever.
                    data = {}
            data[_counts_tag(board_key)] = {
                "version": _COUNTS_SCHEMA_VERSION,
                "board": _counts_tag(board_key),
                "counts": {str(k): v for k, v in counts.items()},
            }
            tmp = f"{path}.{os.getpid()}.tmp"  # private per writer: a
            # shared .tmp name lets a concurrent writer truncate it
            # mid-publish
            with open(tmp, "w") as f:
                json.dump(data, f)
            os.replace(tmp, path)
    except (OSError, ValueError):  # pragma: no cover - best-effort cache
        pass

# DenseTables memoizes per-level constants lazily; sharing one instance per
# board keeps repeat solves (bench best-of-N) from rebuilding the host-side
# move maps inside the timed region.
_TABLES: Dict[tuple, DenseTables] = {}


def tables_for(width: int, height: int, connect: int = 4) -> DenseTables:
    key = (width, height, connect)
    if key not in _TABLES:
        _TABLES[key] = DenseTables(width, height, connect)
    return _TABLES[key]


class DenseSolver:
    """Dense solver for Connect4 games (sym=False); single-chip or meshed.

    Usage mirrors solve.Solver: DenseSolver(game).solve() -> result with
    .value/.remoteness/.num_positions/.stats/.lookup.

    count_positions: "auto" runs the reachability sweep once per board per
    process (exact reachable count, validated against the BFS engine);
    False skips it and reports positions=0 unless already cached.

    devices > 1 partitions every level kernel over a 1-D mesh by RANK
    (the [P, cblock] work arrays' lane axis): the unrank walks, win folds
    and child ranking — the VPU work that is ~all of the dense cost — are
    embarrassingly parallel per position, so each device computes only
    its rank slice (XLA SPMD partitions from the out_sharding constraint;
    the global `iota` makes each shard's ranks correct with no kernel
    changes). The one communication is re-replicating each level's cells
    for the NEXT level's child gathers — an all_gather of the level
    (table bytes total over the whole solve, riding ICI), which is the
    simple regime this engine targets (boards whose peak level fits one
    device's HBM, <= 6x5; the 6x6+ halo-exchange design is recorded in
    docs/ARCHITECTURE.md). Single-controller only: the mesh spans local
    devices.
    """

    def __init__(self, game: Connect4, store_tables: bool = True,
                 block_elems: Optional[int] = None, logger=None,
                 count_positions="auto", devices: int = 1,
                 checkpointer=None):
        if not isinstance(game, Connect4):
            raise TypeError("DenseSolver requires a Connect4-family game")
        if game.sym:
            raise ValueError(
                "DenseSolver solves the full space; use sym=False "
                "(symmetry only reduces memory, which dense tables "
                "already cut to 1 byte/position)"
            )
        self.game = game
        self.store_tables = store_tables
        self.logger = logger
        self.count_positions = count_positions
        #: Restart-from-level for the backward sweep: each level's flat
        #: cells go to disk as computed (one forced download per level —
        #: through a slow host link this roughly doubles wall time, which
        #: is why it is opt-in), and a resumed solve skips the deepest
        #: CONTIGUOUS completed prefix, rechaining from its last level.
        self.checkpointer = checkpointer
        self.devices = int(devices)
        if self.devices < 1:
            raise ValueError(f"devices must be >= 1, got {devices}")
        if self.devices > 1:
            from gamesmanmpi_tpu.parallel.mesh import make_mesh

            self._mesh = make_mesh(self.devices)
        else:
            self._mesh = None
        self.tables = tables_for(game.width, game.height, game.connect)
        self.block_elems = block_elems or env_int(
            "GAMESMAN_DENSE_BLOCK", 64 * 1024 * 1024
        )
        # Async run-ahead control: the level loop enqueues without syncing
        # (the relay charges ~65 ms per host sync), so on big boards the
        # host can enqueue every level's buffers before any kernel
        # retires — the classic engine OOM'd exactly this way in round 2.
        # Levels bigger than this many cells drain with a 1-byte fetch.
        self.sync_cells = env_int(
            "GAMESMAN_DENSE_SYNC_CELLS", 256 * 1024 * 1024
        )
        # Binom lookup lowering: the one-hot select tree is bounded VPU
        # work (K-1 selects, K <= 23); take_along_axis emits a gather,
        # and XLA's TPU gathers measured ~11 ns/element (tools/microbench)
        # — at (1 + max_moves) * ncells lookups per position that would
        # dominate the whole solve. CONFIRMED on the v5e (chip session
        # r04, 5x5): onehot 9.04M pos/s vs take 212k — a 43x collapse,
        # exactly the predicted gather catastrophe. onehot is the default;
        # GAMESMAN_DENSE_BINOM=take re-enables the gather for measurement.
        self.use_onehot = env_str(
            "GAMESMAN_DENSE_BINOM", "onehot"
        ) != "take"
        # Child-ranking lowering: "fused" = one walk for all moves
        # (_rank_all_moves_fused), "simple" = per-move walks. Identical
        # results (tests pin it). MEASURED on the v5e (chip session r04,
        # 5x5 A/B): simple 9.04M pos/s vs fused 4.83M — simple wins 1.9x
        # and stays the default; the flag remains for re-measurement.
        self.use_fused = env_str(
            "GAMESMAN_DENSE_RANK", "simple"
        ) == "fused"
        # Gather lowering (identical results in all modes, tests pin it):
        #   "plain"  — clip + XLA gather. MEASURED on the v5e (chip
        #              session r04): 9.04M pos/s — the fastest XLA form.
        #   "sorted" — monotone fill + indices_are_sorted hint. MEASURED:
        #              6.35M — the hint costs fill arithmetic and buys
        #              nothing (microbench2: XLA's gather runs ~0.37 GB/s
        #              with or without sorted indices).
        #   "pallas" — monotone fill + the Pallas monotone-window gather
        #              (ops/pallas_gather.py), streaming window tiles
        #              through VMEM; per-call lax.cond fallback to the
        #              sorted XLA gather when any block's span misses its
        #              window. The dense backward is ~pure gather (8.6e8
        #              operand bytes at 0.112 GB/s, r04), so this is the
        #              candidate past 9M pos/s; go/no-go is
        #              tools/pallas_chip_check.py on silicon.
        self.gather_mode = platform_auto_flag(
            "GAMESMAN_DENSE_GATHER", accel="plain", cpu="plain",
            choices=("plain", "sorted", "pallas"),
        )
        if (self.gather_mode == "pallas" and self.devices > 1
                and jax.default_backend() != "cpu"
                and env_str(
                    "GAMESMAN_DENSE_GATHER_PALLAS_MESH", "0") != "1"):
            # devices>1 + pallas is exercised only in CPU interpret mode
            # (where pallas_call is emulated with plain JAX ops); whether
            # the real Mosaic custom call partitions correctly under
            # auto-SPMD is chip-unproven (ADVICE r4). Fall back to the
            # plain XLA gather until a mesh+pallas chip-session step
            # proves it; GAMESMAN_DENSE_GATHER_PALLAS_MESH=1 is that
            # step's escape hatch.
            import warnings

            warnings.warn(
                "GAMESMAN_DENSE_GATHER=pallas with devices>1 is not yet "
                "chip-proven; falling back to gather_mode=plain "
                "(set GAMESMAN_DENSE_GATHER_PALLAS_MESH=1 to override)",
                stacklevel=2,
            )
            # "plain", not "sorted": the r04 chip A/B measured sorted at
            # 0.70x plain (the hint buys nothing and the extra sort
            # costs) — the safety valve must demote to the shipped
            # optimum, not the slowest mode.
            self.gather_mode = "plain"
        nc = self.tables.ncells
        max_class = max(self.tables.class_size)
        self._rank_dtype = (jnp.uint32 if max_class < (1 << 31)
                            else jnp.uint64)
        max_flat = max(
            self.tables.class_size[L] * len(self.tables.profiles[L])
            for L in range(nc + 1)
        )
        # int64 flat spaces (6x6+) are pallas-eligible since r5: the
        # kernel takes pre-subtracted block-local int32 offsets, so the
        # 64-bit arithmetic stays outside Mosaic (ops/pallas_gather.py
        # module docstring, VERDICT r4 #3).
        self._flat_dtype = jnp.int32 if max_flat < (1 << 31) else jnp.int64

    @property
    def _board_key(self):
        g = self.game
        return (g.width, g.height, g.connect)

    def _replicate(self, arr):
        """Re-replicate a level's flat cells for the next level's gathers
        (devices > 1): THE one communication of the sharded dense design —
        an all_gather of the level, riding ICI. The outputs come back
        committed with the rank-partitioned sharding; the next kernel's
        in_shardings would otherwise reject them (committed arrays are
        never silently resharded)."""
        if self._mesh is None:
            return arr
        from jax.sharding import NamedSharding, PartitionSpec

        return jax.device_put(
            arr, NamedSharding(self._mesh, PartitionSpec())
        )

    def _jit_kwargs(self, kind: str):
        """Mesh partitioning for a level kernel (devices > 1), else {}.

        Inputs replicate (the child/parent flat table is what every shard
        gathers from; consts are KBs); the [P, cblock] output shards over
        its RANK axis, and XLA's SPMD partitioner propagates that
        constraint back through the elementwise/fori unrank chain so each
        device computes only its lane slice. The reach step's scalar count
        replicates (XLA inserts the cross-shard sum).
        """
        if self._mesh is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec

        from gamesmanmpi_tpu.parallel.mesh import AXIS

        rep = NamedSharding(self._mesh, PartitionSpec())
        cells = NamedSharding(self._mesh, PartitionSpec(None, AXIS))
        out = (cells, rep) if kind == "dense_reach" else cells
        return dict(in_shardings=rep, out_shardings=out)

    def _kernel(self, kind: str, level: int, cblock: int, builder):
        t, rd, fd, oh, fr, gm = (self.tables, self._rank_dtype,
                                 self._flat_dtype, self.use_onehot,
                                 self.use_fused, self.gather_mode)
        return get_kernel(
            self.game, kind, self._kernel_key(kind, level, cblock),
            lambda g: builder(t, level, cblock, rd, fd, oh, fused_rank=fr,
                              gather_mode=gm),
            jit_kwargs=self._jit_kwargs(kind),
        )

    def _rank0(self, b: int, cblock: int):
        """First rank of block b, in rank_dtype: Python ints don't
        overflow, so typing the scalar here keeps b*cblock exact past
        2^31 (uint64 boards like 6x6's C(36,18)=9.1e9 top classes)."""
        return self._rank_dtype(b * cblock)

    def _cblock(self, level: int) -> tuple[int, int]:
        P = len(self.tables.profiles[level])
        C = self.tables.class_size[level]
        cblock = max(min(C, max(self.block_elems // max(P, 1), 1)), 1)
        if self.gather_mode == "pallas" and cblock >= PALLAS_BLOCK:
            # Round to a PALLAS_BLOCK multiple so the Pallas kernel's
            # blocks never straddle a profile row (a straddling block's
            # index span is ~the child class size — a guaranteed window
            # miss). Only in pallas mode: the rounding changes cblock,
            # which keys every kernel cache entry — the other modes would
            # recompile their whole program set for nothing.
            cblock -= cblock % PALLAS_BLOCK
        if self._mesh is not None:
            # A sharded [P, cblock] output must split its rank axis evenly
            # across the mesh; round UP (pad ranks) — out-of-range lanes
            # already exist in every last block and both kernels handle
            # them (in_range masks / clipped gathers), and callers slice
            # back to C.
            cblock = -(-cblock // self.devices) * self.devices
            if (self.gather_mode == "pallas" and cblock >= PALLAS_BLOCK
                    and cblock % PALLAS_BLOCK):
                # The round-up broke the pallas invariant (every cblock
                # >= PALLAS_BLOCK is a PALLAS_BLOCK multiple, so kernel
                # blocks never straddle profile rows — including when the
                # round-up itself crossed the threshold); re-round to a
                # size satisfying both.
                import math

                q = math.lcm(self.devices, PALLAS_BLOCK)
                cblock = -(-cblock // q) * q
        return cblock, -(-C // cblock)

    def _avals(self, level: int, cblock: int, for_reach: bool):
        """ShapeDtypeStructs matching the kernels' call signature exactly
        (the compiled executable is shared through the same cache key)."""
        t = self.tables
        P = len(t.profiles[level])
        w = t.width
        nc1 = t.ncells + 1
        other = level - 1 if for_reach else level + 1
        if 0 <= other <= t.ncells:
            flat = t.class_size[other] * len(t.profiles[other])
        else:
            flat = 1
        dt = t.bits_dtype
        rk = np.uint32 if self._rank_dtype == jnp.uint32 else np.uint64
        common = (
            sds((), rk),  # rank0: rank_dtype end to end (i32 overflows
            # past 2^31 ranks, e.g. C(36,18)=9.1e9 at 6x6 level 36)
            sds((flat,), np.uint8),
            sds((nc1, t.n1_width), rk),
            sds((t.ncells, P), np.int32),
            sds((P,), dt),
        )
        if for_reach:
            return common + (
                sds((P, w), dt),          # topstone
                sds((P, w), np.int32),    # parent_row
                sds((t.ncells, P, w), np.int32),
            )
        return common + (
            sds((P, w), dt),              # newbit
            sds((P, w), np.bool_),        # valid
            sds((P, w), np.int32),        # move_row
            sds((P, w), np.int32),        # move_fill
            sds((t.ncells, P, w), np.int32),  # child_cellidx
            sds((t.ncells, P), np.int32),     # snapk
        )

    def _kernel_key(self, kind: str, level: int, cblock: int):
        # use_fused/gather_mode only change dense_step lowering;
        # keying them into the reach kernels would recompile byte-identical
        # programs on a flag flip (seconds each over the relay).
        fused = self.use_fused if kind == "dense_step" else False
        gm = self.gather_mode if kind == "dense_step" else "plain"
        return (
            kind, level, cblock, self.use_onehot, fused, gm,
            str(self._rank_dtype), str(self._flat_dtype), self.devices,
        )

    def schedule_compiles(self, reach_first: bool = False,
                          last_level: Optional[int] = None) -> None:
        """Queue background compiles of EVERY level's kernels.

        Unlike the BFS engine's speculative capacity ladder, the dense
        engine's shapes are closed-form — all programs are known before the
        first kernel runs, so the precompiler pool can overlap the whole
        set with the early levels' execution (the relay charges ~15 s per
        serial compile; docs/ARCHITECTURE.md "Where the time went").

        last_level bounds both phases (the hybrid engine runs dense
        kernels only up to its cutover region).
        """
        t = self.tables
        nc = t.ncells if last_level is None else min(last_level, t.ncells)

        def sched(kind, level, builder, for_reach):
            cblock, _ = self._cblock(level)
            key = self._kernel_key(kind, level, cblock)
            rd, fd, oh, fr, gm = (self._rank_dtype, self._flat_dtype,
                                  self.use_onehot, self.use_fused,
                                  self.gather_mode)
            P = len(t.profiles[level])
            schedule_kernel(
                self.game, kind, key,
                lambda g: builder(t, level, cblock, rd, fd, oh,
                                  fused_rank=fr, gather_mode=gm),
                self._avals(level, cblock, for_reach),
                heavy=P * cblock * 8 > (512 << 20),
                jit_kwargs=self._jit_kwargs(kind),
            )

        phases = [
            ("dense_step", range(nc, -1, -1), build_dense_step, False),
            ("dense_reach", range(1, nc + 1), build_reach_step, True),
        ]
        if reach_first:
            phases.reverse()
        for kind, levels, builder, for_reach in phases:
            for L in levels:
                sched(kind, L, builder, for_reach)

    def _binom_dev(self):
        """The [ncells+1, K] binomial table on device (uploaded once)."""
        self.tables.drop_stale_device_caches()
        if self.tables._dev_binom is None:
            rk = np.uint32 if self._rank_dtype == jnp.uint32 else np.uint64
            self.tables._dev_binom = jnp.asarray(
                self.tables.binom.astype(rk)
            )
        return self.tables._dev_binom

    def _upload_consts(self, level: int, for_reach: bool):
        """Per-level device constants. Kernels gather binom rows on device
        from the shared tiny table, so uploads here are small int arrays
        ([ncells, P] cell indices, [P, w] move maps — KBs per level, not
        the MBs the prebuilt binom-row layout would push through the
        relay's 30-60 MB/s pipe). Cached on the shared DenseTables so
        repeat solves re-use the device arrays."""
        t = self.tables
        t.drop_stale_device_caches()
        ck = (level, for_reach)
        if ck in t._dev_consts:
            return t._dev_consts[ck]
        consts = t.level_consts(level)

        def steps_first(a):  # [P, ..., ncells] -> [ncells, P, ...]
            return np.ascontiguousarray(
                np.moveaxis(a.astype(np.int32), -1, 0)
            )

        out = dict(
            binom=self._binom_dev(),
            cellidx=jnp.asarray(steps_first(consts["cellidx"])),
            filled=jnp.asarray(consts["filled"]),
        )
        if for_reach:
            out.update(
                topstone=jnp.asarray(consts["topstone"]),
                parent_row=jnp.asarray(consts["parent_row"]),
                parent_cellidx=jnp.asarray(
                    steps_first(consts["parent_cellidx"])
                ),
            )
        else:
            out.update(
                newbit=jnp.asarray(consts["newbit"]),
                valid=jnp.asarray(consts["valid"]),
                move_row=jnp.asarray(consts["move_row"]),
                move_fill=jnp.asarray(consts["move_fill"]),
                child_cellidx=jnp.asarray(
                    steps_first(consts["child_cellidx"])
                ),
                snapk=jnp.asarray(steps_first(consts["snapk"])),
            )
        t._dev_consts[ck] = out
        return out

    # -- reachability sweep -------------------------------------------------

    def _maybe_drain(self, added_cells: int, ref) -> bool:
        """Run-ahead control shared by every dense level loop (sweep,
        backward, and the hybrid's copies of both): after sync_cells cells
        of async dispatch, force a 1-byte fetch so the host cannot enqueue
        every level's buffers before any kernel retires (the round-2 OOM;
        see __init__)."""
        self._undrained = getattr(self, "_undrained", 0) + added_cells
        if self._undrained > self.sync_cells:
            np.asarray(ref[:1])
            self._undrained = 0
            return True
        return False

    def _sweep_levels(self, last_level: int):
        """The reach-sweep loop 1..last_level: -> (counts {0..last_level},
        reach_flat [P*C] u8 at last_level, on device). Shared by
        reachable_counts (full sweep) and the hybrid engine (sweep to its
        boundary); includes the run-ahead drain."""
        t = self.tables
        reach_flat = jnp.ones((1,), jnp.uint8)  # level 0: the root
        self._undrained = 0
        counts_dev: Dict[int, jnp.ndarray] = {}
        for L in range(1, last_level + 1):
            cblock, nblk = self._cblock(L)
            step = self._kernel("dense_reach", L, cblock, build_reach_step)
            consts = self._upload_consts(L, for_reach=True)
            blocks = []
            cnt = None
            for b in range(nblk):
                r_b, c_b = step(
                    self._rank0(b, cblock), reach_flat,
                    consts["binom"], consts["cellidx"], consts["filled"],
                    consts["topstone"], consts["parent_row"],
                    consts["parent_cellidx"],
                )
                blocks.append(r_b)
                cnt = c_b if cnt is None else cnt + c_b
            level_reach = (
                blocks[0] if nblk == 1 else jnp.concatenate(blocks, axis=1)
            )
            C = t.class_size[L]
            if nblk * cblock != C:
                level_reach = level_reach[:, :C]
            reach_flat = self._replicate(level_reach.reshape(-1))
            self._maybe_drain(len(t.profiles[L]) * C, reach_flat)
            counts_dev[L] = cnt
        counts = {0: 1}
        counts.update({L: int(v) for L, v in counts_dev.items()})
        return counts, reach_flat

    def reachable_counts(self) -> Dict[int, int]:
        """Exact per-level reachable-position counts (cached per process)."""
        cached = _REACH_COUNTS.get(self._board_key)
        if cached is not None:
            return cached
        cached = _load_cached_counts(self._board_key)
        if cached is not None:
            _REACH_COUNTS[self._board_key] = cached
            return cached
        self.schedule_compiles(reach_first=True)
        counts, _ = self._sweep_levels(self.tables.ncells)
        _REACH_COUNTS[self._board_key] = counts
        _store_cached_counts(self._board_key, counts)
        return counts

    def _backward_level(self, L: int, child_flat):
        """One dense backward level (blocked, no sync): the deeper level's
        flat cells -> this level's [P, C] cells. Shared by solve() and the
        hybrid's below-cutover loop."""
        t = self.tables
        C = t.class_size[L]
        cblock, nblk = self._cblock(L)
        step = self._kernel("dense_step", L, cblock, build_dense_step)
        consts = self._upload_consts(L, for_reach=False)
        blocks = []
        for b in range(nblk):
            blocks.append(step(
                self._rank0(b, cblock), child_flat,
                consts["binom"], consts["cellidx"], consts["filled"],
                consts["newbit"], consts["valid"],
                consts["move_row"], consts["move_fill"],
                consts["child_cellidx"], consts["snapk"],
            ))
        cells = blocks[0] if nblk == 1 else jnp.concatenate(blocks, axis=1)
        if nblk * cblock != C:
            cells = cells[:, :C]
        return cells

    # -- the solve ----------------------------------------------------------

    def solve(self) -> DenseSolveResult:
        g, t = self.game, self.tables
        nc = t.ncells
        t0 = time.perf_counter()
        encodable_total = 0
        saved: Optional[Dict[int, np.ndarray]] = (
            {} if self.store_tables else None
        )
        child_flat = jnp.zeros((1,), jnp.uint8)  # dummy for the top level
        start_L = nc
        if self.checkpointer is not None:
            # ":dense" namespaces the binding: these files are flat cell
            # arrays, not the classic engine's LevelTables — a directory
            # must never serve both.
            self.checkpointer.bind_game(g.name + ":dense")
            completed = set(self.checkpointer.dense_levels())
            K = nc + 1
            while K - 1 in completed:
                K -= 1
            if K <= nc:
                # Levels K..nc are on disk; rechain from K's cells. Only
                # K's file must actually be READ (plus all of them when
                # tables are materialized) — in --no-tables mode a resume
                # near level 0 of a big board would otherwise re-read the
                # whole multi-GB checkpoint just for shape checks. The
                # save-then-manifest ordering guarantees a LISTED level's
                # file is complete.
                for L in range(K, nc + 1):
                    P = len(t.profiles[L])
                    C = t.class_size[L]
                    encodable_total += P * C
                    if saved is None and L != K:
                        continue
                    cells = self.checkpointer.load_dense_level(L)
                    if cells.shape[0] != P * C:
                        raise ValueError(
                            f"checkpointed dense level {L} has "
                            f"{cells.shape[0]} cells, expected {P * C} — "
                            "stale checkpoint directory?"
                        )
                    if saved is not None:
                        saved[L] = cells.reshape(P, C)
                    if L == K:
                        child_flat = self._replicate(jnp.asarray(cells))
                if self.logger is not None:
                    self.logger.log({
                        "phase": "dense_backward_resume",
                        "levels_resumed": nc - K + 1, "from_level": K,
                    })
                start_L = K - 1
        levels_resumed = nc - start_L
        # After binding/resume: a refused directory or a fully-resumed run
        # must not have queued (then abandoned) a whole board's background
        # compiles; a partial resume bounds the dense_step set to what it
        # will actually run.
        if start_L >= 0:
            self.schedule_compiles(last_level=start_L)
        computed_encodable = 0
        self._undrained = 0
        last_drain = t0  # drains are the only real sync points, so they
        # are the only honest per-segment timestamps (dispatch is async)
        for L in range(start_L, -1, -1):
            P = len(t.profiles[L])
            C = t.class_size[L]
            encodable_total += P * C
            computed_encodable += P * C
            level_cells = self._backward_level(L, child_flat)
            child_flat = self._replicate(level_cells.reshape(-1))
            drained = self._maybe_drain(P * C, child_flat)
            if self.logger is not None:
                rec = {
                    "phase": "dense_backward", "level": L, "classes": P,
                    "class_size": C,
                }
                if drained:
                    now = time.perf_counter()
                    rec["secs_since_last_drain"] = round(now - last_drain, 4)
                    last_drain = now
                self.logger.log(rec)
            if saved is not None:
                saved[L] = np.asarray(level_cells).reshape(P, C)
            if self.checkpointer is not None:
                self.checkpointer.save_dense_level(
                    L, np.asarray(level_cells)
                )

        root_cell = int(jnp.reshape(child_flat, (-1,))[0])
        value, remoteness = root_cell & 3, root_cell >> 2
        solve_secs = time.perf_counter() - t0

        counted = _REACH_COUNTS.get(self._board_key)
        count_secs = 0.0
        if counted is None and self.count_positions != False:  # noqa: E712
            tc = time.perf_counter()
            counted = self.reachable_counts()
            count_secs = time.perf_counter() - tc
        positions = sum(counted.values()) if counted else 0

        stats = {
            "game": g.name,
            "engine": "dense",
            # EFFECTIVE mode, not the env request: the pallas-mesh safety
            # valve can demote it, and a published record attributing one
            # mode's numbers to another would corrupt the A/B evidence.
            "gather_mode": self.gather_mode,
            "devices": self.devices,
            "positions": positions,
            "encodable_positions": encodable_total,
            "levels": nc + 1,
            "secs_forward": 0.0,  # there is no forward pass
            "secs_backward": solve_secs,
            "secs_total": solve_secs,
            "secs_count_reachable": count_secs,  # excluded from secs_total:
            # a per-board constant, computed once per process, not part of
            # the solve (docs/ARCHITECTURE.md "Dense engine (Connect-4
            # family)").
            # A resumed run's elapsed time covers only the levels it
            # actually computed — attributing the whole board's positions
            # to it would overstate measured throughput (this repo
            # publishes these numbers); report 0 and the resumed count.
            "positions_per_sec": (
                positions / max(solve_secs, 1e-9)
                if levels_resumed == 0 else 0.0
            ),
            "levels_resumed": levels_resumed,
            "bytes_sorted": 0,
            # Operand bytes of the gathers this RUN issued (u8 cells).
            "bytes_gathered": computed_encodable * g.max_moves,
        }
        if counted:
            stats["reachable_per_level"] = counted
        if self.logger is not None:
            self.logger.log({"phase": "done", **stats})
        return DenseSolveResult(g, t, value, remoteness, saved, stats)
