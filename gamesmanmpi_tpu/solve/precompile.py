"""Background parallel kernel compilation.

On the axon-relayed TPU this project runs on, XLA compilation is a remote
RPC with a ~15 s floor PER PROGRAM regardless of size, executables cannot be
serialized (the persistent compilation cache silently stores nothing), and —
measured in tools/microbench.py — the compile service accepts concurrent
requests (4 compiles complete in ~11 s wall vs ~15-17 s for one). A solve
that naively compiles its ~30 shapes serially therefore spends ~8 minutes
compiling a ~30 s computation, which is exactly what BENCH_r02 measured.

This module turns compilation into background work: kernels are lowered
eagerly (cheap, host-side) and compiled on DAEMON worker threads, so the
solver overlaps compilation of upcoming capacities with execution of current
ones, the precise backward shapes (known the moment forward discovery ends)
compile while the deepest levels resolve — and speculative compiles still in
flight can never block interpreter exit (a stock ThreadPoolExecutor's
non-daemon workers would).

There is no reference counterpart (SURVEY.md §2.2 — the reference is pure
interpreted Python); this is infrastructure the XLA execution model makes
necessary, the moral analog of the reference relying on mpi4py being
imported once, not per message.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Dict, Hashable

import jax

from gamesmanmpi_tpu.utils.env import env_float, env_int


def _workers() -> int:
    return max(1, env_int("GAMESMAN_COMPILE_WORKERS", 8))


def _heavy_slots() -> int:
    """Concurrent-compile limit for HEAVY programs (big-capacity kernels).

    The relay's compile helper is a subprocess with finite memory: eight
    concurrent ~GB-working-set compiles crashed it (HTTP 500) on the 6x5
    uint64 board, while the same programs compile fine serially. Heavy jobs
    therefore share a small semaphore; light jobs keep the full pool.
    """
    return max(1, env_int("GAMESMAN_HEAVY_COMPILES", 2))


class Precompiler:
    """Schedules jit-function compilations on daemon worker threads.

    Keys match the engine's kernel-cache keys, so a kernel is compiled at
    most once per process whether it was scheduled ahead of time or demanded
    synchronously. `get` returns the AOT-compiled executable when the
    schedule won the race, else None (caller falls back to calling the jit
    function, which compiles inline). Successfully consumed futures are
    evicted so executables are owned by the caller's kernel cache, not
    pinned here.
    """

    def __init__(self):
        self._q: "queue.Queue" = queue.Queue()
        self._futures: Dict[Hashable, Future] = {}
        self._lock = threading.Lock()
        self._threads: list = []
        self._closed = False
        self._heavy_sem = threading.Semaphore(_heavy_slots())

    def _ensure_threads(self) -> None:
        # Caller holds self._lock (schedule does).
        if self._threads or self._closed:
            return
        for i in range(_workers()):
            t = threading.Thread(
                target=self._worker, name=f"gm-compile-{i}", daemon=True
            )
            t.start()
            self._threads.append(t)

    @staticmethod
    def _transient(e: Exception) -> bool:
        """Errors worth one retry: the relay compile service failing under
        load (HTTP 5xx / INTERNAL / UNAVAILABLE), not deterministic
        failures like an OOM-sized speculative shape (whose messages can
        embed arbitrary numbers — match structured markers only)."""
        msg = str(e)
        return any(
            t in msg for t in ("HTTP 5", "INTERNAL", "UNAVAILABLE")
        )

    def _worker(self) -> None:
        while True:
            item = self._q.get()
            if item is None:  # close() sentinel
                return
            fut, fn, avals, heavy = item
            if self._closed:
                # Cancelled by close(); resolve the future so a blocking
                # get() can never hang on a dead pool (this also covers a
                # heavy job requeued behind the close sentinels).
                fut.set_exception(RuntimeError("precompiler closed"))
                continue
            if heavy and not self._heavy_sem.acquire(blocking=False):
                if self._closed:
                    # Never requeue after close: the item could land behind
                    # the close sentinels with every worker already gone,
                    # leaving its future unresolved forever.
                    fut.set_exception(RuntimeError("precompiler closed"))
                    continue
                # No heavy slot free: requeue and stay available for light
                # jobs — heavy work must never park the whole pool.
                self._q.put(item)
                time.sleep(0.25)
                continue
            try:
                if not fut.set_running_or_notify_cancel():
                    continue
                try:
                    fut.set_result(fn.lower(*avals).compile())
                except Exception as e:  # noqa: BLE001 - maybe retry once
                    if not self._transient(e):
                        fut.set_exception(e)
                        continue
                    # Give the relay a breather and retry before giving up
                    # (the caller then falls back to an inline compile).
                    try:
                        time.sleep(8.0)
                        fut.set_result(fn.lower(*avals).compile())
                    except Exception as e2:  # noqa: BLE001
                        fut.set_exception(e2)
                except BaseException as e:  # noqa: BLE001 - report via future
                    # Never let the worker die with the future unresolved —
                    # a blocked get() would hang a solve forever.
                    fut.set_exception(e)
            finally:
                if heavy:
                    self._heavy_sem.release()

    def schedule(self, key: Hashable, fn, avals: tuple,
                 heavy: bool = False) -> None:
        """Schedule `fn.lower(*avals).compile()` in the background (idempotent).

        fn must be a jax.jit-wrapped callable; avals are
        jax.ShapeDtypeStruct leaves matching the call signature. heavy=True
        routes the job through the small heavy-compile semaphore (see
        _heavy_slots).
        """
        with self._lock:
            if key in self._futures or self._closed:
                return
            self._ensure_threads()
            fut = Future()
            self._futures[key] = fut
            self._q.put((fut, fn, avals, heavy))

    def get(self, key: Hashable, block: bool = True):
        """The compiled executable for `key`, or None if never scheduled.

        block=True waits for an in-flight compile (still a win: the wait is
        the residual, not the full compile, and other compiles progress
        meanwhile). A successful result is evicted — the caller caches it.
        """
        with self._lock:
            fut = self._futures.get(key)
        if fut is None:
            return None
        if not block and not fut.done():
            return None
        try:
            result = fut.result()
        except Exception:
            # A failed background compile (OOM-sized speculative cap, relay
            # hiccup) must not kill the solve — the caller's inline jit path
            # remains correct; drop the future so a retry is possible.
            result = None
        with self._lock:
            self._futures.pop(key, None)
        return result

    def scheduled(self, key: Hashable) -> bool:
        with self._lock:
            return key in self._futures

    def purge(self, predicate) -> None:
        """Drop scheduled futures whose key matches `predicate`.

        Used by the engine's stale-epoch sweep: after a genuine backend
        clear no old-epoch key can ever be fetched again, so keeping the
        futures would pin executables and their closed-over Mesh/device
        objects forever. Not-yet-running jobs are cancelled (the worker's
        set_running_or_notify_cancel skips them — no wasted ~15 s remote
        compile); in-flight ones finish and are garbage-collected with
        their future."""
        with self._lock:
            stale = [k for k in self._futures if predicate(k)]
            for k in stale:
                self._futures.pop(k).cancel()

    def close(self) -> None:
        """Stop the worker threads; jobs not yet running are cancelled
        (their futures resolve with an exception, so blocking get()s
        return None instead of hanging). The instance stays closed:
        schedule() becomes a no-op and get() reports the cancellations.

        The process-wide singleton never needs this (daemon threads die
        with the process); standalone instances — tests construct several —
        must close, or each leaks its worker pool for the process
        lifetime (a full-suite run accumulated 30+ idle compile threads
        this way).
        """
        with self._lock:
            if self._closed:
                return
            self._closed = True
            n = len(self._threads)
        # One sentinel per STARTED thread (the env-derived _workers() can
        # have changed since the pool started).
        for _ in range(n):
            self._q.put(None)
        # Drain jobs that were already queued BEHIND the sentinels: every
        # worker may exit on a sentinel before reaching them, which would
        # leave their futures unresolved and a blocking get() hung. The
        # sentinels consumed here are re-put for the workers.
        sentinels = 0
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is None:
                sentinels += 1
                continue
            fut = item[0]
            if not fut.done():
                try:
                    fut.set_exception(RuntimeError("precompiler closed"))
                except Exception:  # pragma: no cover - raced with a worker
                    pass
        for _ in range(sentinels):
            self._q.put(None)


_GLOBAL: Precompiler | None = None


def _atexit_drain() -> None:
    """Let in-flight compiles retire before interpreter teardown.

    Daemon threads die with the process — but one killed INSIDE an XLA
    compile aborts teardown (C++ "terminate called ... FATAL: exception
    not rethrown", observed when a solve scheduled kernels moments
    before process exit). Closing cancels everything still queued; the
    bounded join then waits out only compiles already on a worker. A
    wedged relay compile must not hang exit forever — hence the cap
    (GAMESMAN_COMPILE_EXIT_GRACE seconds, default 120).
    """
    pre = _GLOBAL
    if pre is None:
        return
    pre.close()
    grace = env_float("GAMESMAN_COMPILE_EXIT_GRACE", 120.0)
    deadline = time.time() + grace
    for t in pre._threads:
        t.join(timeout=max(0.0, deadline - time.time()))


def global_precompiler() -> Precompiler:
    global _GLOBAL
    if _GLOBAL is None:
        _GLOBAL = Precompiler()
        import atexit

        atexit.register(_atexit_drain)
    return _GLOBAL


def sds(shape, dtype, sharding=None) -> jax.ShapeDtypeStruct:
    """Shorthand ShapeDtypeStruct for schedule() avals.

    sharding: pass the NamedSharding the kernel will actually be called
    with for mesh-partitioned (shard_map) kernels — an AOT executable is
    strict about input shardings, so scheduling one with unsharded avals
    would compile a program the call site then rejects. The sharded
    engine's edge-backward prescheduling is the first user.
    """
    if sharding is not None:
        return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)
    return jax.ShapeDtypeStruct(shape, dtype)
