"""Hybrid dense/BFS solver for large Connect-4 boards.

The dense engine (solve/dense.py) pays for ENCODABLE positions — a
closed-form superset of the reachable set whose blowup concentrates in the
near-full levels (2.5x at 5x5 but 10-16x at 6x6/7x6, docs/ARCHITECTURE.md
"Hybrid candidate"). The BFS engine (solve/engine.py) pays for REACHABLE
positions but buys them with sort-heavy discovery and lookup joins. This
module composes them at a cutover level K:

* levels 0..K   — dense: no discovery, no sorts, 1 byte/position over the
  encodable set (its blowup is small at low levels);
* levels K+1..N — classic level-BFS over reachable positions only, exactly
  where the encodable superset explodes.

The seam needs only existing machinery plus two small kernels:

1. the dense reachability sweep (build_reach_step) runs UP to B = K+1 and
   keeps level B's reach mask;
2. `build_extract_step` turns level B's reachable (row, rank) cells into
   the game's packed guard-encoded states (packed = current | guards) —
   one sorted frontier, handed to the BFS forward;
3. the BFS engine solves levels B..N from that frontier (its forward
   starts at an arbitrary frontier since engine._forward_fast accepts
   one) and materializes level B's sparse (states, values, remoteness);
4. `build_boundary_step` resolves dense level K: children are constructed
   as packed states (child = opponent | (guards + newbit), the same
   branch-free drop as games/connect4.expand) and looked up in level B's
   sorted table by binary search / sort-join (ops.lookup lowering rules);
5. levels K-1..0 are standard dense steps chaining dense cell arrays.

Correctness across the seam: children of reachable positions are
reachable, so a reachable level-K parent can never miss the level-B
table; unreachable (garbage) parents may miss and absorb UNDECIDED
cells, but garbage is read only by garbage ancestors — the same
quarantine argument the pure dense engine makes for its encodable
superset (dense.py module docstring).

The cutover decision is a measured quantity (chip-rate dense vs BFS —
docs/CHIP_PLAN.md); the default is the 2/3 point recorded in the
ARCHITECTURE table, override with GAMESMAN_HYBRID_CUTOVER or the
`cutover=` argument.

Reference parity: this solves the same contract as the reference's
solver (value + remoteness of the root and, as a by-product, of every
reachable position — SURVEY.md §1); the engine split is pure
implementation strategy, pinned bit-identical to both component engines
in tests/test_hybrid.py.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from gamesmanmpi_tpu.core.bitops import sentinel_for
from gamesmanmpi_tpu.core.values import LOSE, UNDECIDED
from gamesmanmpi_tpu.games.connect4 import Connect4
from gamesmanmpi_tpu.ops.combine import combine_children
from gamesmanmpi_tpu.ops.dedup import sort_unique
from gamesmanmpi_tpu.ops.lookup import search_method
from gamesmanmpi_tpu.solve.dense import (
    DenseSolver,
    _connected_fold,
    _unrank_bits,
    n1_of_level,
)
from gamesmanmpi_tpu.solve.engine import Solver, get_kernel
from gamesmanmpi_tpu.utils.env import env_int_strict as _env_int_strict
from gamesmanmpi_tpu.utils.env import env_opt
from gamesmanmpi_tpu.utils.platform import platform_auto_bool


def default_cutover(ncells: int) -> int:
    """The 2/3 point: at 6x6 this is K=24, where encodable(<=K) = 3.1e10
    of the 6.0e11 total (ARCHITECTURE "Hybrid candidate" table) — the
    dense region keeps ~95% of the blowup out while still covering the
    bulk of the backward work. A measured chip ratio refines this."""
    return (2 * ncells) // 3


def build_extract_step(tables, level: int, cblock: int, rank_dtype,
                       use_onehot: bool, canon_fn=None):
    """Level-B frontier extraction: (row, rank) reach cells -> packed states.

    Returned fn:
      (rank0 rank_dtype scalar, reach [P, cblock] u8 block,
       binom, cellidx [ncells, P], filled [P], guards [P])
      -> packed [P, cblock] state_dtype, SENTINEL where not reachable
         (or rank past the class size).

    packed = current-player stones | guards (games/connect4.py encoding);
    at level B the player to move is p1 iff B is even.

    canon_fn (sym=1 only): the game's canonicalize, applied to the kept
    packed states so the handed-off frontier is mirror representatives —
    the BFS engines' tables are canonical, and a non-canonical frontier
    would seed them with both class members. Applied BEFORE sentinel
    fill: canonicalizing the sentinel would corrupt the padding.
    """
    ncells = tables.ncells
    dt = jnp.uint64 if tables.bits_dtype == np.uint64 else jnp.uint32
    n1 = n1_of_level(level)
    C = tables.class_size[level]
    current_is_p1 = level % 2 == 0
    bitpos = [int(b) for b in tables.bitpos]
    sentinel = sentinel_for(np.dtype(np.uint64 if dt == jnp.uint64
                                     else np.uint32))

    def step(rank0, reach, binom, cellidx, filled, guards):
        ranks = (rank0.astype(rank_dtype)
                 + jax.lax.iota(rank_dtype, cblock)[None, :])
        in_range = ranks < rank_dtype(C)
        p1 = _unrank_bits(ranks, n1, binom, cellidx, bitpos, dt, rank_dtype,
                          use_onehot)
        current = p1 if current_is_p1 else filled[:, None] ^ p1
        packed = current | guards[:, None]
        if canon_fn is not None:
            packed = canon_fn(packed)
        keep = (reach != 0) & in_range
        return jnp.where(keep, packed, dt(sentinel))

    return step


def build_boundary_step(tables, level: int, cblock: int, wcap: int,
                        rank_dtype, use_onehot: bool, method: str,
                        canon_fn=None):
    """Dense resolve of cutover level K against the sparse level-B table.

    Identical to build_dense_step except the child value source: instead
    of gathering cells from the dense level-(K+1) array, each child is
    CONSTRUCTED as a packed state (child = opponent | (guards + newbit_c),
    the branch-free drop of games/connect4.expand) and searched in the
    BFS level-B table (kstates [wcap] sorted + SENTINEL tail, kcells
    [wcap] dense-format u8 cells). Misses yield UNDECIDED — impossible
    for reachable parents (their children are reachable by construction),
    garbage-quarantined otherwise (module docstring).

    canon_fn (sym=1 only): children are canonicalized before the search —
    the level-B table holds mirror representatives, and the mirror
    preserves value and remoteness, so the representative's cell IS the
    child's (the same rule canonical_children applies inside the BFS
    backward).

    Returned fn:
      (rank0, kstates [wcap], kcells [wcap] u8,
       binom, cellidx, filled, guards, newbit [P, w], valid [P, w])
      -> cells [P, cblock] u8 (value | remoteness << 2)
    """
    w, h, connect = tables.width, tables.height, tables.connect
    dt = jnp.uint64 if tables.bits_dtype == np.uint64 else jnp.uint32
    n1 = n1_of_level(level)
    p1_moves = level % 2 == 0     # player moving OUT of level K
    mover_is_p1 = level % 2 == 1  # player who made the ply INTO it
    bitpos = [int(b) for b in tables.bitpos]

    def step(rank0, kstates, kcells, binom, cellidx, filled, guards,
             newbit, valid):
        P = filled.shape[0]
        p1 = _unrank_bits(
            (rank0.astype(rank_dtype)
             + jax.lax.iota(rank_dtype, cblock)[None, :]),
            n1, binom, cellidx, bitpos, dt, rank_dtype, use_onehot,
        )
        p2 = filled[:, None] ^ p1
        mover = p1 if mover_is_p1 else p2
        current = p2 if mover_is_p1 else p1
        mover_line = _connected_fold(mover, h, connect, dt)
        current_line = _connected_fold(current, h, connect, dt)
        prim_mask = mover_line | current_line

        opponent = p2 if p1_moves else p1  # not moving out of K
        child_vals, child_rems, masks = [], [], []
        for c in range(w):
            child = opponent | (guards[:, None] + newbit[:, c : c + 1])
            if canon_fn is not None:
                child = canon_fn(child)
            idx = jnp.searchsorted(
                kstates, child.reshape(-1), method=method
            )
            idx = jnp.clip(idx, 0, kstates.shape[0] - 1).astype(jnp.int32)
            hit = kstates[idx] == child.reshape(-1)
            cell = jnp.where(
                hit, kcells[idx], jnp.uint8(UNDECIDED)
            ).reshape(child.shape)
            child_vals.append(cell & jnp.uint8(3))
            child_rems.append((cell >> jnp.uint8(2)).astype(jnp.int32))
            masks.append(valid[:, c : c + 1] & jnp.ones((1, cblock), bool))

        cv = jnp.stack(child_vals, axis=-1).reshape(P * cblock, w)
        cr = jnp.stack(child_rems, axis=-1).reshape(P * cblock, w)
        mk = (jnp.stack(masks, axis=-1)
              & ~prim_mask[..., None]).reshape(P * cblock, w)
        values, rem_out = combine_children(cv, cr, mk)
        values = values.reshape(P, cblock)
        rem_out = rem_out.reshape(P, cblock)
        values = jnp.where(prim_mask, jnp.uint8(LOSE), values)
        rem_out = jnp.where(prim_mask, 0, rem_out)
        return values | (jnp.clip(rem_out, 0, 63).astype(jnp.uint8)
                         << jnp.uint8(2))

    return step


def build_boundary_children_step(tables, level: int, cblock: int,
                                 rank_dtype, use_onehot: bool,
                                 canon_fn=None):
    """Streamed boundary, phase 1: one rank block's packed children.

    Returned fn:
      (rank0, binom, cellidx, filled, guards, newbit)
      -> (children [P, cblock, w] state_dtype, prim_mask [P, cblock] bool)

    Same unrank/line/drop algebra as build_boundary_step, but the children
    are EMITTED so the per-window-block lookups (phase 2) never repeat the
    unrank walks — the dense engine's whole economy is amortizing them.
    canon_fn (sym=1): children are emitted as mirror representatives, so
    phase 2's searches hit the canonical level-B blocks.
    """
    w, h, connect = tables.width, tables.height, tables.connect
    dt = jnp.uint64 if tables.bits_dtype == np.uint64 else jnp.uint32
    n1 = n1_of_level(level)
    p1_moves = level % 2 == 0
    mover_is_p1 = level % 2 == 1
    bitpos = [int(b) for b in tables.bitpos]

    def step(rank0, binom, cellidx, filled, guards, newbit):
        p1 = _unrank_bits(
            (rank0.astype(rank_dtype)
             + jax.lax.iota(rank_dtype, cblock)[None, :]),
            n1, binom, cellidx, bitpos, dt, rank_dtype, use_onehot,
        )
        p2 = filled[:, None] ^ p1
        mover = p1 if mover_is_p1 else p2
        current = p2 if mover_is_p1 else p1
        prim_mask = (_connected_fold(mover, h, connect, dt)
                     | _connected_fold(current, h, connect, dt))
        opponent = p2 if p1_moves else p1
        children = jnp.stack(
            [opponent | (guards[:, None] + newbit[:, c : c + 1])
             for c in range(w)],
            axis=-1,
        )
        if canon_fn is not None:
            children = canon_fn(children)
        return children, prim_mask

    return step


def build_boundary_lookup_acc_step(method: str):
    """Streamed boundary, phase 2 (once per window block): search one
    SORTED block of the level-B table and accumulate hit cells.

    Blocks partition a sorted table, so each child hits in at most one
    block; a hit cell is nonzero (decided value), so accumulate is a
    select — the same invariant as the sharded streamed window
    (parallel/sharded._sharded_lookup_acc_step).

    Returned fn: (children_flat [N], acc [N] u8, kstates [wb],
    kcells [wb] u8) -> acc' [N] u8.

    Deliberately NOT ops.lookup.lookup_sorted: its fused one-gather
    payload applies only to uint32 states, and every board big enough to
    need streaming (6x5+) packs in uint64 — where lookup_sorted's
    separate (u8 value, i32 remoteness) arrays would also 5x the
    per-block host->device upload this path exists to minimize. The
    1-byte dense cell keeps the stream at (state + 1 B) per entry.
    """

    def step(children_flat, acc, kstates, kcells):
        idx = jnp.searchsorted(kstates, children_flat, method=method)
        idx = jnp.clip(idx, 0, kstates.shape[0] - 1).astype(jnp.int32)
        hit = kstates[idx] == children_flat
        return jnp.where(hit, kcells[idx], acc)

    return step


def build_boundary_combine_step(cblock: int, w: int):
    """Streamed boundary, phase 3: accumulated child cells -> level-K cells.

    Returned fn: (acc [P, cblock, w] u8, prim_mask [P, cblock] bool,
    valid [P, w] bool) -> cells [P, cblock] u8 — the exact combine tail of
    build_boundary_step.
    """

    def step(acc, prim_mask, valid):
        P = valid.shape[0]
        cv = (acc & jnp.uint8(3)).reshape(P * cblock, w)
        cr = (acc >> jnp.uint8(2)).astype(jnp.int32).reshape(P * cblock, w)
        mk = (valid[:, None, :] & ~prim_mask[..., None]).reshape(
            P * cblock, w
        )
        values, rem_out = combine_children(cv, cr, mk)
        values = values.reshape(P, cblock)
        rem_out = rem_out.reshape(P, cblock)
        values = jnp.where(prim_mask, jnp.uint8(LOSE), values)
        rem_out = jnp.where(prim_mask, 0, rem_out)
        return values | (jnp.clip(rem_out, 0, 63).astype(jnp.uint8)
                         << jnp.uint8(2))

    return step


def _concat_trim(blocks, nblk: int, cblock: int, C: int):
    """Join per-rank-block [P, cblock] results and trim the pad lanes of
    the ragged last block — the one tail both boundary lowerings share."""
    cells = blocks[0] if nblk == 1 else jnp.concatenate(blocks, axis=1)
    if nblk * cblock != C:
        cells = cells[:, :C]
    return cells


class HybridSolveResult:
    """Duck-typed SolveResult: dense cells below the cutover, sparse BFS
    tables above it."""

    def __init__(self, game, tables, cutover: int, value: int,
                 remoteness: int, cells, bfs_levels, stats: dict):
        self.game = game
        self._tables = tables
        self.cutover = cutover
        self.value = int(value)
        self.remoteness = int(remoteness)
        self.cells = cells            # {level<=K: [P, C] u8} or None
        self.levels = bfs_levels      # {level>K: LevelTable} or None
        self.stats = stats

    @property
    def num_positions(self) -> int:
        return self.stats["positions"]

    def lookup(self, state) -> tuple[int, int]:
        """(value, remoteness) of a packed position from whichever side of
        the cutover owns its level. Dense-side semantics match
        DenseSolveResult.lookup (answers for the encodable superset,
        refuses the fabricated mover-already-won class); BFS-side matches
        SolveResult.lookup (reachable positions only)."""
        state = int(state)
        level, row, rank = self._tables.locate(state)
        if level <= self.cutover:
            if self.cells is None:
                raise KeyError("solved in no-tables mode")
            if self._tables.current_player_has_line(level, row, rank):
                raise KeyError(
                    f"state {state:#x} is not a position (the player to "
                    "move already has a line); its cell is a placeholder"
                )
            cell = int(self.cells[level][row, rank])
            return cell & 3, cell >> 2
        if self.levels is None:
            raise KeyError("solved in no-tables mode")
        if self.game.sym:
            # BFS-side tables hold mirror representatives; canonicalize
            # the query so either class member answers (the dense side
            # above needs no such step — it indexes the full space).
            from gamesmanmpi_tpu.solve.engine import canonical_scalar

            state, level = canonical_scalar(self.game, state)
        table = self.levels.get(level)
        if table is not None:
            i = int(np.searchsorted(table.states, state))
            if i < table.states.shape[0] and int(table.states[i]) == state:
                return int(table.values[i]), int(table.remoteness[i])
        raise KeyError(f"state {state:#x} not reachable/solved")


class HybridSolver:
    """Compose the dense engine (levels <= cutover) with level-BFS
    (levels > cutover) — see the module docstring.

    cutover: last dense level K (0 <= K < ncells). None reads
    GAMESMAN_HYBRID_CUTOVER, else default_cutover(ncells).

    devices: 1 = fully single-device; >1 = BOTH regions use the mesh —
    the BFS region (where the reachable set and the sort work live) runs
    the owner-routed ShardedSolver, and the dense region's sweep and
    backward rank-partition their level kernels over the same mesh
    (DenseSolver devices=N; docs/ARCHITECTURE.md "Mesh-partitioned
    dense"). Only the boundary join and frontier extraction stay
    single-device: they are one level's worth of work at the cutover,
    which the HBM-pair bound already forces to be small (a cutover whose
    boundary does not fit one chip is the wrong cutover — see the
    ARCHITECTURE capacity table).
    """

    def __init__(self, game: Connect4, cutover: Optional[int] = None,
                 store_tables: bool = True, logger=None,
                 devices: int = 1):
        if not isinstance(game, Connect4):
            raise TypeError("HybridSolver requires a Connect4-family game")
        self.game = game
        # sym=1: the BFS region keeps the mirror reduction (it is where
        # the reachable-set cost lives — the v4-16 6x6 plan budgets its
        # per-chip peak level WITH sym), while the dense region indexes
        # the FULL space through a sym-free twin: dense perfect indexing
        # enumerates (row, rank) classes and cannot skip mirror
        # duplicates, and its low levels are the cheap side of the
        # cutover. The seam canonicalizes in both directions (extracted
        # frontier -> representatives; boundary-join children ->
        # representatives before the level-B search), mirroring what
        # canonical_children does inside both BFS engines.
        self.dense_game = (
            Connect4(game.width, game.height, game.connect, sym=False)
            if game.sym else game
        )
        self.store_tables = store_tables
        self.logger = logger
        self.devices = int(devices)
        if self.devices < 1:
            raise ValueError(f"devices must be >= 1, got {devices}")
        #: window blocks streamed through the boundary join (observable
        #: for the streamed-path tests; 0 = the table stayed resident).
        self.boundary_stream_blocks = 0
        # Boundary-join capacity knobs, parsed HERE so a typo fails fast
        # with a clear message instead of a raw traceback after the sweep
        # and the whole BFS phase have already run (the join reads them
        # last).
        self.resident_mb = _env_int_strict("GAMESMAN_HYBRID_RESIDENT_MB",
                                           2048)
        self.wblock = _env_int_strict("GAMESMAN_HYBRID_WBLOCK", 1 << 22)
        # The dense half (kernels, consts, tables); its reach sweep is run
        # partially by this class, so disable its own full sweep. devices
        # passes through: the dense region's level kernels rank-partition
        # over the same mesh the BFS region shards over (the capacity-plan
        # composition for 6x6 — docs/ARCHITECTURE.md "Mesh-partitioned
        # dense"); the boundary join stays single-device.
        self.dense = DenseSolver(self.dense_game,
                                 store_tables=store_tables,
                                 logger=logger, count_positions=False,
                                 devices=self.devices)
        self.tables = self.dense.tables
        nc = self.tables.ncells
        if cutover is None:
            env = env_opt("GAMESMAN_HYBRID_CUTOVER")
            if env:
                try:
                    cutover = int(env)
                except ValueError:
                    raise ValueError(
                        f"GAMESMAN_HYBRID_CUTOVER={env!r} is not an integer"
                    ) from None
            else:
                cutover = default_cutover(nc)
        if not 0 <= cutover < nc:
            raise ValueError(
                f"cutover must be in [0, {nc}) for a {nc}-cell board, "
                f"got {cutover}"
            )
        self.cutover = int(cutover)

    # ------------------------------------------------------------- phases

    def _log(self, **rec) -> None:
        if self.logger is not None:
            self.logger.log(rec)

    def _sweep_to_boundary(self):
        """Dense reachability sweep 0..B; returns (per-level counts 0..B,
        level-B reach array [P*C] on device). The loop itself — including
        the run-ahead drain that keeps big boards from enqueueing every
        level before a kernel retires — is DenseSolver._sweep_levels."""
        return self.dense._sweep_levels(self.cutover + 1)

    def _extract_frontier(self, reach_flat) -> np.ndarray:
        """Level-B reachable (row, rank) cells -> sorted packed states."""
        d, t, g = self.dense, self.tables, self.game
        B = self.cutover + 1
        P = len(t.profiles[B])
        C = t.class_size[B]
        cblock, nblk = d._cblock(B)
        consts = d._upload_consts(B, for_reach=True)
        guards = jnp.asarray(t.level_consts(B)["guards"])
        reach = reach_flat.reshape(P, C)

        def key(kind):
            return (kind, self.tables.width, self.tables.height,
                    self.tables.connect, B, cblock, d.use_onehot)

        # Keyed on the SYM game (g.cache_key embeds the _sym name): the
        # canonicalizing and plain extract programs must never share a
        # cache entry.
        canon = g.canonicalize if g.sym else None
        step = get_kernel(
            g, "hyx", key("hyx"),
            lambda _g: build_extract_step(
                t, B, cblock, d._rank_dtype, d.use_onehot, canon_fn=canon
            ),
        )
        pieces = []
        for b in range(nblk):
            lo = b * cblock
            blk = jax.lax.slice(
                reach, (0, lo), (P, min(lo + cblock, C))
            )
            if blk.shape[1] != cblock:  # ragged last block: pad with 0s
                blk = jnp.concatenate(
                    [blk, jnp.zeros((P, cblock - blk.shape[1]), jnp.uint8)],
                    axis=1,
                )
            packed = step(
                d._rank0(b, cblock), blk,
                consts["binom"], consts["cellidx"], consts["filled"],
                guards,
            )
            # Distinct (row, rank) are distinct positions, so without sym
            # this is pure compaction; with sym two cells can share a
            # representative, making the per-block unique a real dedup.
            uniq, count = sort_unique(packed.reshape(-1))
            n = int(count)
            if n:
                pieces.append(np.asarray(uniq[:n]))
        if not pieces:
            return np.empty(0, dtype=g.state_dtype)
        frontier = np.concatenate(pieces)
        if g.sym:
            # Mirror pairs can fall in different rank blocks; the host
            # merge must dedup ACROSS blocks too, not just sort.
            return np.unique(frontier)
        frontier.sort()
        return frontier

    def _dense_cell_table(self, bfs_table) -> tuple:
        """BFS LevelTable -> (sorted padded states, dense u8 cells) HOST
        arrays for the boundary join (uploaded whole in resident mode,
        block-sliced in streamed mode)."""
        from gamesmanmpi_tpu.ops.padding import pad_to_bucket

        states = pad_to_bucket(bfs_table.states)
        cells = np.zeros(states.shape[0], np.uint8)
        n = bfs_table.states.shape[0]
        cells[:n] = (
            bfs_table.values.astype(np.uint8)
            | (np.clip(bfs_table.remoteness, 0, 63).astype(np.uint8) << 2)
        )
        return states, cells

    def _resolve_boundary(self, kstates, kcells):
        """Dense level-K cells resolved against the sparse level-B table.

        Two lowerings, chosen by the table's size against
        GAMESMAN_HYBRID_RESIDENT_MB (default 2 GiB):

        * resident — the whole (states, cells) table lives in HBM and one
          fused kernel per rank block searches it (build_boundary_step);
        * streamed — the table stays on HOST and is streamed through HBM
          in GAMESMAN_HYBRID_WBLOCK-position blocks: children materialize
          once per rank block (phase 1), each sorted block is searched
          with hits accumulated by select (phase 2, at most one hit per
          child across the stream), one combine per rank block (phase 3).
          HBM then holds O(rank block + window block), decoupling the
          join from reachable(B) — the same mechanism as the sharded
          solver's streamed window. Known cost: the table re-uploads once
          per rank block.
        """
        d, t, g = self.dense, self.tables, self.game
        K = self.cutover
        P = len(t.profiles[K])
        C = t.class_size[K]
        cblock, nblk = d._cblock(K)
        consts = d._upload_consts(K, for_reach=False)
        guards = jnp.asarray(t.level_consts(K)["guards"])
        wcap = int(kstates.shape[0])
        sm = search_method()
        w = t.width

        def kkey(kind, *extra):
            return (kind, t.width, t.height, t.connect, K, cblock,
                    d.use_onehot) + extra

        canon = g.canonicalize if g.sym else None
        table_bytes = wcap * (kstates.dtype.itemsize + 1)
        if table_bytes <= self.resident_mb << 20:
            step = get_kernel(
                g, "hyb", kkey("hyb", wcap, sm),
                lambda _g: build_boundary_step(
                    t, K, cblock, wcap, d._rank_dtype, d.use_onehot, sm,
                    canon_fn=canon,
                ),
            )
            ks_dev, kc_dev = jnp.asarray(kstates), jnp.asarray(kcells)
            blocks = []
            for b in range(nblk):
                blocks.append(step(
                    d._rank0(b, cblock), ks_dev, kc_dev,
                    consts["binom"], consts["cellidx"], consts["filled"],
                    guards, consts["newbit"], consts["valid"],
                ))
            return _concat_trim(blocks, nblk, cblock, C)

        # Streamed path.
        wb = min(max(256, 1 << (self.wblock - 1).bit_length()), wcap)
        children_step = get_kernel(
            g, "hybc", kkey("hybc"),
            lambda _g: build_boundary_children_step(
                t, K, cblock, d._rank_dtype, d.use_onehot, canon_fn=canon
            ),
        )
        acc_step = get_kernel(
            g, "hyba", kkey("hyba", wb, sm),
            lambda _g: build_boundary_lookup_acc_step(sm),
        )
        combine_step = get_kernel(
            g, "hybk", kkey("hybk"),
            lambda _g: build_boundary_combine_step(cblock, w),
        )
        blocks = []
        for b in range(nblk):
            children, prim = children_step(
                d._rank0(b, cblock),
                consts["binom"], consts["cellidx"], consts["filled"],
                guards, consts["newbit"],
            )
            flat = children.reshape(-1)
            acc = jnp.zeros(flat.shape, jnp.uint8)
            for off in range(0, wcap, wb):
                acc = acc_step(
                    flat, acc,
                    jnp.asarray(kstates[off : off + wb]),
                    jnp.asarray(kcells[off : off + wb]),
                )
                self.boundary_stream_blocks += 1
            blocks.append(combine_step(
                acc.reshape(P, cblock, w), prim, consts["valid"]
            ))
        return _concat_trim(blocks, nblk, cblock, C)

    # -------------------------------------------------------------- solve

    def solve(self) -> HybridSolveResult:
        g, t, d = self.game, self.tables, self.dense
        K = self.cutover
        B = K + 1
        t0 = time.perf_counter()
        # Background-compile the dense region's kernels (bounded at B —
        # levels past the cutover belong to the BFS engine).
        d.schedule_compiles(reach_first=True, last_level=B)

        # Phase 1-2: dense sweep to the boundary, extract the BFS frontier.
        counts, reach_flat = self._sweep_to_boundary()
        frontier = self._extract_frontier(reach_flat)
        if g.sym:
            # The sweep counts the FULL reachable set at B; extraction
            # canonicalizes, so representatives number between half and
            # all of it (self-mirror positions keep the count above N/2).
            ok = (counts[B] == 0 and frontier.shape[0] == 0) or (
                counts[B] // 2 <= frontier.shape[0] <= counts[B]
            )
        else:
            ok = frontier.shape[0] == counts[B]
        if not ok:
            raise RuntimeError(
                f"hybrid seam: extracted {frontier.shape[0]} level-{B} "
                f"states but the sweep counted {counts[B]} "
                f"(sym={int(g.sym)}) — extraction/sweep disagree"
            )
        t_sweep = time.perf_counter() - t0
        # frontier = what is HANDED to the BFS region (representatives
        # under sym=1); reachable = the sweep's full-space count. Equal
        # without sym; both logged so the ~2x sym gap is auditable.
        self._log(phase="hybrid_sweep", boundary=B,
                  frontier=int(frontier.shape[0]), reachable=counts[B],
                  secs=round(t_sweep, 3))

        # Phase 3: BFS over levels B..N from the extracted frontier —
        # single-device or owner-routed sharded, per `devices`. The
        # engines' internals are driven directly (no root lookup), so the
        # solve()-time knob resolution happens here for the single-device
        # path; the sharded path resolves its own.
        if self.devices > 1:
            from gamesmanmpi_tpu.parallel import ShardedSolver

            bfs = ShardedSolver(g, num_shards=self.devices,
                                store_tables=self.store_tables)
            bfs.materialize_root_table = True  # the boundary join reads B
            levels = bfs._forward_fast(frontier, B)
            bfs_counts = {L: int(rec.counts.sum())
                          for L, rec in levels.items()}
            resolved = bfs._backward(
                levels, B, int(frontier[0]) if frontier.size else 0
            )
        else:
            bfs = Solver(g, store_tables=self.store_tables)
            bfs.use_provenance = platform_auto_bool(
                "GAMESMAN_PROVENANCE", accel=True, cpu=False
            )
            levels = bfs._forward_fast(frontier, B)
            bfs_counts = {L: rec.n for L, rec in levels.items()}
            resolved = bfs._backward_fast(levels, root_level=B)
        k1_table = resolved[B]
        t_bfs = time.perf_counter() - t0 - t_sweep
        self._log(phase="hybrid_bfs", levels=len(bfs_counts),
                  positions=sum(bfs_counts.values()), secs=round(t_bfs, 3))

        # Phase 4: the boundary join at K.
        kstates, kcells = self._dense_cell_table(k1_table)
        boundary_cells = self._resolve_boundary(kstates, kcells)

        # Phase 5: standard dense backward K-1..0 chained from the boundary
        # (DenseSolver._backward_level, with its run-ahead drain).
        saved = {} if self.store_tables else None
        if saved is not None:
            saved[K] = np.asarray(boundary_cells)
        # _replicate: the boundary kernel's output (and each chained
        # level's sharded cells) must be mesh-replicated before feeding
        # the next rank-partitioned level kernel (same chaining rule as
        # DenseSolver.solve; no-op at devices=1).
        child_flat = d._replicate(boundary_cells.reshape(-1))
        d._undrained = 0
        for L in range(K - 1, -1, -1):
            P = len(t.profiles[L])
            C = t.class_size[L]
            cells = d._backward_level(L, child_flat)
            child_flat = d._replicate(cells.reshape(-1))
            d._maybe_drain(P * C, child_flat)
            if saved is not None:
                saved[L] = np.asarray(cells).reshape(P, C)

        root_cell = int(jnp.reshape(child_flat, (-1,))[0])
        value, remoteness = root_cell & 3, root_cell >> 2
        t_total = time.perf_counter() - t0

        positions = (sum(v for L, v in counts.items() if L <= K)
                     + sum(bfs_counts.values()))
        stats = {
            "game": g.name,
            "engine": "hybrid",
            "cutover": K,
            # With sym=1 the two regions count DIFFERENT things: the
            # dense region the full reachable set (it indexes the full
            # space), the BFS region mirror representatives — the
            # breakdown keys make the mixed total auditable.
            "positions": positions,
            "positions_dense_region": sum(
                v for L, v in counts.items() if L <= K),
            "positions_bfs_region": sum(bfs_counts.values()),
            "positions_per_sec": positions / max(t_total, 1e-9),
            # Discovery = sweep + extraction; everything after is resolve.
            "secs_forward": t_sweep,
            "secs_backward": t_total - t_sweep,
            "secs_total": t_total,
            "secs_bfs": t_bfs,
            "bytes_sorted": bfs.bytes_sorted,
            "bytes_gathered": bfs.bytes_gathered,
            # Canonical size actually seeded into the BFS region; the
            # full-space sweep count sits alongside (equal when sym=0).
            "frontier_at_boundary": int(frontier.shape[0]),
            "reachable_at_boundary": counts[B],
        }
        self._log(phase="done", **{k: v for k, v in stats.items()
                                   if k != "game"})
        return HybridSolveResult(
            g, t, K, value, remoteness, saved,
            dict(resolved) if self.store_tables else None, stats,
        )
