"""The level-synchronous retrograde solver (single device).

This replaces the reference's entire L1 distributed runtime — the Process
event loop, priority work queue and Job dispatch table (src/process.py,
src/job.py; SURVEY.md §2.2, §3.2-3.4) — with two bulk phases per level.
The Job types map as follows:

  reference Job (SURVEY.md §2.2)   here
  -------------------------------  -------------------------------------------
  LOOK_UP / DISTRIBUTE             forward pass: expand a whole level's
                                   frontier in one vmapped kernel; children are
                                   dedup'd (sort-unique) and merged into their
                                   level's pool instead of being mailed to
                                   owner ranks one Job at a time.
  CHECK_FOR_UPDATES                gone — no polling; the level barrier is the
                                   only synchronization.
  SEND_BACK / RESOLVE              backward pass: for each level (deepest
                                   first) regenerate children, look their
                                   values up in already-solved deeper levels
                                   (ops.lookup), and combine (ops.combine).
  FINISHED                         the backward loop reaching the root level.

Scheduling differs from the reference by design (SURVEY.md §2.4: asynchronous
small-message actors are anti-idiomatic on TPU); observable behavior — the
(value, remoteness) of every reachable position — is preserved and tested
against a pure-Python oracle.

The forward/backward orchestration is a host loop (level count is tiny — tens
of iterations); all per-position work runs inside jitted kernels with bucketed
static shapes (ops.padding), so the set of compiled programs is small and
reused across levels.
"""

from __future__ import annotations

import time
from typing import Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from gamesmanmpi_tpu.core.bitops import SENTINEL
from gamesmanmpi_tpu.core.values import UNDECIDED
from gamesmanmpi_tpu.games.base import TensorGame
from gamesmanmpi_tpu.ops.combine import combine_children
from gamesmanmpi_tpu.ops.dedup import sort_unique
from gamesmanmpi_tpu.ops.lookup import lookup_window
from gamesmanmpi_tpu.ops.padding import MIN_BUCKET, pad_to_bucket


class LevelTable(NamedTuple):
    """Solved records for one level: parallel arrays sorted by state."""

    states: np.ndarray  # uint64, sorted ascending
    values: np.ndarray  # uint8
    remoteness: np.ndarray  # int32


class SolveResult:
    """Full solve output: root answer + per-level tables + stats."""

    def __init__(self, game: TensorGame, value: int, remoteness: int,
                 levels: Dict[int, LevelTable], stats: dict):
        self.game = game
        self.value = int(value)
        self.remoteness = int(remoteness)
        self.levels = levels
        self.stats = stats

    @property
    def num_positions(self) -> int:
        return sum(t.states.shape[0] for t in self.levels.values())

    def lookup(self, state) -> tuple[int, int]:
        """(value, remoteness) of any reachable packed state."""
        state = np.uint64(state)
        level = int(
            np.asarray(self.game.level_of(jnp.asarray([state], jnp.uint64)))[0]
        )
        table = self.levels.get(level)
        if table is not None:
            i = np.searchsorted(table.states, state)
            if i < table.states.shape[0] and table.states[i] == state:
                return int(table.values[i]), int(table.remoteness[i])
        raise KeyError(f"state {state:#x} not reachable/solved")


class SolverError(RuntimeError):
    pass


class Solver:
    """Single-device level-synchronous solver for a TensorGame."""

    def __init__(
        self,
        game: TensorGame,
        *,
        min_bucket: int = MIN_BUCKET,
        paranoid: bool = False,
        logger=None,
        checkpointer=None,
    ):
        self.game = game
        self.min_bucket = min_bucket
        self.paranoid = paranoid
        self.logger = logger
        self.checkpointer = checkpointer
        self._expand_jit = jax.jit(self._expand_impl)
        self._resolve_jit = jax.jit(self._resolve_impl)

    # ---------------------------------------------------------------- kernels

    def _expand_impl(self, states):
        """[B] states -> (unique children [B*M] sorted, their levels, count)."""
        g = self.game
        valid = states != SENTINEL
        prim = g.primitive(states)
        expandable = valid & (prim == UNDECIDED)
        children, mask = g.expand(states)
        mask = mask & expandable[:, None]
        children = jnp.where(mask, children, SENTINEL)
        uniq, count = sort_unique(children.reshape(-1))
        levels = jnp.where(uniq != SENTINEL, g.level_of(uniq), -1)
        return uniq, levels, count

    def _resolve_impl(self, states, window):
        """[B] states + solved deeper levels -> (values, remoteness, misses)."""
        g = self.game
        valid = states != SENTINEL
        prim = g.primitive(states)
        undecided = valid & (prim == UNDECIDED)
        children, mask = g.expand(states)
        mask = mask & undecided[:, None]
        children = jnp.where(mask, children, SENTINEL)
        child_vals, child_rem, hit = lookup_window(children, window)
        values, remoteness = combine_children(child_vals, child_rem, mask)
        values = jnp.where(undecided, values, jnp.where(valid, prim, UNDECIDED))
        remoteness = jnp.where(undecided, remoteness, 0)
        # Consistency counters (SURVEY.md §5.2): child lookups that missed the
        # solved window, and non-primitive positions with zero legal moves
        # (a game-definition error — they would silently score LOSE/0).
        misses = jnp.sum(mask & ~hit) + jnp.sum(undecided & ~jnp.any(mask, axis=-1))
        return values, remoteness, misses

    # ----------------------------------------------------------------- phases

    def _forward(self, pools: Dict[int, np.ndarray], start_level: int) -> dict:
        """Discover all reachable states, grouped into per-level pools."""
        g = self.game
        stats_levels = {}
        k = start_level
        while pools and k <= max(pools):
            if k not in pools:
                k += 1
                continue
            t0 = time.perf_counter()
            frontier = pools[k]
            padded = pad_to_bucket(frontier, self.min_bucket)
            uniq, levels, count = self._expand_jit(padded)
            n = int(count)
            kids = np.asarray(uniq[:n])
            kid_levels = np.asarray(levels[:n])
            for lv in np.unique(kid_levels):
                lv = int(lv)
                batch = kids[kid_levels == lv]
                if lv in pools:
                    pools[lv] = np.union1d(pools[lv], batch)
                else:
                    pools[lv] = batch
            dt = time.perf_counter() - t0
            stats_levels[k] = {
                "phase": "forward",
                "level": k,
                "frontier": int(frontier.shape[0]),
                "children": n,
                "secs": dt,
            }
            if self.logger is not None:
                self.logger.log(stats_levels[k])
            k += 1
        return stats_levels

    def _backward(self, pools: Dict[int, np.ndarray]) -> Dict[int, LevelTable]:
        """Resolve all levels deepest-first against the solved window.

        Levels already present in the checkpoint (a previous, preempted run)
        are loaded instead of recomputed — restart-from-level recovery.
        """
        g = self.game
        resolved: Dict[int, LevelTable] = {}
        padded_cache: Dict[int, tuple] = {}
        completed = (
            set(self.checkpointer.completed_levels())
            if self.checkpointer is not None
            else set()
        )
        for k in sorted(pools, reverse=True):
            t0 = time.perf_counter()
            states = pools[k]
            padded = pad_to_bucket(states, self.min_bucket)
            n = states.shape[0]
            from_checkpoint = k in completed
            if from_checkpoint:
                table = self.checkpointer.load_level(k)
                if table.states.shape[0] != n or not (table.states == states).all():
                    raise SolverError(
                        f"checkpointed level {k} does not match the discovered "
                        "frontier — stale checkpoint directory?"
                    )
            else:
                window = tuple(
                    padded_cache[k + j]
                    for j in range(1, g.max_level_jump + 1)
                    if (k + j) in padded_cache
                )
                values, remoteness, misses = self._resolve_jit(padded, window)
                if self.paranoid and int(misses) > 0:
                    raise SolverError(
                        f"level {k}: {int(misses)} consistency failures (child "
                        "lookups outside the solved window — level_of/"
                        "max_level_jump inconsistent — or non-primitive "
                        "positions with zero legal moves)"
                    )
                table = LevelTable(
                    states=states,
                    values=np.asarray(values[:n]),
                    remoteness=np.asarray(remoteness[:n]),
                )
            resolved[k] = table
            cap = padded.shape[0]
            pv = np.full(cap, UNDECIDED, dtype=np.uint8)
            pr = np.zeros(cap, dtype=np.int32)
            pv[:n] = table.values
            pr[:n] = table.remoteness
            padded_cache[k] = (padded, pv, pr)
            # Levels deeper than the lookback window can never be read again.
            for done in [d for d in padded_cache if d > k + g.max_level_jump]:
                del padded_cache[done]
            if self.logger is not None:
                self.logger.log(
                    {
                        "phase": "backward",
                        "level": k,
                        "n": n,
                        "resumed": from_checkpoint,
                        "secs": time.perf_counter() - t0,
                    }
                )
            if self.checkpointer is not None and not from_checkpoint:
                self.checkpointer.save_level(k, table)
        return resolved

    # ------------------------------------------------------------------ solve

    def solve(self) -> SolveResult:
        g = self.game
        t0 = time.perf_counter()
        init = np.uint64(g.initial_state())
        start_level = int(np.asarray(g.level_of(jnp.asarray([init])))[0])
        pools = (
            self.checkpointer.load_frontiers()
            if self.checkpointer is not None
            else None
        )
        if pools is None:
            pools = {start_level: np.array([init], np.uint64)}
            self._forward(pools, start_level)
            if self.checkpointer is not None:
                self.checkpointer.save_frontiers(pools)
        t_forward = time.perf_counter() - t0
        resolved = self._backward(pools)
        t_total = time.perf_counter() - t0
        root = resolved[start_level]
        i = int(np.searchsorted(root.states, init))
        value = int(root.values[i])
        remoteness = int(root.remoteness[i])
        num_positions = sum(t.states.shape[0] for t in resolved.values())
        stats = {
            "game": g.name,
            "positions": num_positions,
            "levels": len(resolved),
            "secs_forward": t_forward,
            "secs_total": t_total,
            "positions_per_sec": num_positions / max(t_total, 1e-9),
        }
        if self.logger is not None:
            self.logger.log({"phase": "done", **stats})
        return SolveResult(g, value, remoteness, resolved, stats)


def solve(game: TensorGame, **kwargs) -> SolveResult:
    """Convenience: Solver(game, **kwargs).solve()."""
    return Solver(game, **kwargs).solve()
