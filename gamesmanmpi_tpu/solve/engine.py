"""The level-synchronous retrograde solver (single device).

This replaces the reference's entire L1 distributed runtime — the Process
event loop, priority work queue and Job dispatch table (src/process.py,
src/job.py; SURVEY.md §2.2, §3.2-3.4) — with two bulk phases per level.
The Job types map as follows:

  reference Job (SURVEY.md §2.2)   here
  -------------------------------  -------------------------------------------
  LOOK_UP / DISTRIBUTE             forward pass: expand a whole level's
                                   frontier in one vmapped kernel; children are
                                   dedup'd (sort-unique) and become the next
                                   level's frontier instead of being mailed to
                                   owner ranks one Job at a time.
  CHECK_FOR_UPDATES                gone — no polling; the level barrier is the
                                   only synchronization.
  SEND_BACK / RESOLVE              backward pass: for each level (deepest
                                   first) regenerate children, look their
                                   values up in already-solved deeper levels
                                   (ops.lookup), and combine (ops.combine).
  FINISHED                         the backward loop reaching the root level.

Scheduling differs from the reference by design (SURVEY.md §2.4: asynchronous
small-message actors are anti-idiomatic on TPU); observable behavior — the
(value, remoteness) of every reachable position — is preserved and tested
against a pure-Python oracle.

Two execution paths share the kernels:

* **Fast path** (games with `uniform_level_jump`, i.e. every move advances the
  level by exactly 1 — tic-tac-toe, connect4): fully device-resident. The
  frontier chains on-device level to level (the next frontier is a static
  slice of the dedup output), and the backward window is exactly the
  previously-resolved level, which is already on-device. Host work per level
  is one scalar sync (the unique-count) plus the result-table download.
* **Generic path** (multi-jump games — subtraction games, Nim): children span
  multiple levels, so per-level pools are merged on host and the lookup
  window covers `max_level_jump` deeper levels.

Compiled-program economy: XLA compiles one program per shape, and in this
project's environments compilation is a remote RPC costing ~15 s per shape
with NO working persistent cache (tools/microbench.py; BENCH_r02's 600 s
"solve" was mostly serial compiles), while dispatch is cheap. Three defenses,
in order of importance:

* kernels are compiled in PARALLEL in the background (solve/precompile.py):
  a capacity ladder is scheduled at solve start, doubled ahead of frontier
  growth during forward, and the exact backward shapes — known the moment
  forward ends — are scheduled deepest-first so compilation overlaps
  execution;
* the backward kernel is keyed on ONE common capacity (states and window
  both padded to max of the two buckets), not on (cap, window-cap) pairs —
  halving backward shape count;
* all kernels are cached at module level keyed on (game.cache_key, kind,
  shapes), so re-instantiated Solvers (benchmark repeats, CLI reruns) reuse
  executables, and frontier capacities are power-of-two buckets so the shape
  count is O(log max-frontier), not O(levels).
"""

from __future__ import annotations

import time
from typing import Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from gamesmanmpi_tpu.core.codec import pack_cells, unpack_cells
from gamesmanmpi_tpu.core.values import UNDECIDED
from gamesmanmpi_tpu.games.base import TensorGame
from gamesmanmpi_tpu.ops.combine import combine_children
from gamesmanmpi_tpu.ops.dedup import (
    compact_method,
    compaction_sort_bytes,
    sort_unique,
)
from gamesmanmpi_tpu.ops.fused import (
    fused_dedup_method,
    fused_dedup_provenance,
    fused_enabled,
    fused_sort_unique,
    pipeline_mode,
    use_value_table,
)
from gamesmanmpi_tpu.ops.mergesort import backend_key, use_merge_sort
from gamesmanmpi_tpu.ops.lookup import lookup_window, search_method
from gamesmanmpi_tpu.ops.pallas_gather import cells_table_gather
from gamesmanmpi_tpu.ops.provenance import dedup_provenance, gather_cells
from gamesmanmpi_tpu.ops.padding import MIN_BUCKET, bucket_size, pad_to, pad_to_bucket
from gamesmanmpi_tpu.obs import (
    Heartbeat,
    SolveStatusTracker,
    Span,
    default_registry,
    maybe_status_server,
    trace_span,
)
from gamesmanmpi_tpu.obs import flightrec
from gamesmanmpi_tpu.resilience import faults
from gamesmanmpi_tpu.resilience import memguard, preempt
from gamesmanmpi_tpu.resilience.retry import retry_call
from gamesmanmpi_tpu.resilience.supervisor import maybe_watchdog
from gamesmanmpi_tpu.solve.precompile import global_precompiler, sds
from gamesmanmpi_tpu.utils.env import env_float, env_int, env_str
from gamesmanmpi_tpu.utils.platform import backend_epoch, platform_auto_bool


class LevelTable(NamedTuple):
    """Solved records for one level: parallel arrays sorted by state."""

    states: np.ndarray  # game.state_dtype, sorted ascending
    values: np.ndarray  # uint8
    remoteness: np.ndarray  # int32


class SolveResult:
    """Full solve output: root answer + per-level tables + stats."""

    def __init__(self, game: TensorGame, value: int, remoteness: int,
                 levels: Dict[int, LevelTable], stats: dict):
        self.game = game
        self.value = int(value)
        self.remoteness = int(remoteness)
        self.levels = levels
        self.stats = stats

    @property
    def num_positions(self) -> int:
        # stats carries the authoritative count (valid in store_tables=False
        # mode, where `levels` holds only the root level).
        if "positions" in self.stats:
            return self.stats["positions"]
        return sum(t.states.shape[0] for t in self.levels.values())

    def lookup(self, state) -> tuple[int, int]:
        """(value, remoteness) of any reachable packed state.

        Queries are canonicalized, so symmetry-reduced tables answer for
        every member of a stored class. The probe itself is the shared
        canonicalize→probe search (core/probe.py) — one code path with
        the solved-position DB and checkpoint point queries.
        """
        from gamesmanmpi_tpu.core.probe import probe_sorted_np

        state, level = canonical_scalar(self.game, state)
        table = self.levels.get(level)
        if table is not None:
            idx, hit = probe_sorted_np(
                table.states,
                np.asarray([state], dtype=table.states.dtype),
            )
            if hit[0]:
                i = idx[0]
                return int(table.values[i]), int(table.remoteness[i])
        raise KeyError(f"state {int(state):#x} not reachable/solved")


class SolverError(RuntimeError):
    pass


# ---------------------------------------------------------------------------
# Module-level kernel cache: (game.cache_key, kind, *shape info) -> jitted fn.
# Lives for the process so repeated Solver instances (bench repeats, parity
# tests, CLI reruns) never recompile. Bounded in practice: a handful of kinds
# x O(log max-frontier) capacities per game. Builders receive the game and
# must close over nothing else (a cached kernel outlives the Solver that
# first built it).
_KERNELS: dict = {}


# ---------------------------------------------------------------------------
# Dispatch accounting (ISSUE 14). The fused-megakernel work claims "fewer
# dispatches per level"; this counter is what makes that claim falsifiable
# in a bench record instead of a narrative. Every device computation the
# engines issue — cached-kernel calls (counted in get_kernel's wrapper),
# plus the eager slice/pad/upload/download ops the hot loops perform
# between kernels — calls note_dispatch. The active solver registers a
# sink (set_dispatch_sink) that tallies a per-(phase, level) breakdown and
# the gamesman_dispatches_total{phase} registry counter; with no solver
# active the note is a no-op (canonical_scalar point queries etc.).
_DISPATCH_SINK = None


def set_dispatch_sink(sink):
    """Install a dispatch sink; returns the previous one (nest-safe — the
    hybrid engine runs a Solver inside its own solve)."""
    global _DISPATCH_SINK
    prev = _DISPATCH_SINK
    _DISPATCH_SINK = sink
    return prev


def note_dispatch(kind: str) -> None:
    sink = _DISPATCH_SINK
    if sink is not None:
        sink(kind)


def _counted(kind: str, fn):
    """Wrap a cached kernel so every invocation is tallied by the active
    solver's sink. Host-side bookkeeping at kernel-call rate (a few per
    level), never per-position."""

    def call(*args, **kwargs):
        note_dispatch(kind)
        return fn(*args, **kwargs)

    return call


def roofline_stats(hbm_bytes: int, positions: int, wall_secs: float,
                   dispatches: int, chips: int = 1) -> dict:
    """The ISSUE 15 roofline rollup both engines put in their stats and
    bench.py folds into the record: analytic HBM operand throughput,
    per-chip solve rate, and the wall fraction spent on dispatch
    overhead (dispatch count x the host-calibrated per-dispatch cost,
    ``GAMESMAN_DISPATCH_COST_SECS`` — bench.py measures it; uncalibrated
    processes report 0.0, never a guess)."""
    wall = max(float(wall_secs), 1e-9)
    cost = env_float("GAMESMAN_DISPATCH_COST_SECS", 0.0)
    return {
        "operand_gbps": round(hbm_bytes / wall / 1e9, 3),
        "pps_per_chip": round(positions / wall / max(int(chips), 1), 1),
        "dispatch_overhead_frac": round(
            min(int(dispatches) * cost / wall, 1.0), 6
        ),
    }


def tally_dispatch(solver, kind: str) -> None:
    """The one dispatch-sink body both engines share (their _on_dispatch
    methods delegate here, so the gamesman_dispatches_total series and the
    per-(phase, level) keying can never fork between them). `solver` needs
    progress / game / dispatch_total / level_dispatches / dispatch_by_kind
    — the attributes Solver and ShardedSolver both carry."""
    solver.dispatch_total += 1
    ph = solver.progress.get("phase", "init")
    lvl = solver.progress.get("level", -1)
    key = (ph, lvl)
    solver.level_dispatches[key] = solver.level_dispatches.get(key, 0) + 1
    solver.dispatch_by_kind[kind] = \
        solver.dispatch_by_kind.get(kind, 0) + 1
    default_registry().counter(
        "gamesman_dispatches_total",
        "device computations/transfers dispatched by the engines",
        phase=ph, game=solver.game.name,
    ).inc()


def _cache_key(game: TensorGame, kind: str, shape_key, lowering):
    """Cache key for a kernel. Builders whose programs embed a
    flag/platform-resolved lowering — the sort backend (GAMESMAN_SORT
    [_ROW]), the searchsorted method (GAMESMAN_SEARCH), the dedup
    compaction (GAMESMAN_COMPACT) — pass the RESOLVED choices as the
    `lowering` tuple at their get_kernel/schedule_kernel call site. The
    builder itself captures the same values when it runs (immediately, at
    key time — schedule_kernel calls builder(game) before handing the
    traceable to the pool), so a mid-process flag flip can neither reuse a
    kernel traced under the other lowering nor produce a program that
    disagrees with its key. Each kind carries only the knobs its program
    actually contains — keying every kind on every knob would recompile
    byte-identical kernels on a flag flip (the doubled compile load
    stress-crashed XLA's CPU compiler once in a full-suite run).

    Every key also carries the backend EPOCH (utils/platform.py): when
    force_platform genuinely clears backends, executables closed over the
    old device objects (sharded kernels close over a Mesh) must not be
    reused — they fail with "incompatible devices for jitted computation"."""
    if lowering:
        return (game.cache_key, kind, shape_key, tuple(lowering),
                backend_epoch())
    return (game.cache_key, kind, shape_key, backend_epoch())


# Epoch whose kernels _KERNELS currently holds. Keys carry the epoch, so
# stale entries are unreachable after a genuine backend clear — but without
# a sweep they would leak (executables + closed-over Mesh/device objects)
# once per clear in long-lived processes. Per-game private caches are not
# swept: they die with their game instance.
_KERNELS_EPOCH = 0


def _sweep_stale_kernels() -> None:
    global _KERNELS_EPOCH
    epoch = backend_epoch()
    if epoch != _KERNELS_EPOCH:
        _KERNELS.clear()
        # Scheduled background compiles under old-epoch keys can never be
        # fetched either (every _cache_key ends with the epoch) — purge
        # them too, or their futures pin executables/Mesh objects and
        # queued ones burn ~15 s worker compiles for unreachable results.
        global_precompiler().purge(
            lambda k: isinstance(k, tuple) and bool(k) and k[-1] != epoch
        )
        _KERNELS_EPOCH = epoch


def get_kernel(game: TensorGame, kind: str, shape_key, builder,
               lowering=(), jit_kwargs=None):
    # Games whose identity is per-instance (TensorizedModule: host callbacks
    # can't be compared) carry their own cache dict, so their kernels are
    # garbage-collected with the game instead of pinning it process-wide.
    # jit_kwargs (in_shardings/out_shardings for mesh-partitioned kernels)
    # must be reflected in the caller's shape_key — the cache can't see
    # inside them.
    _sweep_stale_kernels()
    cache = getattr(game, "_private_kernel_cache", _KERNELS)
    key = _cache_key(game, kind, shape_key, lowering)
    fn = cache.get(key)
    if fn is None:
        # A background compile scheduled for this key wins over inline jit:
        # waiting out its residual beats restarting a 15 s remote compile.
        pre = global_precompiler()
        if pre.scheduled(key):
            compiled = pre.get(key, block=True)
            if compiled is not None:
                cache[key] = compiled
                return _counted(kind, compiled)
        fn = cache[key] = jax.jit(builder(game), **(jit_kwargs or {}))
    return _counted(kind, fn)


def schedule_kernel(game: TensorGame, kind: str, shape_key, builder, avals,
                    heavy: bool = False, lowering=(), jit_kwargs=None):
    """Queue a background compile of a kernel (idempotent, never blocks).

    avals must match the call signature get_kernel's users will invoke the
    kernel with — the compiled executable is shared through the same cache
    key. heavy marks big-working-set programs that must not compile at
    full pool concurrency (see precompile._heavy_slots). builder(game) runs
    HERE (only tracing is deferred to the pool), so builder-captured
    lowering knobs are resolved at the same moment as the key.
    """
    if getattr(game, "_private_kernel_cache", None) is not None:
        # Per-instance-cached games (compat host-callback modules): their
        # kernels must die with the instance; routing them through the
        # process-wide precompiler would pin the instance via its future.
        return
    _sweep_stale_kernels()
    cache = _KERNELS
    key = _cache_key(game, kind, shape_key, lowering)
    if key in cache:
        return
    pre = global_precompiler()
    if pre.scheduled(key):
        return
    pre.schedule(key, jax.jit(builder(game), **(jit_kwargs or {})),
                 tuple(avals), heavy=heavy)


def canonical_scalar(game: TensorGame, state):
    """(canonical state, topological level) of one packed state.

    The shared scalar entry for roots and point queries; runs through the
    process-wide kernel cache so per-query dispatch is O(1) even for games
    with expensive canonicalize (dihedral tic-tac-toe).

    Compiled for the HOST CPU backend when one is available: a one-element
    kernel gains nothing from the accelerator, and on the axon relay every
    accelerator compile costs ~15 s — this was a measurable slice of r02's
    solve startup.
    """

    def build(g):
        def f(s):
            c = g.canonicalize(s)
            return c, g.level_of(c)

        return f

    try:
        # local_devices, not devices: under multi-process execution
        # devices("cpu")[0] is process 0's device — any other process
        # would compute onto a non-addressable buffer and die fetching it.
        cpu = jax.local_devices(backend="cpu")[0]
    except RuntimeError:
        cpu = None
    arg = np.array([state], dtype=game.state_dtype)
    if cpu is not None:
        with jax.default_device(cpu):
            fn = get_kernel(game, "canon1cpu", 1, build)
            c, lvl = fn(arg)
    else:
        fn = get_kernel(game, "canon1", 1, build)
        c, lvl = fn(arg)
    return game.state_dtype(np.asarray(c)[0]), int(np.asarray(lvl)[0])


def undecided_mask(game: TensorGame, states):
    """Which lanes hold real, non-terminal positions: [B] bool."""
    return (states != game.sentinel) & (game.primitive(states) == UNDECIDED)


def canonical_children(game: TensorGame, states, active):
    """expand + canonicalize + deactivate parents + sentinel-fill.

    The one implementation of the per-level child generation all four solver
    kernels (single/sharded x forward/backward) share: children of inactive
    parents (padding lanes, primitives) are sentinel; survivors are
    symmetry-class representatives (identity for games without sym).
    Returns (children [B, M], mask [B, M]).
    """
    children, mask = game.expand(states)
    children = game.canonicalize(children)
    mask = mask & active[:, None]
    children = jnp.where(mask, children, game.sentinel)
    return children, mask


def expand_core(game: TensorGame, states, merge: bool | None = None,
                compact: str | None = None):
    """Shared expand+mask+dedup: [B] -> (uniq [B*M] sorted, count).

    merge/compact: sort-backend flag and compaction lowering, resolved at
    BUILD time by kernel builders (None = read the env/platform at trace
    time; see ops.mergesort.sort1, ops.dedup.compact_method)."""
    children, _ = canonical_children(game, states, undecided_mask(game, states))
    return sort_unique(children.reshape(-1), merge, compact)


def expand_provenance(game: TensorGame, states, merge: bool | None = None,
                      compact: str | None = None):
    """Forward expand that also keeps the dedup sort's provenance.

    Returns (uniq [B*M], count, uidx [B*M] int32, prim [B] uint8):
    uidx[b*M + m] is the index of child (b, m) within the `uniq` prefix
    (-1 for padding/invalid children), and prim is primitive(states).

    Rationale: the forward dedup sort already determines where every child
    lands in the next level's sorted table. Carrying the origin slot through
    the sort (one extra operand) and routing the run-index back (one pair
    sort) preserves that knowledge, so the backward pass needs NO search and
    NO re-expansion — child values become a single gather (see
    resolve_provenance). Costs one extra pair sort in forward; saves the
    sort-merge join (the backward pass's dominant cost) per level. The
    pair-sort core is shared with the sharded engine's edge-cached backward
    (ops/provenance.dedup_provenance).
    """
    prim = game.primitive(states)
    active = (states != game.sentinel) & (prim == UNDECIDED)
    children, _ = canonical_children(game, states, active)
    uniq, count, uidx = dedup_provenance(children.reshape(-1), merge, compact)
    return uniq, count, uidx, prim


def resolve_provenance(n, prim, uidx, wvals, wrem, max_moves: int):
    """Backward resolve from stored provenance: gathers + combine only.

    n: scalar int32 — number of real rows (real states are a prefix of the
    capacity, a dedup-compaction invariant). prim: [C] uint8 (from forward).
    uidx: [C*M] int32 child indices into the deeper level's prefix (-1 =
    no child). wvals/wrem: deeper level's solved values/remoteness [W].

    Lookup misses are structurally impossible here (the indices were
    derived from the very sort that built the deeper level), so the
    consistency counter only tracks non-primitive zero-move rows.
    """
    C = prim.shape[0]
    valid = jax.lax.iota(jnp.int32, C) < n
    undecided = valid & (prim == UNDECIDED)
    m = uidx.reshape(C, max_moves)
    mask = (m >= 0) & undecided[:, None]
    cv, cr = unpack_cells(gather_cells(m, wvals, wrem))
    values, remoteness = combine_children(cv, cr, mask)
    values = jnp.where(
        undecided, values,
        jnp.where(valid, prim, jnp.uint8(UNDECIDED)),
    )
    remoteness = jnp.where(undecided, remoteness, 0)
    misses = jnp.sum(undecided & ~jnp.any(mask, axis=-1))
    return values, remoteness, misses


def expand_with_levels(game: TensorGame, states, merge: bool | None = None,
                       compact: str | None = None):
    """Generic-path forward: expand_core + each child's topological level."""
    uniq, count = expand_core(game, states, merge, compact)
    levels = jnp.where(uniq != game.sentinel, game.level_of(uniq), -1)
    return uniq, levels, count


# ------------------------------------------------------- fused level kernels
# ISSUE 14: the megakernel bodies. One jitted program per level replaces the
# unfused chain of expand-kernel dispatch + eager next-frontier slice/pad (+
# speculative re-dispatch); the dedup inside is the fused rank/sort+dedup
# stage (ops/fused), fed the level's COUNT so the callback lowering sorts
# only the real prefix instead of the padded capacity.


def _chain_to_cap(buf, cap: int, sentinel):
    """In-program frontier chaining: slice (or sentinel-extend) the previous
    level's dedup output to this level's capacity bucket. The unfused path
    does this with eager ops between dispatches; here it fuses into the
    megakernel, so the chained buffer never surfaces as its own dispatch."""
    if buf.shape[0] >= cap:
        return jax.lax.slice(buf, (0,), (cap,))
    return jnp.concatenate(
        [buf, jnp.full(cap - buf.shape[0], sentinel, dtype=buf.dtype)]
    )


def fused_forward_step(game: TensorGame, states, n, keep_children: bool,
                       method: str | None, merge: bool | None,
                       compact: str | None):
    """One fused forward level: primitive + expand + canonicalize + dedup.

    states: [cap] (sentinel tail beyond the real count n). Returns
    (states [cap], uniq [cap*M], count, prim [cap], aux [cap*M]) where aux
    is the level's canonical children (keep_children=True — the value-table
    backward's input) or its dedup provenance uidx (the gather-only
    backward's input). `states` is echoed so the caller can retain the
    level without re-slicing outside the program.
    """
    prim = game.primitive(states)
    active = (states != game.sentinel) & (prim == UNDECIDED)
    children, _ = canonical_children(game, states, active)
    flat = children.reshape(-1)
    nv = n.astype(jnp.int32) * jnp.int32(game.max_moves)
    if keep_children:
        uniq, count = fused_sort_unique(flat, nv, method, merge, compact)
        return states, uniq, count, prim, flat
    uniq, count, uidx = fused_dedup_provenance(flat, nv, method, merge,
                                               compact)
    return states, uniq, count, prim, uidx


def expand_with_levels_fused(game: TensorGame, states, n,
                             method: str | None, merge: bool | None,
                             compact: str | None):
    """Generic-path fused forward: expand_with_levels with the fused dedup
    stage and the count-limited prefix (n = real frontier rows)."""
    prim = game.primitive(states)
    active = (states != game.sentinel) & (prim == UNDECIDED)
    children, _ = canonical_children(game, states, active)
    nv = n.astype(jnp.int32) * jnp.int32(game.max_moves)
    uniq, count = fused_sort_unique(children.reshape(-1), nv, method, merge,
                                    compact)
    levels = jnp.where(uniq != game.sentinel, game.level_of(uniq), -1)
    return uniq, levels, count


def fused_table_resolve(game: TensorGame, cells, states, prim, kids,
                        table_len: int):
    """One fused backward level against the persistent value table.

    cells: [2^state_bits] packed (value, remoteness) cells indexed by
    packed state — the cross-level ping-pong buffer: it is DONATED to this
    kernel and returned updated, so the whole backward sweep runs in two
    alternating aliases of one allocation. Children gather their answers
    directly (cells_table_gather — every child of level k lives in level
    k+1, already scattered), the negamax combine runs in-program, and this
    level's own cells scatter in before the buffer is handed back.

    Replaces, per level: the window slice/pad chain, the sort-merge join
    or binary search, and (with stored kids) the re-expansion. Misses are
    structurally impossible for real children; the counter tracks
    undecided-with-UNDECIDED-child (a table-discipline bug) and zero-move
    undecided rows (a game-definition error), same as resolve_level.
    """
    M = game.max_moves
    B = states.shape[0]
    valid = states != game.sentinel
    undecided = valid & (prim == UNDECIDED)
    k2 = kids.reshape(B, M)
    kvalid = (k2 != game.sentinel) & undecided[:, None]
    cv, cr = unpack_cells(cells_table_gather(cells, k2, kvalid))
    mask = kvalid & (cv != UNDECIDED)
    values, remoteness = combine_children(cv, cr, mask)
    values = jnp.where(
        undecided, values,
        jnp.where(valid, prim, jnp.uint8(UNDECIDED)),
    )
    remoteness = jnp.where(undecided, remoteness, 0)
    misses = jnp.sum(kvalid & (cv == UNDECIDED)) + jnp.sum(
        undecided & ~jnp.any(kvalid, axis=-1)
    )
    # Sentinel lanes scatter out of bounds and drop; real lanes (including
    # primitives — children of the shallower level need them) land at
    # their state index.
    idx = jnp.where(valid, states, states.dtype.type(table_len))
    cells = cells.at[idx].set(pack_cells(values, remoteness), mode="drop")
    return values, remoteness, misses, cells


def _make_fwdm_builder(cap: int, keep_children: bool, method: str,
                       merge: bool, compact: str):
    """Builder factory for the forward megakernel — shared by the inline
    get_kernel call and the background scheduler so both produce the same
    program under the same key."""

    def build(game):
        def f(buf, n):
            states = _chain_to_cap(buf, cap, game.sentinel)
            return fused_forward_step(game, states, n, keep_children,
                                      method, merge, compact)

        return f

    return build


def _make_bwdt_builder(has_kids: bool, table_len: int):
    """Builder factory for the value-table backward kernel (see _bwdt)."""

    def build(game):
        def f_kids(cells, states, prim, kids):
            return fused_table_resolve(game, cells, states, prim, kids,
                                       table_len)

        def f_expand(cells, states):
            # Level lost its stored children (budget eviction / resumed
            # from checkpoint): regenerate them in-program — still one
            # dispatch, just with the expand work back in it.
            prim = game.primitive(states)
            undecided = (states != game.sentinel) & (prim == UNDECIDED)
            kids, _ = canonical_children(game, states, undecided)
            return fused_table_resolve(game, cells, states, prim,
                                       kids.reshape(-1), table_len)

        return f_kids if has_kids else f_expand

    return build


def resolve_level(game: TensorGame, states, window,
                  method: str | None = None):
    """[B] states + solved deeper levels -> (values, remoteness, misses).

    Children are canonicalized to match the canonical solved tables.
    method: searchsorted lowering (see ops.lookup.lookup_sorted).
    """
    valid = states != game.sentinel
    prim = game.primitive(states)
    undecided = valid & (prim == UNDECIDED)
    children, mask = canonical_children(game, states, undecided)
    child_vals, child_rem, hit = lookup_window(children, window, method)
    values, remoteness = combine_children(child_vals, child_rem, mask)
    values = jnp.where(undecided, values, jnp.where(valid, prim, UNDECIDED))
    remoteness = jnp.where(undecided, remoteness, 0)
    # Consistency counters (SURVEY.md §5.2): child lookups that missed the
    # solved window, and non-primitive positions with zero legal moves
    # (a game-definition error — they would silently score LOSE/0).
    misses = jnp.sum(mask & ~hit) + jnp.sum(undecided & ~jnp.any(mask, axis=-1))
    return values, remoteness, misses


def _env_int(name: str, default: int) -> int:
    """Read an integer env knob lazily; malformed values degrade to the
    default with a warning instead of breaking package import. (Public
    re-export of utils.env.env_int — the sharded engine imports these
    names from here; the body lives in the one module GM301 audits.)"""
    return env_int(name, default)


def _env_float(name: str, default: float) -> float:
    """Float twin of _env_int (same degradation contract)."""
    return env_float(name, default)


def _backward_block() -> int:
    """Max positions resolved per backward kernel call (per shard).

    The backward step's temporaries scale with cap*max_moves (child blocks,
    lookup gathers, routing buffers in the sharded solver); levels wider
    than this are processed in column blocks against the same window, so
    peak memory is bounded by the block, not the level. Power-of-two,
    lazily read from GAMESMAN_BACKWARD_BLOCK (positions; 0 = unbounded,
    never block). Default 4M rows: the provenance resolve blocks for free
    (no per-block window re-join — just gathers against the shared table),
    and a 4M-row block's ~0.6 GB of temporaries leaves the v5e's ~15 GB
    usable HBM to the stored levels + provenance (a 16M-row block OOMed the
    5x5 solve with provenance resident).
    """
    n = _env_int("GAMESMAN_BACKWARD_BLOCK", 1 << 22)
    if n <= 0:
        return 1 << 62  # unbounded
    return max(256, 1 << (n - 1).bit_length())


def _device_store_bytes() -> int:
    """Device-resident level-store budget for the fast path (bytes of packed
    states, plus forward provenance, kept on device between the forward and
    backward phases; levels past the budget are spilled to host and
    re-uploaded during backward, and their provenance is dropped). Default
    sized for the 16 GB v5e: ~8 GB stored leaves ~2x headroom for the
    biggest level's kernel temporaries."""
    return _env_int("GAMESMAN_DEVICE_STORE_MB", 8192) << 20


class _Level:
    """One discovered level: host states + optionally the device copy.

    prim/uidx are the forward pass's provenance (expand_provenance): this
    level's primitive values and its out-edge indices into the NEXT level's
    prefix. Device-only, kept while the store budget allows; when absent the
    backward pass falls back to the sort-merge join.

    kids is the fused value-table alternative to uidx (ISSUE 14): the
    level's canonical children [cap*M], kept so the fused backward gathers
    their cells from the persistent table with no re-expansion. A level
    carries uidx OR kids, never both (the forward mode decides).
    """

    __slots__ = ("n", "host", "dev", "prim", "uidx", "kids")

    def __init__(self, n: int, host: Optional[np.ndarray], dev,
                 prim=None, uidx=None, kids=None):
        self.n = n  # real (non-sentinel) count
        self.host = host  # np [n] sorted, or None if only on device
        self.dev = dev  # jnp [cap] sorted + sentinel tail, or None
        self.prim = prim  # jnp [cap] uint8, or None
        self.uidx = uidx  # jnp [cap*M] int32, or None
        self.kids = kids  # jnp [cap*M] states, or None (fused table mode)

    def host_states(self) -> np.ndarray:
        if self.host is None:
            note_dispatch("download")
            self.host = np.asarray(self.dev[: self.n])
        return self.host


class Solver:
    """Single-device level-synchronous solver for a TensorGame."""

    def __init__(
        self,
        game: TensorGame,
        *,
        min_bucket: Optional[int] = None,
        paranoid: bool = False,
        logger=None,
        checkpointer=None,
        force_generic: bool = False,
        store_tables: bool = True,
        level_sink=None,
        heartbeat_secs: Optional[float] = None,
    ):
        self.game = game
        if min_bucket is None:
            # On accelerators every distinct capacity is a ~15 s remote
            # compile, and a 64k-row kernel still runs in ~a millisecond —
            # so fold all small levels into one capacity there. On CPU
            # (tests, fake meshes) compiles are cheap; keep kernels tiny.
            default = MIN_BUCKET if jax.default_backend() == "cpu" else 65536
            min_bucket = _env_int("GAMESMAN_MIN_BUCKET", default)
        self.min_bucket = min_bucket
        self.paranoid = paranoid
        self.logger = logger
        self.checkpointer = checkpointer
        #: False = big-run mode: only the root level's table is materialized
        #: on host (plus checkpoints); see the sharded solver's docstring.
        self.store_tables = store_tables
        #: Export hook (db/writer.DbWriter.add_level_table): called with
        #: (level, LevelTable) for every level as the backward pass
        #: resolves it, deepest first — so a DB export streams level by
        #: level and never holds the full table in host memory
        #: (combine with store_tables=False).
        self.level_sink = level_sink
        #: Heartbeat period in seconds (0 = off); None reads
        #: GAMESMAN_HEARTBEAT_SECS. The heartbeat thread reads `progress`
        #: (replaced atomically per level, never mutated in place) so a
        #: wedged multi-hour solve still reports where it stopped.
        if heartbeat_secs is None:
            heartbeat_secs = _env_float("GAMESMAN_HEARTBEAT_SECS", 0.0)
        self.heartbeat_secs = float(heartbeat_secs)
        self.progress: dict = {"phase": "init"}
        self.fast = bool(game.uniform_level_jump) and not force_generic
        self.device_store_bytes = _device_store_bytes()
        self.backward_block = _backward_block()
        # Analytic traffic counters (SURVEY.md §5.5): operand bytes of the
        # sort/gather kernels, the denominators that turn positions/sec
        # into a roofline fraction for this memory-bound workload. Computed
        # from static shapes (no device counters); XLA's TPU sort makes
        # ~log2(n) passes, so HBM traffic is ~log2(n) x these bytes — the
        # convention docs/ARCHITECTURE.md states.
        self.bytes_sorted = 0
        self.bytes_gathered = 0
        # Background compiles only pay off where compiles are expensive
        # (remote accelerator); on CPU they would just slow the test suite.
        flag = env_str("GAMESMAN_PRECOMPILE", "auto")
        if flag == "auto":
            self.precompile = jax.default_backend() != "cpu"
        else:
            self.precompile = flag not in ("0", "off", "false")
        self._cap_ceiling = self._cap_limit() if self.precompile else 0
        # Provenance forward (expand_provenance: two pair sorts + a re-sort)
        # trades forward sort work for a gather-only backward — a clear win
        # on the TPU, where sorts hide behind the relay's dispatch latency
        # and the backward's sort-merge join was the dominant cost. On CPU
        # the same trade REGRESSED the solve ~5x (BENCH_r01 813k vs
        # BENCH_r03 150k pos/s on 5x4): forward sort work tripled while the
        # backward it saves was already cheap. Keyed on the platform that
        # will execute, not on an env var benches could forget
        # (GAMESMAN_PROVENANCE=0/1 remains as a test/experiment override).
        # RESOLVED AT SOLVE TIME, like every other platform-auto knob: a
        # force_platform between construction and solve() must re-resolve
        # (speculate/search/compact all would; this must not lag behind on
        # the stale platform).
        self.use_provenance: bool | None = None
        #: transient level-step failures absorbed by retry (stats field;
        #: the registry carries the per-point gamesman_retries_total).
        self.retries = 0
        # ISSUE 14 fused/pipeline mode, resolved at SOLVE time like every
        # platform/env-auto knob (a force_platform or env flip between
        # construction and solve() must be honored).
        self.use_fused: bool | None = None
        self.pipeline: str | None = None
        self._fused_table = False
        #: dispatch accounting (see note_dispatch): total device
        #: computations/transfers this solve issued, and the per-(phase,
        #: level) breakdown the zero-extra-dispatch tests assert on.
        self.dispatch_total = 0
        self.level_dispatches: Dict[tuple, int] = {}
        self.dispatch_by_kind: Dict[str, int] = {}
        #: host-side seconds the pingpong pipeline ran while a device
        #: kernel was in flight (downloads/export/checkpoint deferred one
        #: level — stats field; 0.0 in level mode).
        self.overlap_secs = 0.0
        #: analytic host-transfer bytes (frontier/table uploads+downloads
        #: and checkpoint materializations) — the host-side roofline
        #: denominator next to bytes_sorted/bytes_gathered's HBM side.
        self.bytes_host = 0
        #: live-status progress model (obs/status.py): per-level schedule
        #: + ETA behind the GAMESMAN_STATUS_PORT /status endpoint.
        self.status_tracker = SolveStatusTracker()

    def _on_dispatch(self, kind: str) -> None:
        """Dispatch sink (set_dispatch_sink) — see tally_dispatch."""
        tally_dispatch(self, kind)

    def _retry(self, point: str, fn, reset=None, level=None):
        """Level-step retry wrapper: bounded exponential backoff on
        transient runtime errors, re-entering from the step's held
        inputs via ``reset`` (see resilience.retry)."""

        def on_retry(attempt, exc):
            self.retries += 1

        return retry_call(fn, point=point, reset=reset, level=level,
                          logger=self.logger, on_retry=on_retry)

    # ---------------------------------------------------------------- kernels

    def _expand_impl(self, states):
        """[B] states -> (unique children, their levels, count).

        Traceable generic-path forward (also the driver compile-check entry).
        """
        return expand_with_levels(self.game, states)

    # Cached kernel getters. Builders close over the game only — a cached
    # kernel outlives this Solver (see _KERNELS).

    @staticmethod
    def _fwdp_builder(game):
        # Builders run at cache-key time (inside get_kernel/
        # schedule_kernel), so resolving the lowering knobs HERE keeps the
        # traced program consistent with the key even when a background
        # worker traces it later.
        mb, cm = use_merge_sort(), compact_method()
        return lambda states: expand_provenance(game, states, mb, cm)

    @staticmethod
    def _bwd_builder(game):
        sm = search_method()  # resolved at cache-key time

        def f(states, *window_flat):
            window = tuple(
                (window_flat[i], window_flat[i + 1], window_flat[i + 2])
                for i in range(0, len(window_flat), 3)
            )
            return resolve_level(game, states, window, sm)

        return f

    @staticmethod
    def _bwdp_builder(game):
        M = game.max_moves
        return lambda n, prim, uidx, wvals, wrem: resolve_provenance(
            n, prim, uidx, wvals, wrem, M
        )

    @staticmethod
    def _fwdf_builder(game):
        mb, cm = use_merge_sort(), compact_method()
        return lambda states: expand_core(game, states, mb, cm)

    @staticmethod
    def _fwd_lowering():
        """Knobs the forward kernels embed: sorts + dedup compaction."""
        return (backend_key(), compact_method())

    def _fwdp(self, cap: int):
        """Provenance forward: states[cap] -> (uniq, count, uidx, prim)."""
        return get_kernel(self.game, "fwdp", cap, self._fwdp_builder,
                          lowering=self._fwd_lowering())

    def _fwdf(self, cap: int):
        """Plain fast forward (one dedup sort, no provenance): states[cap]
        -> (uniq, count). The CPU default — see use_provenance."""
        return get_kernel(self.game, "fwdf", cap, self._fwdf_builder,
                          lowering=self._fwd_lowering())

    def _bwdp(self, cap: int, wcap: int):
        """Provenance backward: (n, prim[cap], uidx[cap*M], wvals[wcap],
        wrem[wcap]) -> (values, rem, misses)."""
        return get_kernel(self.game, "bwdp", (cap, wcap), self._bwdp_builder)

    def _fwd_generic(self, cap: int):
        if self.use_fused:
            # Generic-path megakernel: fused dedup + count-limited prefix
            # (the caller passes the real frontier row count alongside the
            # padded states). Separate kind — the signatures differ.
            md = fused_dedup_method()

            def build_fused(game):
                mb, cm = use_merge_sort(), compact_method()
                return lambda states, n: expand_with_levels_fused(
                    game, states, n, md, mb, cm
                )

            return get_kernel(self.game, "fwdgm", cap, build_fused,
                              lowering=self._fused_lowering())

        def build(game):
            # resolved at cache-key time
            mb, cm = use_merge_sort(), compact_method()
            return lambda states: expand_with_levels(game, states, mb, cm)

        return get_kernel(self.game, "fwdg", cap, build,
                          lowering=self._fwd_lowering())

    def _bwd(self, cap: int, wcaps: tuple):
        """Backward: states[cap] + window levels -> (values, rem, misses).

        wcaps: tuple of window-level capacities (possibly empty — deepest
        level, everything primitive; the fast path always passes a single
        window level padded to the common capacity, see _backward_fast).
        """
        return get_kernel(
            self.game, "bwd", (cap, tuple(wcaps)), self._bwd_builder,
            lowering=(search_method(),),  # lookup_window's search lowering
        )

    # ------------------------------------------------- fused megakernels

    def _fused_lowering(self):
        """Knobs the fused kernels embed: dedup method + sorts + compact."""
        return (fused_dedup_method(), backend_key(), compact_method())

    def _fwdm(self, in_len: int, cap: int):
        """Forward megakernel: (buf [in_len], n) -> (states [cap],
        uniq [cap*M], count, prim [cap], kids|uidx [cap*M]). Keyed on the
        chain-input length AND the capacity — both are power-of-two
        buckets, so the key count stays O(log max-frontier)."""
        md = fused_dedup_method()
        mb, cm = use_merge_sort(), compact_method()
        return get_kernel(
            self.game, "fwdm", (in_len, cap, self._fused_table),
            _make_fwdm_builder(cap, self._fused_table, md, mb, cm),
            lowering=self._fused_lowering(),
        )

    def _sched_fwdm(self, in_len: int, cap: int) -> None:
        if cap > self._cap_ceiling:
            return
        md = fused_dedup_method()
        mb, cm = use_merge_sort(), compact_method()
        schedule_kernel(
            self.game, "fwdm", (in_len, cap, self._fused_table),
            _make_fwdm_builder(cap, self._fused_table, md, mb, cm),
            (sds((in_len,), self.game.state_dtype), sds((), np.int32)),
            heavy=self._heavy(cap), lowering=self._fused_lowering(),
        )

    def _bwdt(self, cap: int, has_kids: bool):
        """Value-table backward megakernel: (cells [T], states [cap]
        [, prim [cap], kids [cap*M]]) -> (values, rem, misses, cells').

        The cells buffer is donated — the ping-pong discipline: exactly
        two aliases of the [2^state_bits] table alternate across the
        whole backward sweep, and no window tensors exist at all.
        """
        return get_kernel(
            self.game, "bwdt", (cap, has_kids),
            _make_bwdt_builder(has_kids, 1 << self.game.state_bits),
            jit_kwargs={"donate_argnums": (0,)},
        )

    def _sched_bwdt(self, cap: int, has_kids: bool) -> None:
        if cap > self._cap_ceiling:
            return
        g = self.game
        T = 1 << g.state_bits
        avals = [sds((T,), np.uint32), sds((cap,), g.state_dtype)]
        if has_kids:
            avals += [sds((cap,), np.uint8),
                      sds((cap * g.max_moves,), g.state_dtype)]
        schedule_kernel(
            self.game, "bwdt", (cap, has_kids),
            _make_bwdt_builder(has_kids, T), tuple(avals),
            heavy=self._heavy(cap),
            jit_kwargs={"donate_argnums": (0,)},
        )

    def _bwdc(self, cap: int):
        """Checkpoint-resume cell scatter: fold a loaded level's solved
        (values, remoteness) into the persistent table without resolving."""
        T = 1 << self.game.state_bits

        def build(game, T=T):
            def f(cells, states, values, rem):
                valid = states != game.sentinel
                idx = jnp.where(valid, states, states.dtype.type(T))
                return cells.at[idx].set(pack_cells(values, rem),
                                         mode="drop")

            return f

        return get_kernel(self.game, "bwdc", cap, build,
                          jit_kwargs={"donate_argnums": (0,)})

    # ---------------------------------------------- background compile plan

    def _cap_limit(self) -> int:
        """Largest capacity worth speculatively compiling for.

        Bounded by the state space (2^state_bits can't be exceeded by a
        frontier) and by device memory for the kernel's temporaries
        (children block + sort buffers ~ 4x children bytes).
        """
        g = self.game
        item = np.dtype(g.state_dtype).itemsize
        mem = _env_int("GAMESMAN_PRECOMPILE_MEM_MB", 4096) << 20
        by_mem = mem // max(g.max_moves * item * 4, 1)
        by_space = 1 << min(g.state_bits, 34)
        return bucket_size(max(min(by_mem, by_space), 1), self.min_bucket)

    def _sched_bwd(self, cap: int, wcaps: tuple) -> None:
        if cap > self._cap_ceiling:
            return
        dt = self.game.state_dtype
        avals = [sds((cap,), dt)]
        for w in wcaps:
            avals += [sds((w,), dt), sds((w,), np.uint8), sds((w,), np.int32)]
        schedule_kernel(
            self.game, "bwd", (cap, tuple(wcaps)), self._bwd_builder, avals,
            heavy=self._heavy(max((cap,) + tuple(wcaps))),
            lowering=(search_method(),),
        )

    def _heavy(self, cap: int) -> bool:
        """Programs whose children block exceeds ~256 MB compile under the
        heavy semaphore — concurrent big compiles crash the relay's
        compile helper (see precompile._heavy_slots)."""
        item = np.dtype(self.game.state_dtype).itemsize
        return cap * self.game.max_moves * item > (256 << 20)

    def _sched_fwdp(self, cap: int) -> None:
        if cap > self._cap_ceiling:
            return
        schedule_kernel(
            self.game, "fwdp", cap, self._fwdp_builder,
            (sds((cap,), self.game.state_dtype),),
            heavy=self._heavy(cap), lowering=self._fwd_lowering(),
        )

    def _sched_fwdf(self, cap: int) -> None:
        if cap > self._cap_ceiling:
            return
        schedule_kernel(
            self.game, "fwdf", cap, self._fwdf_builder,
            (sds((cap,), self.game.state_dtype),),
            heavy=self._heavy(cap), lowering=self._fwd_lowering(),
        )

    def _sched_fwd_step(self, cap: int) -> None:
        """Schedule whichever forward kernel this solver will request."""
        if self.use_fused:
            # The chain key the megakernel will actually request: the
            # previous bucket's uniq buffer feeding this capacity (plus
            # the same-capacity entry key for the root level).
            self._sched_fwdm(cap, cap)
            self._sched_fwdm(cap * self.game.max_moves, cap)
            self._sched_fwdm(cap * self.game.max_moves, cap * 2)
        elif self.use_provenance:
            self._sched_fwdp(cap)
        else:
            self._sched_fwdf(cap)

    def _sched_bwd_step(self, cap: int, wcap: int) -> None:
        """Schedule whichever backward kernel this solver will request."""
        if self._fused_table:
            self._sched_bwdt(cap, True)
        elif self.use_provenance:
            self._sched_bwdp(cap, wcap)
        else:
            self._sched_bwd(cap, (wcap,))

    def _sched_bwdp(self, cap: int, wcap: int) -> None:
        if cap > self._cap_ceiling:
            return
        M = self.game.max_moves
        avals = (
            sds((), np.int32),
            sds((cap,), np.uint8),
            sds((cap * M,), np.int32),
            sds((wcap,), np.uint8),
            sds((wcap,), np.int32),
        )
        schedule_kernel(
            self.game, "bwdp", (cap, wcap), self._bwdp_builder, avals,
            heavy=self._heavy(max(cap, wcap)),
        )

    def _schedule_initial_ladder(self) -> None:
        """Queue background compiles for the first few capacity doublings.

        Forward growth will outrun a ~15 s compile long before the ladder
        top is reached; scheduling the whole plausible ladder up front lets
        the pool compile ~8 shapes concurrently while small levels execute.
        """
        cap = self.min_bucket
        for _ in range(7):
            if cap > self._cap_ceiling:
                break
            self._sched_fwd_step(cap)
            self._sched_bwd_step(min(cap, self._block_size()), cap)
            cap *= 2

    def _block_size(self) -> int:
        """Largest power of two <= backward_block: caps are powers of two,
        so this always divides cap exactly (no ragged final block), even
        when the attribute was set directly to an odd value. Shared by
        _resolve_blocked and the backward compile scheduler — their kernel
        keys must agree."""
        return 1 << max(self.backward_block, 1).bit_length() - 1

    def _resolve_blocked_prov(self, n: int, prim, uidx, wvals, wrem):
        """Provenance resolve, in column blocks when the level is wide.

        Same blocking contract as _resolve_blocked: per-block temporaries
        bounded by the block; the window (wvals/wrem) is shared by every
        block; results concatenate on device; misses accumulate on device.
        """
        C = prim.shape[0]
        M = self.game.max_moves
        block = self._block_size()
        if C <= block:
            return self._bwdp(C, C)(np.int32(n), prim, uidx, wvals, wrem)
        values, rems = [], []
        misses = None
        for off in range(0, C, block):
            nb = np.int32(min(max(n - off, 0), block))
            v, r, m = self._bwdp(block, C)(
                nb,
                jax.lax.slice(prim, (off,), (off + block,)),
                jax.lax.slice(uidx, (off * M,), ((off + block) * M,)),
                wvals,
                wrem,
            )
            values.append(v)
            rems.append(r)
            misses = m if misses is None else misses + m
        return jnp.concatenate(values), jnp.concatenate(rems), misses

    def _resolve_blocked(self, states_dev, wcaps: tuple, window_args: tuple):
        """Backward-resolve a level, in column blocks when it is wide.

        Levels wider than `backward_block` run the same kernel per block
        against the same window — peak temporaries are bounded by the block
        (SURVEY.md §7 "Memory budget"); results concatenate on device.
        """
        cap = states_dev.shape[0]
        block = self._block_size()
        if cap <= block:
            return self._bwd(cap, wcaps)(states_dev, *window_args)
        values, rems = [], []
        misses = None
        for off in range(0, cap, block):
            v, r, m = self._bwd(block, wcaps)(
                jax.lax.slice(states_dev, (off,), (off + block,)),
                *window_args,
            )
            values.append(v)
            rems.append(r)
            # Accumulate on device — callers sync the total only under
            # --paranoid, so block dispatch never serializes on the host.
            misses = m if misses is None else misses + m
        return jnp.concatenate(values), jnp.concatenate(rems), misses

    # ------------------------------------------------------------- fast phase

    def _forward_fast(self, init, start_level: int,
                      resume: Optional[Dict[int, np.ndarray]] = None,
                      ) -> Dict[int, _Level]:
        """Device-resident forward sweep for uniform_level_jump games.

        Two latency hiders on top of the level loop:

        * the expand kernel is expand_provenance — its uidx/prim outputs are
          stored (budget permitting) so the backward pass becomes pure
          gathers (see resolve_provenance);
        * the next level's expand is dispatched SPECULATIVELY at the current
          capacity before the unique-count host sync (~65 ms on the relay);
          most levels keep their bucket, so the device computes through the
          sync instead of idling. A mispredicted bucket just re-dispatches
          at the right capacity — the speculative result is dropped.

        With a checkpointer, each level's frontier is saved the moment its
        count is known (same total bytes as the old end-of-forward snapshot
        — host_states() caches the download — but a mid-forward death keeps
        the prefix). `resume` is that prefix from a previous interrupted
        run: expansion continues from its deepest level; earlier levels
        carry no provenance, so the backward pass uses the lookup join for
        them, exactly as for budget-evicted levels.
        """
        g = self.game
        levels: Dict[int, _Level] = {}
        if resume:
            ks = sorted(resume)
            if ks != list(range(ks[0], ks[-1] + 1)) or ks[0] != start_level:
                raise SolverError(
                    f"forward checkpoint levels {ks} are not contiguous from "
                    f"the root level {start_level} — stale checkpoint "
                    "directory?"
                )
            for kk in ks:
                arr = np.asarray(resume[kk], dtype=g.state_dtype)
                levels[kk] = _Level(arr.shape[0], arr, None)
            k = ks[-1]
            host0 = levels[k].host
        else:
            # init: one root state, or a whole sorted frontier (the hybrid
            # engine starts BFS at its cutover level's reachable set).
            host0 = np.atleast_1d(np.asarray(init, dtype=g.state_dtype))
            k = start_level
        cap0 = bucket_size(host0.shape[0], self.min_bucket)
        note_dispatch("upload")
        frontier = jnp.asarray(pad_to(host0, cap0))
        if resume:
            levels[k].dev = frontier
        else:
            levels[k] = _Level(host0.shape[0], host0, frontier)
            if self.checkpointer is not None:
                with trace_span("checkpoint", level=k, kind="frontier"):
                    self.checkpointer.save_frontier_level(k, host0)
        stored_bytes = frontier.nbytes
        # Speculation hides the ~65 ms relay host-sync; on CPU the sync is
        # microseconds and a dropped speculative expand is real wasted work.
        speculate = platform_auto_bool(
            "GAMESMAN_SPECULATE", accel=True, cpu=False
        )

        def fwd_step(arr):
            """Dispatch the platform-selected forward kernel; normalize to
            (uniq, count, uidx|None, prim|None)."""
            if self.use_provenance:
                return self._fwdp(arr.shape[0])(arr)
            u, c = self._fwdf(arr.shape[0])(arr)
            return u, c, None, None

        pending = fwd_step(frontier)
        while True:
            sp = Span("forward", logger=self.logger, level=k)
            self.progress = {
                "phase": "forward", "level": k, "frontier": levels[k].n,
            }
            # Level boundary: everything before this level is saved
            # (save_frontier_level is eager), so a grace signal stops
            # HERE and the next run resumes expansion from level k.
            preempt.check("forward", level=k, logger=self.logger)
            memguard.check("forward", level=k, logger=self.logger)
            cap = frontier.shape[0]
            d0 = self.dispatch_total
            spec = spec_input = None
            if speculate:
                note_dispatch("eager")
                spec_input = jax.lax.slice(pending[0], (0,), (cap,))
                spec = fwd_step(spec_input)
            # The expand+dedup kernel retires AT this host sync (dispatch
            # is async), so the dedup/sort wait is what this span times.
            # The sync is the level's transient-failure surface: a relay
            # hiccup raises here, and the retry re-dispatches from the
            # frontier (still in hand) — checkpoint-consistent re-entry.
            holder = [pending]

            def _sync(holder=holder, k=k):
                faults.fire("engine.forward", level=k)
                faults.fire("engine.dedup", level=k)
                return int(holder[0][1])  # the one host sync per level

            def _redispatch(holder=holder, frontier=frontier):
                holder[0] = fwd_step(frontier)

            with trace_span("dedup", level=k):
                n = self._retry("engine.forward", _sync, reset=_redispatch,
                                level=k)
            if holder[0] is not pending:
                pending = holder[0]
                spec = spec_input = None  # speculation predates the retry
            uniq, count, uidx, prim = pending
            rec = levels[k]
            if uidx is not None:
                extra = prim.nbytes + uidx.nbytes
                if n > 0 and stored_bytes + extra <= self.device_store_bytes:
                    # Keep this level's provenance for the gather-only
                    # backward.
                    rec.prim, rec.uidx = prim, uidx
                    stored_bytes += extra
            if n == 0:
                # Terminal probe: the span's trace event is kept (its
                # wait time is real) but no JSONL record — the per-level
                # stream is unchanged from the hand-rolled log calls.
                sp.end(log=False)
                self.status_tracker.forward_level(k, levels[k].n, sp.secs)
                flightrec.boundary("forward", k)
                break
            if k + 1 >= g.num_levels:
                # num_levels is the declared exclusive bound on level_of over
                # reachable states; children past it mean the game's
                # level_of/num_levels contract is broken (and, unchecked,
                # a buggy level function could loop forever here).
                raise SolverError(
                    f"game {g.name}: children found at level {k + 1} but "
                    f"num_levels={g.num_levels} — level_of/num_levels "
                    "inconsistent"
                )
            next_cap = bucket_size(n, self.min_bucket)
            if next_cap > cap:
                # Frontier grew into a new bucket: queue compiles two and
                # four doublings ahead so growth never outruns the pool.
                # Backward kernels block at _block_size() — schedule the key
                # the backward pass will actually request.
                for ahead in (next_cap * 2, next_cap * 4):
                    self._sched_fwd_step(ahead)
                    self._sched_bwd_step(min(ahead, self._block_size()), ahead)
            if next_cap == cap and spec is not None:
                nxt = spec_input
                pending = spec
            else:
                note_dispatch("eager")
                if next_cap <= uniq.shape[0]:
                    nxt = jax.lax.slice(uniq, (0,), (next_cap,))
                else:
                    # bucket(n) can exceed cap*M for non-power-of-two
                    # branching factors (e.g. M=7: n in (1024, 1792] at
                    # cap=256); extend with sentinel padding on device — no
                    # host round-trip.
                    nxt = jnp.concatenate(
                        [
                            uniq,
                            jnp.full(
                                next_cap - uniq.shape[0], g.sentinel,
                                dtype=uniq.dtype,
                            ),
                        ]
                    )
                pending = fwd_step(nxt)
            rec = _Level(n, None, nxt)
            if stored_bytes + nxt.nbytes > self.device_store_bytes:
                # Device-store budget exhausted: keep this level on host only
                # (backward re-uploads it); the live frontier still chains on
                # device.
                rec.host_states()
                rec.dev = None
            else:
                stored_bytes += nxt.nbytes
            levels[k + 1] = rec
            frontier = nxt
            if self.checkpointer is not None:
                with trace_span("checkpoint", level=k + 1, kind="frontier"):
                    self.checkpointer.save_frontier_level(k + 1,
                                                          rec.host_states())
            item = np.dtype(g.state_dtype).itemsize
            # Only operands of actual sorts count (the traffic denominator
            # must match the kernel the platform lowered).
            compaction = compaction_sort_bytes(item)
            if self.use_provenance:
                # expand_provenance: (child, origin i32) pair sort +
                # (origin, uid) i32 pair sort + the compaction.
                level_sort_bytes = cap * g.max_moves * (item + 12 + compaction)
            else:
                # expand_core: one dedup sort + the compaction.
                level_sort_bytes = cap * g.max_moves * (item + compaction)
            self.bytes_sorted += level_sort_bytes
            # Host-transfer bytes this level caused: the frontier download
            # for the checkpoint write (host_states caches it) — the
            # per-level roofline denominator on the host side.
            fwd_host_bytes = (
                n * item if self.checkpointer is not None else 0
            )
            self.bytes_host += fwd_host_bytes
            sp.end(
                frontier=levels[k].n,
                children=n,
                bytes_sorted=level_sort_bytes,
                bytes_hbm=level_sort_bytes,
                bytes_host=fwd_host_bytes,
                dispatches=self.dispatch_total - d0,
            )
            self.status_tracker.forward_level(k, levels[k].n, sp.secs)
            flightrec.boundary("forward", k)
            k += 1
        return levels

    def _forward_fast_fused(self, init, start_level: int,
                            resume: Optional[Dict[int, np.ndarray]] = None,
                            ) -> Dict[int, _Level]:
        """Megakernel forward sweep (GAMESMAN_FUSED=1): ONE dispatch/level.

        The unfused path's per-level chain — expand-kernel dispatch, eager
        next-frontier slice/pad, speculative re-dispatch — collapses into a
        single jitted program per (in_len, cap) key (_fwdm): the previous
        level's dedup output enters UNSLICED, the chain slice happens
        in-program, and the fused dedup stage receives the previous level's
        count so the callback lowering sorts only the real prefix. The
        kernel also emits everything the backward pass needs (states echo,
        primitive values, canonical children or provenance), so the
        backward never re-expands and nothing round-trips through host
        buffers.

        Pipelining is inherent here — the chain is exactly the ping-pong
        shape (uniq buffer feeding the next dispatch while the states echo
        is retained) — so per-level host work (frontier checkpoint, budget
        downloads) always runs AFTER the next level's kernel is in flight;
        those seconds accumulate into overlap_secs.
        """
        g = self.game
        levels: Dict[int, _Level] = {}
        if resume:
            ks = sorted(resume)
            if ks != list(range(ks[0], ks[-1] + 1)) or ks[0] != start_level:
                raise SolverError(
                    f"forward checkpoint levels {ks} are not contiguous from "
                    f"the root level {start_level} — stale checkpoint "
                    "directory?"
                )
            for kk in ks:
                arr = np.asarray(resume[kk], dtype=g.state_dtype)
                levels[kk] = _Level(arr.shape[0], arr, None)
            k = ks[-1]
            host0 = levels[k].host
        else:
            host0 = np.atleast_1d(np.asarray(init, dtype=g.state_dtype))
            k = start_level
        cap = bucket_size(host0.shape[0], self.min_bucket)
        note_dispatch("upload")
        frontier = jnp.asarray(pad_to(host0, cap))
        if resume:
            levels[k].dev = frontier
        else:
            levels[k] = _Level(host0.shape[0], host0, frontier)
            if self.checkpointer is not None:
                with trace_span("checkpoint", level=k, kind="frontier"):
                    self.checkpointer.save_frontier_level(k, host0)
        stored_bytes = frontier.nbytes
        item = np.dtype(g.state_dtype).itemsize
        callback_dedup = fused_dedup_method() == "callback"
        # The retried unit's held inputs: (buf, n_arg, in_len, cap).
        call = (frontier, np.int32(levels[k].n), cap, cap)
        pending = self._fwdm(call[2], call[3])(call[0], call[1])
        evicted: set = set()
        while True:
            sp = Span("forward", logger=self.logger, level=k)
            d0 = self.dispatch_total
            self.progress = {
                "phase": "forward", "level": k, "frontier": levels[k].n,
            }
            preempt.check("forward", level=k, logger=self.logger)
            memguard.check("forward", level=k, logger=self.logger)
            holder = [pending]

            def _sync(holder=holder, k=k):
                faults.fire("engine.forward", level=k)
                faults.fire("engine.dedup", level=k)
                return int(holder[0][2])  # the one host sync per level

            def _redispatch(holder=holder, call=call):
                holder[0] = self._fwdm(call[2], call[3])(call[0], call[1])

            with trace_span("dedup", level=k):
                n = self._retry("engine.forward", _sync, reset=_redispatch,
                                level=k)
            pending = holder[0]
            states_out, uniq, count, prim, aux = pending
            rec = levels[k]
            if rec.dev is None and k not in evicted:
                rec.dev = states_out
            extra = prim.nbytes + aux.nbytes
            if n > 0 and stored_bytes + extra <= self.device_store_bytes:
                rec.prim = prim
                if self._fused_table:
                    rec.kids = aux
                else:
                    rec.uidx = aux
                stored_bytes += extra
            if n == 0:
                sp.end(log=False)
                self.status_tracker.forward_level(k, levels[k].n, sp.secs)
                flightrec.boundary("forward", k)
                break
            if k + 1 >= g.num_levels:
                raise SolverError(
                    f"game {g.name}: children found at level {k + 1} but "
                    f"num_levels={g.num_levels} — level_of/num_levels "
                    "inconsistent"
                )
            next_cap = bucket_size(n, self.min_bucket)
            if next_cap > call[3]:
                for ahead in (next_cap * 2, next_cap * 4):
                    self._sched_fwdm(ahead * g.max_moves, ahead)
                    self._sched_bwd_step(min(ahead, self._block_size()),
                                         ahead)
            in_len = uniq.shape[0]
            rec2 = _Level(n, None, None)
            levels[k + 1] = rec2
            call = (uniq, count, in_len, next_cap)
            pending = self._fwdm(in_len, next_cap)(uniq, count)
            # Host work runs with the next level's kernel in flight (the
            # ping-pong overlap); its wall time is real but concurrent.
            t_host = time.perf_counter()
            over_budget = stored_bytes + next_cap * item \
                > self.device_store_bytes
            if over_budget:
                evicted.add(k + 1)
            else:
                stored_bytes += next_cap * item
            if self.checkpointer is not None or over_budget:
                note_dispatch("download")
                rec2.host = np.asarray(uniq[:n])
            if self.checkpointer is not None:
                with trace_span("checkpoint", level=k + 1, kind="frontier"):
                    self.checkpointer.save_frontier_level(k + 1, rec2.host)
            self.overlap_secs += time.perf_counter() - t_host
            if callback_dedup:
                # numpy radix sort over the real children prefix only.
                level_sort_bytes = levels[k].n * g.max_moves * item
            elif self._fused_table:
                # plain dedup sort + compaction over the padded block.
                level_sort_bytes = in_len * (
                    item + compaction_sort_bytes(item)
                )
            else:
                # scatterinv: ONE (state, i32) pair sort + the compaction
                # (vs the provenance path's two pair sorts).
                level_sort_bytes = in_len * (
                    item + 4 + compaction_sort_bytes(item)
                )
            self.bytes_sorted += level_sort_bytes
            fwd_host_bytes = (
                n * item
                if (self.checkpointer is not None or over_budget) else 0
            )
            self.bytes_host += fwd_host_bytes
            sp.end(
                frontier=levels[k].n,
                children=n,
                bytes_sorted=level_sort_bytes,
                bytes_hbm=level_sort_bytes,
                bytes_host=fwd_host_bytes,
                dispatches=self.dispatch_total - d0,
            )
            self.status_tracker.forward_level(k, levels[k].n, sp.secs)
            flightrec.boundary("forward", k)
            k += 1
        return levels

    @staticmethod
    def _pad_dev(arr, cap: int, fill):
        """Pad a 1-D device array to `cap` with `fill` (no-op when already)."""
        if arr.shape[0] >= cap:
            return arr
        note_dispatch("eager")
        return jnp.concatenate(
            [arr, jnp.full(cap - arr.shape[0], fill, dtype=arr.dtype)]
        )

    def _level_host_bytes(self, k: int, root_level: int, cap: int,
                          n: int, item: int, uploaded: bool,
                          from_checkpoint: bool) -> int:
        """Analytic host-transfer bytes of one resolved fast-path level
        (the roofline span field): the state re-upload when the level
        was host-spilled, plus the table materialization download
        (states + packed values/remoteness) when one will happen. ONE
        formula for both fast backward variants — hand-synced copies
        drift."""
        will_tbl = (
            self.store_tables or k == root_level
            or self.checkpointer is not None
            or self.level_sink is not None
        )
        return (
            (cap * item if uploaded else 0)
            + (n * (item + 5)
               if will_tbl and not from_checkpoint else 0)
        )

    def _backward_plan(self, levels: Dict[int, _Level]):
        """Per-level common capacity: max of own and window (deeper) bucket.

        Padding states and window to ONE capacity keys the backward kernel
        on a single integer, collapsing the (cap, window-cap) shape
        cross-product — at ~15 s per remote compile this halves backward
        compile count; the padding itself is a device-side concat.
        """
        ks = sorted(levels, reverse=True)
        caps = {k: bucket_size(levels[k].n, self.min_bucket) for k in ks}
        common = {}
        # Common-capacity padding halves backward COMPILE count — the right
        # trade at ~15 s per remote compile, the wrong one on CPU where
        # compiles are cheap and the padding is real lookup/combine work on
        # alternating levels. The provenance resolve requires it regardless
        # (its blocked kernel assumes states and window share one shape).
        pad = self.use_provenance or jax.default_backend() != "cpu"
        for k in ks:
            if k + 1 in caps and pad:
                common[k] = max(caps[k], caps[k + 1])
            else:
                common[k] = caps[k]
        return ks, caps, common

    def _resolve_blocked_table(self, rec: _Level, states_dev, cells):
        """Value-table resolve, in column blocks when the level is wide.

        Same memory contract as _resolve_blocked; the cells buffer chains
        through the blocks (each donation hands the table to the next).
        """
        cap = states_dev.shape[0]
        block = self._block_size()
        has_kids = rec.kids is not None and rec.prim is not None
        if cap <= block:
            if has_kids:
                return self._bwdt(cap, True)(cells, states_dev, rec.prim,
                                             rec.kids)
            return self._bwdt(cap, False)(cells, states_dev)
        M = self.game.max_moves
        values, rems = [], []
        misses = None
        for off in range(0, cap, block):
            note_dispatch("eager")
            sd = jax.lax.slice(states_dev, (off,), (off + block,))
            if has_kids:
                pr = jax.lax.slice(rec.prim, (off,), (off + block,))
                kd = jax.lax.slice(rec.kids, (off * M,),
                                   ((off + block) * M,))
                v, r, m, cells = self._bwdt(block, True)(cells, sd, pr, kd)
            else:
                v, r, m, cells = self._bwdt(block, False)(cells, sd)
            values.append(v)
            rems.append(r)
            misses = m if misses is None else misses + m
        note_dispatch("eager")
        return jnp.concatenate(values), jnp.concatenate(rems), misses, cells

    def _backward_fast_table(self, levels: Dict[int, _Level],
                             root_level: int) -> Dict[int, LevelTable]:
        """Fused value-table backward (GAMESMAN_FUSED=1, u32 games within
        the GAMESMAN_FUSED_TABLE_BITS gate): ONE dispatch per level.

        A persistent [2^state_bits] packed-cell table replaces the sliding
        window entirely: level k's kernel gathers its children's cells
        (every child lives in level k+1, scattered the step before),
        combines, and scatters its own cells in — with the table DONATED
        through every call, so the whole sweep ping-pongs between two
        aliases of one allocation. No window slices, no pads, no search,
        no re-expansion (stored kids), no per-level host sync.

        Retry contract under fusion (docs/ARCHITECTURE.md): donation makes
        a failed dispatch non-re-entrant (the consumed table cannot be
        re-presented), so this path has NO per-level retry — a kernel
        failure aborts the solve and recovery is the checkpoint prefix,
        exactly the campaign-level story. The unfused path keeps its
        per-level retry; flip GAMESMAN_FUSED=0 to trade throughput for it.
        """
        g = self.game
        resolved: Dict[int, LevelTable] = {}
        completed = (
            set(self.checkpointer.completed_levels())
            if self.checkpointer is not None
            else set()
        )
        ks = sorted(levels, reverse=True)
        block = self._block_size()
        for k in ks:
            if k in completed:
                continue
            rec = levels[k]
            cap = bucket_size(rec.n, self.min_bucket)
            self._sched_bwdt(min(cap, block),
                             rec.kids is not None and rec.prim is not None)
        T = 1 << g.state_bits
        note_dispatch("table_init")
        cells = jnp.zeros(T, dtype=jnp.uint32)
        pending_fin = None
        for k in ks:
            sp = Span("backward", logger=self.logger, level=k)
            d0 = self.dispatch_total
            rec = levels[k]
            n = rec.n
            self.progress = {"phase": "backward", "level": k, "n": n}
            preempt.check("backward", level=k, logger=self.logger)
            memguard.check("backward", level=k, logger=self.logger)
            uploaded = rec.dev is None
            if rec.dev is not None:
                states_dev = rec.dev
            else:
                note_dispatch("upload")
                states_dev = jnp.asarray(
                    pad_to(rec.host_states(),
                           bucket_size(n, self.min_bucket))
                )
            cap = states_dev.shape[0]
            from_checkpoint = k in completed
            table = None
            if from_checkpoint:
                from gamesmanmpi_tpu.utils.checkpoint import TORN_NPZ_ERRORS

                try:
                    table = self.checkpointer.load_level(k)
                except TORN_NPZ_ERRORS as e:
                    self.checkpointer.quarantine_and_log(k, e, self.logger)
                    from_checkpoint = False
            if from_checkpoint:
                states_host = rec.host_states()
                if table.states.shape[0] != n or not (
                    np.asarray(table.states, dtype=g.state_dtype)
                    == states_host
                ).all():
                    raise SolverError(
                        f"checkpointed level {k} does not match the "
                        "discovered frontier — stale checkpoint directory?"
                    )
                note_dispatch("upload")
                values_dev = jnp.asarray(pad_to_cap_u8(table.values, cap))
                rem_dev = jnp.asarray(pad_to_cap_i32(table.remoteness, cap))
                cells = self._bwdc(cap)(cells, states_dev, values_dev,
                                        rem_dev)
                misses = None
            else:
                faults.fire("engine.backward", level=k)
                values_dev, rem_dev, misses, cells = \
                    self._resolve_blocked_table(rec, states_dev, cells)
                if self.paranoid and int(misses) > 0:
                    raise SolverError(
                        f"level {k}: {int(misses)} consistency failures "
                        "(UNDECIDED child cells — table discipline — or "
                        "non-primitive positions with zero legal moves)"
                    )
            lvl_gather_bytes = 0 if from_checkpoint \
                else cap * g.max_moves * 8  # kid read (4 B) + cell (4 B)
            self.bytes_gathered += lvl_gather_bytes

            def _finalize(k=k, rec=rec, n=n, table=table,
                          values_dev=values_dev, rem_dev=rem_dev,
                          from_checkpoint=from_checkpoint):
                tbl = table
                if tbl is None and (
                    self.store_tables
                    or k == root_level
                    or self.checkpointer is not None
                    or self.level_sink is not None
                ):
                    note_dispatch("download")
                    tbl = LevelTable(
                        states=rec.host_states(),
                        values=np.asarray(values_dev[:n]),
                        remoteness=np.asarray(rem_dev[:n]),
                    )
                if tbl is not None and (self.store_tables
                                        or k == root_level):
                    resolved[k] = tbl
                if self.level_sink is not None and tbl is not None:
                    with trace_span("db_export", level=k, n=n):
                        self.level_sink(k, tbl)
                if self.checkpointer is not None and not from_checkpoint:
                    with trace_span("checkpoint", level=k, kind="level"):
                        self.checkpointer.save_level(k, tbl)
                rec.dev = None
                rec.prim = rec.uidx = rec.kids = None
                if not self.store_tables:
                    rec.host = None

            if pending_fin is not None:
                # The deferred host work runs with this level's kernel in
                # flight — the pipeline's measured overlap.
                t0f = time.perf_counter()
                pending_fin()
                self.overlap_secs += time.perf_counter() - t0f
                pending_fin = None
            if self.pipeline == "pingpong":
                pending_fin = _finalize
            else:
                _finalize()
            if not from_checkpoint and cap >= (1 << 21):
                # Same enqueue-run-ahead bound as the unfused path: one
                # 8-byte fetch per BIG level caps liveness.
                np.asarray(misses)
            item = np.dtype(g.state_dtype).itemsize
            lvl_host_bytes = self._level_host_bytes(
                k, root_level, cap, n, item, uploaded, from_checkpoint
            )
            self.bytes_host += lvl_host_bytes
            sp.end(
                n=n,
                resumed=from_checkpoint,
                bytes_gathered=lvl_gather_bytes,
                bytes_hbm=lvl_gather_bytes,
                bytes_host=lvl_host_bytes,
                dispatches=self.dispatch_total - d0,
            )
            self.status_tracker.backward_level(k, n, sp.secs,
                                               resumed=from_checkpoint)
            flightrec.boundary("backward", k)
        if pending_fin is not None:
            pending_fin()
        return resolved

    def _backward_fast(self, levels: Dict[int, _Level],
                       root_level: int) -> Dict[int, LevelTable]:
        """Deepest-first resolve; the window is the previous (deeper) level."""
        if self._fused_table:
            return self._backward_fast_table(levels, root_level)
        g = self.game
        resolved: Dict[int, LevelTable] = {}
        completed = (
            set(self.checkpointer.completed_levels())
            if self.checkpointer is not None
            else set()
        )
        ks, caps, common = self._backward_plan(levels)
        # All backward shapes are now known exactly; queue them deepest-first
        # so compilation overlaps the deep levels' execution. Checkpointed
        # levels load instead of resolving — no kernel needed.
        block = self._block_size()
        for k in ks:
            if k in completed:
                continue
            C = common[k]
            rec = levels[k]
            if k + 1 in levels and rec.uidx is not None:
                self._sched_bwdp(min(C, block), C)
            else:
                # Window shape = its own bucket padded to C (no-op pad when
                # the plan uses exact buckets) — must match the key the
                # resolve below will request.
                wcaps = (max(C, caps[k + 1]),) if k + 1 in levels else ()
                self._sched_bwd(min(C, block), wcaps)
        prev = None  # (states_dev, values_dev, rem_dev) of level k+1, at its C
        pending_fin = None  # pingpong: the deeper level's deferred host work
        for k in ks:
            sp = Span("backward", logger=self.logger, level=k)
            d0 = self.dispatch_total
            rec = levels[k]
            n = rec.n
            self.progress = {"phase": "backward", "level": k, "n": n}
            preempt.check("backward", level=k, logger=self.logger)
            memguard.check("backward", level=k, logger=self.logger)
            C = common[k]
            uploaded = rec.dev is None
            if rec.dev is not None:
                states_dev = rec.dev
            else:
                note_dispatch("upload")
                states_dev = jnp.asarray(
                    pad_to(rec.host_states(),
                           bucket_size(n, self.min_bucket))
                )
            states_dev = self._pad_dev(states_dev, C, g.sentinel)
            cap = states_dev.shape[0]
            from_checkpoint = k in completed
            item = np.dtype(g.state_dtype).itemsize
            lvl_sort_bytes = lvl_gather_bytes = 0
            table = None
            if from_checkpoint:
                from gamesmanmpi_tpu.utils.checkpoint import TORN_NPZ_ERRORS

                try:
                    table = self.checkpointer.load_level(k)
                except TORN_NPZ_ERRORS as e:
                    # Torn or crc-mismatching sealed level (the loader
                    # already quarantined a crc failure): degrade to the
                    # intact prefix — the frontier is still known, so the
                    # level recomputes and re-seals over the quarantine.
                    self.checkpointer.quarantine_and_log(k, e, self.logger)
                    from_checkpoint = False
            if from_checkpoint:
                states_host = rec.host_states()
                if table.states.shape[0] != n or not (
                    np.asarray(table.states, dtype=g.state_dtype) == states_host
                ).all():
                    raise SolverError(
                        f"checkpointed level {k} does not match the discovered "
                        "frontier — stale checkpoint directory?"
                    )
                values_dev = jnp.asarray(pad_to_cap_u8(table.values, cap))
                rem_dev = jnp.asarray(pad_to_cap_i32(table.remoteness, cap))
            else:
                def _resolve():
                    # The level's inputs (states_dev, prev window triple,
                    # stored provenance) are all still referenced, so a
                    # transient failure re-dispatches idempotently.
                    nonlocal lvl_sort_bytes, lvl_gather_bytes
                    faults.fire("engine.backward", level=k)
                    if prev is not None and rec.uidx is not None:
                        # uidx read (4 B) + packed-cell gather (4 B) per
                        # child.
                        lvl_gather_bytes = C * g.max_moves * 8
                        # Gather-only resolve from forward provenance: no
                        # search, no re-expansion (see resolve_provenance).
                        wcap = caps[k + 1]
                        note_dispatch("eager")
                        note_dispatch("eager")
                        wv = jax.lax.slice(prev[1], (0,), (wcap,))
                        wr = jax.lax.slice(prev[2], (0,), (wcap,))
                        return self._resolve_blocked_prov(
                            n,
                            self._pad_dev(rec.prim, C, np.uint8(UNDECIDED)),
                            self._pad_dev(
                                rec.uidx, C * g.max_moves, np.int32(-1)
                            ),
                            self._pad_dev(wv, C, np.uint8(UNDECIDED)),
                            self._pad_dev(wr, C, np.int32(0)),
                        )
                    if prev is not None:
                        if search_method() == "sort":
                            # Sort-merge join operands + fused u64 payload
                            # gather with its i32 indices.
                            lvl_sort_bytes = (C * g.max_moves + C) * (item + 4)
                            lvl_gather_bytes = C * g.max_moves * 12
                        else:
                            # Binary search: no join sort; one fused payload
                            # gather per child (the log2(W) traversal reads
                            # are not modeled).
                            lvl_gather_bytes = C * g.max_moves * 8
                    if prev is None:
                        args, wcaps = (), ()
                    else:
                        # Slice the deeper level down to its own bucket, then
                        # pad to this level's common capacity when the plan
                        # uses one (see _backward_plan; exact buckets on
                        # CPU, so _pad_dev may no-op and the window keeps
                        # its own shape).
                        wcap = caps[k + 1]
                        for _ in range(3):
                            note_dispatch("eager")
                        ws = jax.lax.slice(prev[0], (0,), (wcap,))
                        wv = jax.lax.slice(prev[1], (0,), (wcap,))
                        wr = jax.lax.slice(prev[2], (0,), (wcap,))
                        args = (
                            self._pad_dev(ws, C, g.sentinel),
                            self._pad_dev(wv, C, np.uint8(UNDECIDED)),
                            self._pad_dev(wr, C, np.int32(0)),
                        )
                        wcaps = (args[0].shape[0],)
                    return self._resolve_blocked(states_dev, wcaps, args)

                values_dev, rem_dev, misses = self._retry(
                    "engine.backward", _resolve, level=k
                )
                if self.paranoid and int(misses) > 0:
                    raise SolverError(
                        f"level {k}: {int(misses)} consistency failures (child "
                        "lookups outside the solved window — level_of/"
                        "max_level_jump inconsistent — or non-primitive "
                        "positions with zero legal moves)"
                    )
            prev = (states_dev, values_dev, rem_dev)

            def _finalize(k=k, rec=rec, n=n, table=table,
                          values_dev=values_dev, rem_dev=rem_dev,
                          from_checkpoint=from_checkpoint):
                # The level's host-side tail: table materialization (the
                # downloads), export, checkpoint seal, buffer release. In
                # pingpong mode this runs one level LATE — after the next
                # (shallower) level's kernel is dispatched — so the
                # downloads overlap device execution (overlap_secs).
                tbl = table
                if tbl is None and (
                    self.store_tables
                    or k == root_level
                    or self.checkpointer is not None
                    or self.level_sink is not None
                ):
                    note_dispatch("download")
                    tbl = LevelTable(
                        states=rec.host_states(),
                        values=np.asarray(values_dev[:n]),
                        remoteness=np.asarray(rem_dev[:n]),
                    )
                if tbl is not None and (self.store_tables
                                        or k == root_level):
                    resolved[k] = tbl
                if self.level_sink is not None and tbl is not None:
                    with trace_span("db_export", level=k, n=n):
                        self.level_sink(k, tbl)
                if self.checkpointer is not None and not from_checkpoint:
                    with trace_span("checkpoint", level=k, kind="level"):
                        self.checkpointer.save_level(k, tbl)
                rec.dev = None  # release the forward copy
                rec.prim = rec.uidx = rec.kids = None  # release provenance
                if not self.store_tables:
                    rec.host = None

            if pending_fin is not None:
                t0f = time.perf_counter()
                pending_fin()
                self.overlap_secs += time.perf_counter() - t0f
                pending_fin = None
            if self.pipeline == "pingpong":
                pending_fin = _finalize
            else:
                _finalize()
            if not from_checkpoint and C >= (1 << 21):
                # Bound enqueue run-ahead: with no per-level downloads the
                # host races through the whole backward, allocating every
                # level's padded inputs before any kernel retires — enough
                # to OOM HBM at 5x5 scale. An 8-byte fetch (~65 ms) per BIG
                # level caps liveness at ~one level's working set; small
                # levels stay fully async.
                np.asarray(misses)
            self.bytes_sorted += lvl_sort_bytes
            self.bytes_gathered += lvl_gather_bytes
            lvl_host_bytes = self._level_host_bytes(
                k, root_level, cap, n, item, uploaded, from_checkpoint
            )
            self.bytes_host += lvl_host_bytes
            sp.end(
                n=n,
                resumed=from_checkpoint,
                bytes_sorted=lvl_sort_bytes,
                bytes_gathered=lvl_gather_bytes,
                bytes_hbm=lvl_sort_bytes + lvl_gather_bytes,
                bytes_host=lvl_host_bytes,
                dispatches=self.dispatch_total - d0,
            )
            self.status_tracker.backward_level(k, n, sp.secs,
                                               resumed=from_checkpoint)
            flightrec.boundary("backward", k)
        if pending_fin is not None:
            pending_fin()
        return resolved

    # ---------------------------------------------------------- generic phase

    def _forward_generic(self, pools: Dict[int, np.ndarray], start_level: int):
        """Host-pooled forward for multi-jump games (children span levels)."""
        g = self.game
        k = start_level
        while pools and k <= max(pools):
            if k not in pools:
                k += 1
                continue
            sp = Span("forward", logger=self.logger, level=k)
            frontier = pools[k]
            self.progress = {
                "phase": "forward", "level": k,
                "frontier": int(frontier.shape[0]),
            }
            preempt.check("forward", level=k, logger=self.logger)
            memguard.check("forward", level=k, logger=self.logger)
            padded = pad_to_bucket(frontier, self.min_bucket)
            note_dispatch("upload")
            fwd_args = (jnp.asarray(padded),)
            if self.use_fused:
                # The megakernel takes the real row count so its callback
                # dedup sorts only the real prefix.
                fwd_args += (np.int32(frontier.shape[0]),)
            uniq, levels, count = self._fwd_generic(padded.shape[0])(
                *fwd_args
            )
            # expand_core's dedup sort (+ compaction re-sort when the
            # platform lowers compaction as a sort). The fused callback
            # lowering sorts only the real children prefix — its operand
            # accounting must match the kernel that ran.
            item = np.dtype(g.state_dtype).itemsize
            if self.use_fused and fused_dedup_method() == "callback":
                lvl_sort_bytes = frontier.shape[0] * g.max_moves * item
            else:
                lvl_sort_bytes = (
                    padded.shape[0] * g.max_moves
                    * (item + compaction_sort_bytes(item))
                )
            self.bytes_sorted += lvl_sort_bytes
            # Generic-path dedup is two-stage: the kernel's sort-unique
            # (whose wait is the int(count) sync) plus the host-side
            # merge of multi-jump children into per-level pools.
            with trace_span("dedup", level=k):
                holder = [(uniq, levels, count)]

                def _sync(holder=holder, k=k):
                    faults.fire("engine.forward", level=k)
                    faults.fire("engine.dedup", level=k)
                    u, lv, c = holder[0]
                    nn = int(c)
                    return nn, np.asarray(u[:nn]), np.asarray(lv[:nn])

                def _redispatch(holder=holder, fwd_args=fwd_args,
                                padded=padded):
                    holder[0] = self._fwd_generic(padded.shape[0])(
                        *fwd_args
                    )

                n, kids, kid_levels = self._retry(
                    "engine.forward", _sync, reset=_redispatch, level=k
                )
                for lv in np.unique(kid_levels):
                    lv = int(lv)
                    if lv >= g.num_levels:
                        raise SolverError(
                            f"game {g.name}: children found at level {lv} "
                            f"but num_levels={g.num_levels} — level_of/"
                            "num_levels inconsistent"
                        )
                    batch = kids[kid_levels == lv]
                    if lv in pools:
                        pools[lv] = np.union1d(pools[lv], batch)
                    else:
                        pools[lv] = batch
            sp.end(
                frontier=int(frontier.shape[0]),
                children=n,
                bytes_sorted=lvl_sort_bytes,
                bytes_hbm=lvl_sort_bytes,
            )
            self.status_tracker.forward_level(
                k, int(frontier.shape[0]), sp.secs
            )
            flightrec.boundary("forward", k)
            k += 1

    def _backward_generic(self, pools: Dict[int, np.ndarray],
                          root_level: int) -> Dict[int, LevelTable]:
        """Resolve all levels deepest-first against a multi-level window.

        Levels already present in the checkpoint (a previous, preempted run)
        are loaded instead of recomputed — restart-from-level recovery.
        store_tables=False only bounds result-RAM here (tables are still
        materialized transiently for the host window cache; the multi-jump
        games in the catalog are small — the big-run mode that avoids
        downloads entirely is the fast path and the sharded solver).
        """
        g = self.game
        resolved: Dict[int, LevelTable] = {}
        padded_cache: Dict[int, tuple] = {}
        completed = (
            set(self.checkpointer.completed_levels())
            if self.checkpointer is not None
            else set()
        )
        for k in sorted(pools, reverse=True):
            sp = Span("backward", logger=self.logger, level=k)
            states = pools[k]
            padded = pad_to_bucket(states, self.min_bucket)
            n = states.shape[0]
            self.progress = {"phase": "backward", "level": k, "n": int(n)}
            preempt.check("backward", level=k, logger=self.logger)
            memguard.check("backward", level=k, logger=self.logger)
            from_checkpoint = k in completed
            lvl_sort_bytes = lvl_gather_bytes = 0
            table = None
            if from_checkpoint:
                from gamesmanmpi_tpu.utils.checkpoint import TORN_NPZ_ERRORS

                try:
                    table = self.checkpointer.load_level(k)
                except TORN_NPZ_ERRORS as e:
                    # Same degrade contract as the fast path: quarantine
                    # and recompute from the still-known frontier.
                    self.checkpointer.quarantine_and_log(k, e, self.logger)
                    from_checkpoint = False
            if from_checkpoint:
                if table.states.shape[0] != n or not (
                    np.asarray(table.states, dtype=g.state_dtype) == states
                ).all():
                    raise SolverError(
                        f"checkpointed level {k} does not match the discovered "
                        "frontier — stale checkpoint directory?"
                    )
            else:
                window_levels = [
                    k + j
                    for j in range(1, g.max_level_jump + 1)
                    if (k + j) in padded_cache
                ]
                window_flat = []
                for L in window_levels:
                    window_flat.extend(padded_cache[L])
                wcaps = tuple(padded_cache[L][0].shape[0] for L in window_levels)
                item = np.dtype(g.state_dtype).itemsize
                cm = padded.shape[0] * g.max_moves
                if search_method() == "sort":
                    # Per-window-level sort-merge joins + payload gathers.
                    lvl_sort_bytes = sum(
                        (cm + w) * (item + 4) for w in wcaps
                    )
                    lvl_gather_bytes = cm * 12 * len(wcaps)
                else:
                    # Binary search: payload gathers only.
                    lvl_gather_bytes = cm * 8 * len(wcaps)
                self.bytes_sorted += lvl_sort_bytes
                self.bytes_gathered += lvl_gather_bytes

                def _resolve():
                    faults.fire("engine.backward", level=k)
                    return self._resolve_blocked(
                        jnp.asarray(padded), wcaps,
                        tuple(jnp.asarray(a) for a in window_flat),
                    )

                values_dev, rem_dev, misses = self._retry(
                    "engine.backward", _resolve, level=k
                )
                if self.paranoid and int(misses) > 0:
                    raise SolverError(
                        f"level {k}: {int(misses)} consistency failures (child "
                        "lookups outside the solved window — level_of/"
                        "max_level_jump inconsistent — or non-primitive "
                        "positions with zero legal moves)"
                    )
                values = np.asarray(values_dev[:n])
                remoteness = np.asarray(rem_dev[:n])
                table = LevelTable(states=states, values=values,
                                   remoteness=remoteness)
            if self.store_tables or k == root_level:
                resolved[k] = table
            if self.level_sink is not None:
                with trace_span("db_export", level=k, n=int(n)):
                    self.level_sink(k, table)
            cap = padded.shape[0]
            pv = np.full(cap, UNDECIDED, dtype=np.uint8)
            pr = np.zeros(cap, dtype=np.int32)
            pv[:n] = table.values
            pr[:n] = table.remoteness
            padded_cache[k] = (padded, pv, pr)
            # Levels deeper than the lookback window can never be read again.
            for done in [d for d in padded_cache if d > k + g.max_level_jump]:
                del padded_cache[done]
            item = np.dtype(g.state_dtype).itemsize
            # Deliberately NOT _level_host_bytes: generic-path pools are
            # host-resident (the padded frontier uploads every level)
            # and states never re-download — only the packed
            # values/remoteness (5 B/row) come back.
            lvl_host_bytes = (
                padded.shape[0] * item
                + (0 if from_checkpoint else int(n) * 5)
            )
            self.bytes_host += lvl_host_bytes
            sp.end(
                n=n,
                resumed=from_checkpoint,
                bytes_sorted=lvl_sort_bytes,
                bytes_gathered=lvl_gather_bytes,
                bytes_hbm=lvl_sort_bytes + lvl_gather_bytes,
                bytes_host=lvl_host_bytes,
            )
            self.status_tracker.backward_level(k, int(n), sp.secs,
                                               resumed=from_checkpoint)
            flightrec.boundary("backward", k)
            if self.checkpointer is not None and not from_checkpoint:
                with trace_span("checkpoint", level=k, kind="level"):
                    self.checkpointer.save_level(k, table)
        return resolved

    # ------------------------------------------------------------------ solve

    def solve(self) -> SolveResult:
        """Public entry: the solve body under an optional heartbeat.

        The heartbeat thread (obs/heartbeat.py) reads `self.progress` —
        replaced atomically at each phase/level boundary — and emits
        periodic JSONL records + registry gauges, so a wedged multi-hour
        solve reports its last known level, RSS, and device memory. The
        watchdog (resilience/supervisor.py, GAMESMAN_WATCHDOG_SECS)
        reads the same progress and turns a stall past its adaptive
        deadline into a diagnosed abort with the checkpoint prefix
        intact."""
        hb = None
        if self.heartbeat_secs > 0:
            hb = Heartbeat(
                self.heartbeat_secs,
                progress=lambda: self.progress,
                logger=self.logger,
            ).start()
        wd = maybe_watchdog(lambda: self.progress, logger=self.logger)
        # Live status endpoint (GAMESMAN_STATUS_PORT / --status-port):
        # read-only /status + /metrics served for the solve's lifetime.
        self.status_tracker.begin(
            game=self.game.name, engine="classic", world=1, rank=0,
        )
        status_srv = maybe_status_server(self._status_payload)
        prev_sink = set_dispatch_sink(self._on_dispatch)
        try:
            return self._solve_impl()
        finally:
            set_dispatch_sink(prev_sink)
            if hb is not None:
                hb.stop()
            if wd is not None:
                wd.stop()
            if status_srv is not None:
                status_srv.stop()

    def _status_payload(self) -> dict:
        """The /status body (runs on HTTP handler threads: reads only
        atomically-replaced state — the `progress` contract)."""
        snap = self.status_tracker.snapshot(progress=self.progress)
        snap["retries"] = self.retries
        snap["dispatches_total"] = self.dispatch_total
        return snap

    def _solve_impl(self) -> SolveResult:
        g = self.game
        t0 = time.perf_counter()
        # ISSUE 14 gates, resolved at solve time like every env/platform-
        # auto knob. The fused fast path always carries backward inputs
        # forward: canonical children when the value table applies (u32
        # within GAMESMAN_FUSED_TABLE_BITS), dedup provenance otherwise —
        # so use_provenance is implied by the mode, not the platform.
        self.use_fused = fused_enabled()
        self.pipeline = pipeline_mode()
        self._fused_table = (
            self.use_fused and self.fast and use_value_table(g)
        )
        if self.use_fused:
            self.use_provenance = self.fast and not self._fused_table
        else:
            # Platform-auto knob, resolved here (not in __init__) so a
            # force_platform between construction and solve() is honored.
            self.use_provenance = platform_auto_bool(
                "GAMESMAN_PROVENANCE", accel=True, cpu=False
            )
        if self.checkpointer is not None:
            self.checkpointer.bind_game(g.name)
        saved = (
            self.checkpointer.load_frontiers()
            if self.checkpointer is not None
            else None
        )
        # A previous run's interrupted forward left per-level frontier
        # files: continue expansion from its deepest level.
        partial = (
            self.checkpointer.load_forward_levels()
            if self.fast and saved is None and self.checkpointer is not None
            else {}
        )
        if self.fast and saved is None:
            if not partial:
                # Fully-resumed runs skip forward discovery entirely — the
                # ladder's speculative forward compiles would be dead
                # weight; mid-forward resumes (below) seed the plan at the
                # resumed capacity instead of the root's min_bucket.
                self._schedule_initial_ladder()
            else:
                cap = bucket_size(partial[max(partial)].shape[0],
                                  self.min_bucket)
                for c in (cap, cap * 2, cap * 4):
                    self._sched_fwd_step(c)
                    self._sched_bwd_step(min(c, self._block_size()), c)
        init, start_level = canonical_scalar(g, g.initial_state())
        if self.fast:
            if saved is not None:
                levels = {
                    k: _Level(v.shape[0], np.asarray(v, dtype=g.state_dtype),
                              None)
                    for k, v in saved.items()
                }
            else:
                fwd = (self._forward_fast_fused if self.use_fused
                       else self._forward_fast)
                levels = fwd(init, start_level, resume=partial or None)
                if self.checkpointer is not None:
                    self.checkpointer.mark_frontiers_complete()
            t_forward = time.perf_counter() - t0
            num_positions = sum(rec.n for rec in levels.values())
            # Forward fixed the per-level position counts: publish the
            # level schedule so /status's ETA model knows the remaining
            # backward work exactly (obs/status.py).
            self.status_tracker.set_schedule(
                {k: rec.n for k, rec in levels.items()}
            )
            resolved = self._backward_fast(levels, start_level)
        else:
            if saved is not None:
                pools = {
                    k: np.asarray(v, dtype=g.state_dtype)
                    for k, v in saved.items()
                }
            else:
                pools = {start_level: np.array([init], g.state_dtype)}
                self._forward_generic(pools, start_level)
                if self.checkpointer is not None:
                    self.checkpointer.save_frontiers(pools)
            t_forward = time.perf_counter() - t0
            num_positions = sum(int(a.shape[0]) for a in pools.values())
            self.status_tracker.set_schedule(
                {k: int(a.shape[0]) for k, a in pools.items()}
            )
            resolved = self._backward_generic(pools, start_level)

        t_total = time.perf_counter() - t0
        root = resolved[start_level]
        i = int(np.searchsorted(root.states, init))
        if i >= root.states.shape[0] or root.states[i] != init:
            # A canonicalization/level_of bug would otherwise silently read
            # a neighboring entry (VERDICT.md r2 weak #6: make it loud).
            raise SolverError(
                f"root state {int(init):#x} missing from its solved level "
                f"{start_level} — canonicalize/level_of inconsistent"
            )
        value = int(root.values[i])
        remoteness = int(root.remoteness[i])
        stats = {
            "game": g.name,
            "positions": num_positions,
            "levels": len(resolved),
            "secs_forward": t_forward,
            "secs_backward": t_total - t_forward,
            "secs_total": t_total,
            "positions_per_sec": num_positions / max(t_total, 1e-9),
            # Transient level-step failures absorbed by retry (0 on a
            # clean run; the per-point breakdown is in the registry's
            # gamesman_retries_total).
            "retries": self.retries,
            # Roofline denominators (SURVEY.md §5.5): analytic operand
            # bytes of the sort/gather kernels; see docs/ARCHITECTURE.md
            # "Efficiency accounting" for how to read them.
            "bytes_sorted": self.bytes_sorted,
            "bytes_gathered": self.bytes_gathered,
            # ISSUE 14 dispatch economy: total device computations/
            # transfers this solve issued, per discovered level, plus the
            # host seconds the pingpong pipeline overlapped with device
            # execution. These are what a bench record cites to prove the
            # fused path dispatches LESS, not just runs faster.
            "dispatches_total": self.dispatch_total,
            "dispatches_per_level": round(
                self.dispatch_total
                / max(len(levels) if self.fast else len(pools), 1), 2),
            "overlap_secs": round(self.overlap_secs, 3),
            "fused": bool(self.use_fused),
            "pipeline": self.pipeline,
            # ISSUE 15 roofline accounting: the per-solve rollup of the
            # per-level bytes_hbm/bytes_host/dispatches/wall span fields.
            # dispatch_overhead_frac prices the dispatch count against a
            # measured per-dispatch cost (bench.py calibrates
            # GAMESMAN_DISPATCH_COST_SECS on the running host; 0 = not
            # calibrated, the fraction reads 0 rather than invented).
            "bytes_host": self.bytes_host,
            "roofline": roofline_stats(
                self.bytes_sorted + self.bytes_gathered,
                num_positions, t_total, self.dispatch_total, chips=1,
            ),
        }
        self.progress = {"phase": "done"}
        if self.logger is not None:
            self.logger.log({"phase": "done", **stats})
        # Solve-level registry rollups: the counters a /metrics scrape (or
        # --metrics-out dump) aggregates across every solve this process
        # ran — the per-level breakdown lives in gamesman_span_seconds.
        reg = default_registry()
        reg.counter(
            "gamesman_solves_total", "completed solves", game=g.name
        ).inc()
        reg.counter(
            "gamesman_solve_positions_total",
            "reachable positions solved", game=g.name,
        ).inc(num_positions)
        reg.histogram(
            "gamesman_solve_seconds", "wall seconds per full solve",
            game=g.name,
        ).observe(t_total)
        return SolveResult(g, value, remoteness, resolved, stats)


def pad_to_cap_u8(a, cap: int) -> np.ndarray:
    out = np.full(cap, UNDECIDED, dtype=np.uint8)
    out[: len(a)] = a
    return out


def pad_to_cap_i32(a, cap: int) -> np.ndarray:
    out = np.zeros(cap, dtype=np.int32)
    out[: len(a)] = a
    return out


def solve(game: TensorGame, **kwargs) -> SolveResult:
    """Convenience: Solver(game, **kwargs).solve()."""
    return Solver(game, **kwargs).solve()
