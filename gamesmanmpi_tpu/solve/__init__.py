"""solve: the solver engines (single-device sweep, dense class engine,
dense/BFS hybrid, host oracle). The dense and hybrid engines live in
solve.dense / solve.hybrid and are imported lazily by their users (CLI,
bench) — they are Connect-4-family specific."""

from gamesmanmpi_tpu.solve.engine import Solver, SolveResult, LevelTable
from gamesmanmpi_tpu.solve.oracle import oracle_solve

__all__ = ["Solver", "SolveResult", "LevelTable", "oracle_solve"]
