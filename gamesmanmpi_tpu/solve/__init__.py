"""solve: the solver engines (single-device sweep + host oracle)."""

from gamesmanmpi_tpu.solve.engine import Solver, SolveResult, LevelTable
from gamesmanmpi_tpu.solve.oracle import oracle_solve

__all__ = ["Solver", "SolveResult", "LevelTable", "oracle_solve"]
