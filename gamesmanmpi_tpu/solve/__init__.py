"""solve: the solver engines (single-device sweep, dense class engine,
host oracle). The dense engine lives in solve.dense and is imported lazily
by its users (CLI, bench) — it is Connect-4-family specific."""

from gamesmanmpi_tpu.solve.engine import Solver, SolveResult, LevelTable
from gamesmanmpi_tpu.solve.oracle import oracle_solve

__all__ = ["Solver", "SolveResult", "LevelTable", "oracle_solve"]
