"""Pure-Python oracle: memoized negamax over the reference's scalar game API.

This is the ~50-line reference solver SURVEY.md §4.2 prescribes as the parity
axis: an implementation-independent ground truth with the same observable
semantics as the reference's distributed solve (value + remoteness of every
reachable position). It consumes *unmodified reference-style modules* —
`initial_position`, `gen_moves`/`generate_moves`, `do_move`, `primitive` —
and is also the execution path of the compat shim for arbitrary plugin
modules (gamesmanmpi_tpu.compat).

Primitive return values are normalized: the reference's string constants
("WIN"/"LOSE"/"TIE"/"UNDECIDED", SURVEY.md §2.2 "Constants"), our uint8
constants, or None for undecided are all accepted.
"""

from __future__ import annotations

from typing import Dict, Tuple

from gamesmanmpi_tpu.core.values import (
    WIN,
    LOSE,
    TIE,
    UNDECIDED,
    MAX_REMOTENESS,
)

_STRING_VALUES = {
    "WIN": WIN,
    "LOSE": LOSE,
    "LOSS": LOSE,
    "TIE": TIE,
    "DRAW": TIE,
    "UNDECIDED": UNDECIDED,
}


def normalize_value(v) -> int:
    """Map a primitive() return (str/int/None) onto the uint8 constants."""
    if v is None:
        return UNDECIDED
    if isinstance(v, str):
        try:
            return _STRING_VALUES[v.upper()]
        except KeyError:
            raise ValueError(f"unrecognized primitive value {v!r}") from None
    v = int(v)
    if v not in (WIN, LOSE, TIE, UNDECIDED):
        raise ValueError(f"unrecognized primitive value {v!r}")
    return v


def module_api(module):
    """Extract (initial_position, gen_moves, do_move, primitive) from a module.

    Accepts both spellings of the move generator (SURVEY.md §2.1.1 flags the
    reference's exact name as gen_moves vs generate_moves — support both).
    """
    gen = getattr(module, "gen_moves", None) or getattr(module, "generate_moves", None)
    if gen is None:
        raise AttributeError("game module needs gen_moves or generate_moves")
    for attr in ("initial_position", "do_move", "primitive"):
        if not hasattr(module, attr):
            raise AttributeError(f"game module needs {attr}")
    return module.initial_position, gen, module.do_move, module.primitive


def combine_host(child_results) -> Tuple[int, int]:
    """Host twin of ops.combine.combine_children for one parent.

    child_results: list of (value, remoteness) in child perspective.
    """
    lose = [r for v, r in child_results if v == LOSE]
    tie = [r for v, r in child_results if v == TIE]
    if lose:
        return WIN, 1 + min(lose)
    if tie:
        return TIE, 1 + max(tie)
    if not child_results:
        return LOSE, 0
    return LOSE, 1 + max(r for _, r in child_results)


def oracle_solve(module) -> Tuple[int, int, Dict[object, Tuple[int, int]]]:
    """Strongly solve a scalar game module.

    Returns (root_value, root_remoteness, table) where table maps every
    reachable position to its (value, remoteness). Iterative DFS (explicit
    stack) so deep games don't hit the recursion limit; raises on cycles
    (the reference's recursion assumes acyclic games, SURVEY.md §2.1.5).
    """
    initial, gen_moves, do_move, primitive = module_api(module)
    table: Dict[object, Tuple[int, int]] = {}
    on_stack = set()
    # Stack frames: (pos, children list or None, next child index, results).
    stack = [[initial, None, 0, []]]
    on_stack.add(initial)
    while stack:
        frame = stack[-1]
        pos, children, idx, results = frame
        if children is None:
            value = normalize_value(primitive(pos))
            if value != UNDECIDED:
                table[pos] = (value, 0)
                on_stack.discard(pos)
                stack.pop()
                continue
            frame[1] = children = [do_move(pos, m) for m in gen_moves(pos)]
        if idx < len(children):
            child = children[idx]
            frame[2] += 1
            if child in table:
                results.append(table[child])
            elif child in on_stack:
                raise ValueError(
                    f"cycle detected at position {child!r}; oracle (like the "
                    "reference) requires acyclic games"
                )
            else:
                stack.append([child, None, 0, []])
                on_stack.add(child)
            continue
        # All children resolved.
        missing = len(children) - len(results)
        if missing:
            # Children solved after we pushed them: collect now.
            results = [table[c] for c in children]
        value, remoteness = combine_host(results)
        if remoteness > MAX_REMOTENESS:
            raise ValueError("remoteness overflow")
        table[pos] = (value, remoteness)
        on_stack.discard(pos)
        stack.pop()
    root_value, root_rem = table[initial]
    return root_value, root_rem, table
