"""Game-theoretic value algebra.

Rebuild of the reference's value constants and `negate` (src/utils.py per
SURVEY.md §2.2; the reference stores values as strings — here they are uint8 so
whole frontiers of them live in TPU registers/HBM).

Semantics (SURVEY.md §2.1, items 2-3):

  A position's value is from the perspective of the player to move (negamax):
    WIN  iff at least one child is LOSE
    TIE  iff no child is LOSE and at least one child is TIE
    LOSE iff all children are WIN (vacuously LOSE with zero children)

  Remoteness (GamesCrafters convention; moves-to-end under optimal play):
    primitive positions have remoteness 0
    WIN  -> 1 + min remoteness over LOSE children   (win as fast as possible)
    LOSE -> 1 + max remoteness over all children    (delay losing)
    TIE  -> 1 + max remoteness over TIE children

The TIE min/max choice is flagged [MED] in SURVEY.md §2.1.3; the convention used
here (max) is applied consistently in both the JAX kernels (ops/combine.py) and
the pure-Python oracle (solve/oracle.py), and gives the known 3x3 tic-tac-toe
answer (TIE, remoteness 9).
"""

import jax.numpy as jnp
import numpy as np

# uint8 encodings. UNDECIDED doubles as "not yet resolved" in tables.
UNDECIDED = 0
WIN = 1
LOSE = 2
TIE = 3

VALUE_NAMES = {UNDECIDED: "UNDECIDED", WIN: "WIN", LOSE: "LOSE", TIE: "TIE"}

# negate: value from the parent's perspective of a child's value.
# WIN <-> LOSE, TIE -> TIE, UNDECIDED -> UNDECIDED (src/utils.py `negate`).
# NB: no module-level jnp constants anywhere in this package — they would
# initialize the JAX backend at import time, before callers (tests, the
# multichip dry run) can select a platform.
_NEGATE_TABLE = np.array([UNDECIDED, LOSE, WIN, TIE], dtype=np.uint8)

VALUE_DTYPE = jnp.uint8
REMOTENESS_DTYPE = jnp.int32

# Remoteness values are packed into 30 bits in core/codec.py; this bound also
# serves as the +inf pad for masked min-reductions in ops/combine.py.
MAX_REMOTENESS = (1 << 30) - 1


def negate(values):
    """Vectorized negate over a uint8 value array (or scalar)."""
    return jnp.asarray(_NEGATE_TABLE)[values]


def negate_np(values):
    """NumPy twin of `negate` for host-side code (oracle, compat shim)."""
    return _NEGATE_TABLE[values]


def value_name(v) -> str:
    """Human-readable name of a value constant (rank-0 output formatting)."""
    return VALUE_NAMES[int(v)]
