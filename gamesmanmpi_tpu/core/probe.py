"""Host-side sorted-table probe: the one canonicalize→probe search.

The NumPy twin of ops/lookup.py's sorted-level search, shared by every
host query route — the solved-position DB reader (db/reader.py),
in-process point queries (solve/engine.SolveResult.lookup), and
checkpoint point queries (utils/checkpoint.py). It lives in core/ because
it depends only on numpy and everything above it probes through it; the
db package re-exports it (db/format.py) as part of the DB format's API.
"""

from __future__ import annotations

import numpy as np


def probe_sorted_np(keys: np.ndarray, queries: np.ndarray):
    """Vectorized binary search of canonical queries in one sorted level.

    keys: [N] sorted strictly-ascending states (no sentinel entries —
    DbWriter enforces that, unlike the device tables in ops/lookup.py
    which carry sentinel tails). queries: [K] same dtype.
    Returns (idx [K] int64 clipped in-range, hit [K] bool).
    """
    queries = np.asarray(queries)
    n = int(np.asarray(keys).shape[0])
    if n == 0:
        shape = queries.shape
        return np.zeros(shape, dtype=np.int64), np.zeros(shape, dtype=bool)
    idx = np.minimum(np.searchsorted(keys, queries), n - 1).astype(np.int64)
    hit = np.asarray(keys[idx]) == queries
    return idx, hit
