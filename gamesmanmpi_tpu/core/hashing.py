"""Owner hashing: which shard stores/answers a position.

The reference routes every position to a single owner rank via
`hash(pos) % world_size` (src/game_state.py `get_hash`, SURVEY.md §2.2 / §2.4
"hash-partitioned state-space parallelism"). Python's `hash` of an int is the
int itself, which shards the reference's tables badly for structured encodings;
here we use splitmix64 — a cheap, well-mixed uint64 permutation that runs
vectorized on-device — before the modulo, preserving the contract (total,
deterministic, single owner per position) while load-balancing structured
bitboard keys.
"""

import jax.numpy as jnp
import numpy as np

_C1 = np.uint64(0x9E3779B97F4A7C15)
_C2 = np.uint64(0xBF58476D1CE4E5B9)
_C3 = np.uint64(0x94D049BB133111EB)


def splitmix64(x):
    """splitmix64 finalizer: a bijective mix of uint64 (vectorized)."""
    z = jnp.asarray(x, jnp.uint64) + _C1
    z = (z ^ (z >> np.uint64(30))) * _C2
    z = (z ^ (z >> np.uint64(27))) * _C3
    return z ^ (z >> np.uint64(31))


def owner_shard(states, num_shards: int):
    """Owner shard index in [0, num_shards) for each packed state.

    The TPU analog of the reference's `hash(pos) % world_size` rank routing.
    """
    return (splitmix64(states) % np.uint64(num_shards)).astype(jnp.int32)


def splitmix64_np(x):
    """NumPy twin of splitmix64 for host-side partition checks/tests."""
    with np.errstate(over="ignore"):
        z = np.asarray(x, np.uint64) + _C1
        z = (z ^ (z >> np.uint64(30))) * _C2
        z = (z ^ (z >> np.uint64(27))) * _C3
        return z ^ (z >> np.uint64(31))


def owner_shard_np(states, num_shards: int):
    """NumPy twin of owner_shard."""
    return (splitmix64_np(states) % np.uint64(num_shards)).astype(np.int32)
