"""Packed table-cell codec: (value, remoteness) <-> uint32.

The reference keeps two Python dicts per rank (`resolved: {pos: value}` and
`remote: {pos: remoteness}`, src/process.py per SURVEY.md §2.2). Here a solved
position's record is a single uint32 cell — value in the low 2 bits, remoteness
in the remaining 30 — so a billion-position table shard is 4 bytes/cell in HBM
and checkpoints are flat arrays (utils/checkpoint.py).
"""

import jax.numpy as jnp
import numpy as np

from gamesmanmpi_tpu.core.values import MAX_REMOTENESS

_VALUE_BITS = 2
_VALUE_MASK = (1 << _VALUE_BITS) - 1

CELL_DTYPE = jnp.uint32


def pack_cells(values, remoteness):
    """Pack uint8 values + int32 remoteness into uint32 cells.

    Remoteness must be in [0, MAX_REMOTENESS]; values in [0, 3].
    """
    v = values.astype(jnp.uint32) & _VALUE_MASK
    r = jnp.clip(remoteness, 0, MAX_REMOTENESS).astype(jnp.uint32)
    return v | (r << _VALUE_BITS)


def unpack_cells(cells):
    """Inverse of pack_cells -> (values uint8, remoteness int32)."""
    values = (cells & _VALUE_MASK).astype(jnp.uint8)
    remoteness = (cells >> _VALUE_BITS).astype(jnp.int32)
    return values, remoteness


def pack_cells_np(values, remoteness):
    """NumPy twin of pack_cells for host-side code (checkpoint writers)."""
    v = values.astype(np.uint32) & _VALUE_MASK
    r = np.clip(remoteness, 0, MAX_REMOTENESS).astype(np.uint32)
    return v | (np.uint32(r) << np.uint32(_VALUE_BITS))


def unpack_cells_np(cells):
    """NumPy twin of unpack_cells."""
    values = (cells & _VALUE_MASK).astype(np.uint8)
    remoteness = (cells >> _VALUE_BITS).astype(np.int32)
    return values, remoteness
