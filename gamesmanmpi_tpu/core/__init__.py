"""Core: value algebra, state codecs, hashing, bit ops.

TPU-native counterpart of the reference's src/utils.py (value constants, negate)
and the representation half of src/game_state.py (SURVEY.md §2.2).
"""

from gamesmanmpi_tpu.core.values import (
    WIN,
    LOSE,
    TIE,
    UNDECIDED,
    VALUE_NAMES,
    negate,
    value_name,
)
from gamesmanmpi_tpu.core.codec import pack_cells, unpack_cells
from gamesmanmpi_tpu.core.hashing import splitmix64, owner_shard
from gamesmanmpi_tpu.core.bitops import (
    SENTINEL32,
    SENTINEL64,
    popcount,
    msb_index,
    sentinel_for,
    state_dtype_for,
)

__all__ = [
    "WIN",
    "LOSE",
    "TIE",
    "UNDECIDED",
    "VALUE_NAMES",
    "negate",
    "value_name",
    "pack_cells",
    "unpack_cells",
    "splitmix64",
    "owner_shard",
    "popcount",
    "msb_index",
    "sentinel_for",
    "state_dtype_for",
    "SENTINEL32",
    "SENTINEL64",
]
