"""uint64 bit primitives shared by state codecs and games.

All positions in this framework are bit-packed uint64 scalars (SURVEY.md §7:
"bit-packed state codecs"); these helpers are the common vocabulary.
"""

import jax
import jax.numpy as jnp
import numpy as np

# Padding sentinel for frontiers/tables: sorts after every real state, so
# sorted arrays keep their sentinel tail and searchsorted stays correct.
SENTINEL = np.uint64(0xFFFF_FFFF_FFFF_FFFF)

U64_ONE = np.uint64(1)


def u64(x) -> jnp.ndarray:
    """A uint64 jnp scalar/array from a Python int or array."""
    return jnp.asarray(x, dtype=jnp.uint64)


def popcount64(x):
    """Population count of a uint64 array."""
    return jax.lax.population_count(jnp.asarray(x, jnp.uint64)).astype(jnp.int32)


def msb_index64(x):
    """Index of the most-significant set bit of x (x must be nonzero)."""
    clz = jax.lax.clz(jnp.asarray(x, jnp.uint64)).astype(jnp.int32)
    return 63 - clz
