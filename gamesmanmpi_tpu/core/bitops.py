"""Bit primitives shared by state codecs and games.

All positions in this framework are bit-packed unsigned scalars (SURVEY.md §7:
"bit-packed state codecs") — uint32 when the game's state fits in 31 bits,
uint64 otherwise. The narrow dtype matters on TPU: v5e has no native 64-bit
lanes, so uint64 sorts/compares are emulated at roughly half throughput (and
compile to much larger programs); every game declares its width and the
engine picks the narrowest dtype (games/base.py `state_dtype`).
"""

import jax
import jax.numpy as jnp
import numpy as np

# Padding sentinel for frontiers/tables: all-ones sorts after every real
# state, so sorted arrays keep their sentinel tail and searchsorted stays
# correct. Games guarantee the all-ones pattern is never a reachable state
# (state_bits <= 31 for uint32 / <= 63 for uint64).
SENTINEL64 = np.uint64(0xFFFF_FFFF_FFFF_FFFF)
SENTINEL32 = np.uint32(0xFFFF_FFFF)

U64_ONE = np.uint64(1)


def sentinel_for(dtype) -> np.number:
    """The all-ones sentinel of a state dtype (uint32 or uint64)."""
    dtype = np.dtype(dtype)
    if dtype == np.uint64:
        return SENTINEL64
    if dtype == np.uint32:
        return SENTINEL32
    raise TypeError(f"unsupported state dtype {dtype}")


def state_dtype_for(bits: int):
    """Narrowest supported state dtype for a game of `bits` state bits."""
    if bits <= 31:
        return np.uint32
    if bits <= 63:
        return np.uint64
    raise ValueError(f"state does not fit 63 bits: {bits}")


def u64(x) -> jnp.ndarray:
    """A uint64 jnp scalar/array from a Python int or array."""
    return jnp.asarray(x, dtype=jnp.uint64)


def popcount(x):
    """Population count of an unsigned integer array (any width)."""
    return jax.lax.population_count(x).astype(jnp.int32)


def msb_index(x):
    """Index of the most-significant set bit of x (x must be nonzero)."""
    x = jnp.asarray(x)
    width = np.dtype(x.dtype).itemsize * 8
    return (width - 1) - jax.lax.clz(x).astype(jnp.int32)


