"""GameSpec -> TensorGame lowering (the compiler half of gamedsl).

`compile_spec` turns a validated GameSpec into a generated TensorGame
subclass whose expand/primitive/canonicalize/level_of are the same
jit-ready batched JAX the hand-written games ship — built from
topology-derived bitboard masks instead of hand-derived ones:

* family "drop"  -> the guard-column encoding of games/connect4.py:
  column c occupies bits [c*(h+1), c*(h+1)+h], guard = column msb,
  whole-word masked down-smear decompose. The k-in-line fold's shift
  strides are DERIVED from the spec's adjacency directions — direction
  (dcol, drow) shifts the packed word by dcol*(h+1) + drow — which for
  the full compass {e, n, ne, se} reproduces connect4's hand-coded
  {h+1, 1, h+2, h} exactly.
* family "place" -> the two-plane encoding of games/tictactoe.py:
  X plane bits [0, m*n), O plane [m*n, 2*m*n), cell = r*n + c; the win
  predicate is a fold over topology-enumerated k-window masks, with an
  optional per-window forbid mask implementing the exact-k overline
  rule (win.exact) that the hand-written module cannot express.

Byte-parity with the hand-written modules is the correctness contract
(tests/test_gamedsl.py asserts sha256-equal solved tables for connect4
and tictactoe specs); misere and exact are the compiler-only axes that
make genuinely new games pure descriptions.

The compiled game's `cache_key` embeds the spec's canonical sha256, so
the module-level kernel caches (solve/engine.py) and the Precompiler
(solve/precompile.py) treat every rules change as a different program —
a mutated spec can never silently reuse a stale kernel. `spec_doc` /
`spec_hash` are also what db/writer.py persists into the manifest.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from gamesmanmpi_tpu.core.bitops import popcount
from gamesmanmpi_tpu.core.values import LOSE, TIE, UNDECIDED, WIN
from gamesmanmpi_tpu.games.base import TensorGame
from gamesmanmpi_tpu.gamedsl.spec import (
    DIRECTION_VECTORS,
    GameSpec,
    SpecError,
    load_spec,
    spec_problems,
)


def compile_spec(spec) -> TensorGame:
    """Lower a GameSpec (or a path to one) into a generated TensorGame.

    Refuses (SpecError) when the spec has error-severity problems; the
    message carries every finding so a CLI user sees the whole list.
    """
    if isinstance(spec, str):
        spec = load_spec(spec)
    if not isinstance(spec, GameSpec):
        spec = GameSpec.from_dict(spec)
    errors = [
        p for p in spec_problems(spec) if p["severity"] == "error"
    ]
    if errors:
        raise SpecError(
            f"spec {spec.name!r} is not compilable:\n" + "\n".join(
                f"  {p['code']}: {p['message']}" for p in errors
            )
        )
    cls = _DropGame if spec.family == "drop" else _PlaceGame
    return cls(spec)


class _CompiledGame(TensorGame):
    """Shared shell: identity, spec plumbing, and the cache-key contract."""

    uniform_level_jump = True  # both families add exactly one stone per move

    def __init__(self, spec: GameSpec):
        self.spec = spec
        self.name = spec.name
        self.spec_hash = spec.spec_hash
        self.spec_doc = spec.to_doc()
        self.sym = bool(spec.symmetry)
        self.num_levels = spec.cells + 1
        self.max_level_jump = 1
        self.state_bits = spec.state_bits

    @property
    def cache_key(self):
        # The sha256 of the canonical spec IS the rules' identity: two
        # compiled games trace identical kernels iff their canonical specs
        # match, so the hash (not the mutable file path or display name)
        # keys the jit caches and the Precompiler.
        return ("gamedsl", self.name, self.state_bits, self.spec_hash)


class _DropGame(_CompiledGame):
    """Gravity games (connect4 family): guard-column bitboard encoding."""

    def __init__(self, spec: GameSpec):
        super().__init__(spec)
        width, height = spec.width, spec.height
        self.width, self.height = width, height
        self.k = spec.k
        self.max_moves = width
        dt = self.state_dtype
        h1 = height + 1
        self._col_masks = np.array(
            [((1 << h1) - 1) << (c * h1) for c in range(width)], dtype=dt
        )
        self._top_bits = np.array(
            [1 << (c * h1 + height) for c in range(width)], dtype=dt
        )
        self._full_mask = dt(
            sum(((1 << height) - 1) << (c * h1) for c in range(width))
        )
        self._bottom_mask = dt(sum(1 << (c * h1) for c in range(width)))
        # Topology-derived line strides: direction (dcol, drow) moves one
        # step along a line, which in the packed word is a right-shift by
        # dcol*(h+1) + drow (columns are h+1 bits apart, cells 1 bit).
        # Sorted-deduped over the full compass this is {1, h, h+1, h+2} —
        # connect4's hand-coded set.
        self._dirs = tuple(
            dt(s) for s in sorted({
                DIRECTION_VECTORS[d][0] * h1 + DIRECTION_VECTORS[d][1]
                for d in spec.directions_with_windows()
            })
        )
        # Masks for the leak-killed whole-word down-smear (see
        # games/connect4.py._decompose for the derivation).
        self._smear_keep = {}
        i = 1
        while i <= height:
            self._smear_keep[i] = dt(
                sum(((1 << (h1 - i)) - 1) << (c * h1) for c in range(width))
            )
            i <<= 1
        if 1 not in self._smear_keep:  # height 1: smear loop never runs
            self._smear_keep[1] = dt(
                sum(((1 << (h1 - 1)) - 1) << (c * h1) for c in range(width))
            )

    def initial_state(self):
        return self._bottom_mask

    def _mirror(self, states):
        dt = self.state_dtype
        h1 = self.height + 1
        out = jnp.zeros(states.shape, dtype=dt)
        for c in range(self.width):
            col = (states >> dt(c * h1)) & self._col_masks[0]
            out = out | (col << dt((self.width - 1 - c) * h1))
        return out

    def canonicalize(self, states):
        if not self.sym:
            return states
        return jnp.minimum(states, self._mirror(states))

    def _decompose(self, states):
        dt = self.state_dtype
        smear = states
        i = 1
        while i <= self.height:
            smear = smear | ((smear >> dt(i)) & self._smear_keep[i])
            i <<= 1
        guards = smear ^ ((smear >> dt(1)) & self._smear_keep[1])
        filled = smear ^ guards
        current = states ^ guards
        opponent = filled ^ current
        return guards, filled, current, opponent

    def expand(self, states):
        guards, _, _, opponent = self._decompose(states)
        children = []
        masks = []
        for c in range(self.width):
            g = guards & self._col_masks[c]
            children.append(opponent | (guards + g))
            masks.append((guards & self._top_bits[c]) == 0)
        return jnp.stack(children, axis=-1), jnp.stack(masks, axis=-1)

    def _connected(self, stones):
        won = jnp.zeros(stones.shape, dtype=bool)
        for d in self._dirs:
            x = stones
            for i in range(1, self.k):
                x = x & (stones >> (d * self.state_dtype(i)))
            won = won | (x != 0)
        return won

    def primitive(self, states):
        _, filled, _, opponent = self._decompose(states)
        lined = self._connected(opponent)
        full = filled == self._full_mask
        # Normal play: the opponent completed a line, the mover has lost.
        # Misere: completing a line loses for its maker, so the mover WINS.
        lined_value = jnp.uint8(WIN if self.spec.misere else LOSE)
        return jnp.where(
            lined, lined_value,
            jnp.where(full, jnp.uint8(TIE), jnp.uint8(UNDECIDED)),
        )

    def level_of(self, states):
        _, filled, _, _ = self._decompose(states)
        return popcount(filled)

    def describe(self, state) -> str:
        s = int(state)
        h1 = self.height + 1
        cols = [(s >> (c * h1)) & ((1 << h1) - 1) for c in range(self.width)]
        heights = [cv.bit_length() - 1 for cv in cols]
        total = sum(heights)
        cur_char, opp_char = ("X", "O") if total % 2 == 0 else ("O", "X")
        rows = []
        for r in range(self.height - 1, -1, -1):
            row = ""
            for c in range(self.width):
                if r >= heights[c]:
                    row += "."
                elif (cols[c] >> r) & 1:
                    row += cur_char
                else:
                    row += opp_char
            rows.append(row)
        return "\n".join(rows)


class _PlaceGame(_CompiledGame):
    """Free-placement games (m,n,k family): two-bit-plane encoding."""

    def __init__(self, spec: GameSpec):
        super().__init__(spec)
        self.m, self.n = spec.height, spec.width
        self.cells = spec.cells
        self.k = spec.k
        self.max_moves = self.cells
        dt = self.state_dtype
        lines = []
        for cells, forbid in spec.line_windows():
            win_mask = 0
            for r, c in cells:
                win_mask |= 1 << (r * self.n + c)
            forbid_mask = 0
            for r, c in forbid:
                forbid_mask |= 1 << (r * self.n + c)
            lines.append((win_mask, forbid_mask))
        lines = sorted(set(lines))
        self._lines = np.array([w for w, _ in lines], dtype=dt)
        self._forbids = np.array([f for _, f in lines], dtype=dt)
        self._has_forbids = bool(spec.exact)
        self._plane_mask = dt((1 << self.cells) - 1)
        self._full = dt((1 << self.cells) - 1)
        self._cells_shift = dt(self.cells)
        self._bits = np.array([1 << i for i in range(self.cells)], dtype=dt)
        self._sym_perms = spec.symmetry_group() if self.sym else []

    def initial_state(self):
        return self.state_dtype(0)

    def canonicalize(self, states):
        if not self.sym:
            return states
        dt = self.state_dtype
        best = states
        for perm in self._sym_perms:
            out = jnp.zeros(states.shape, dtype=dt)
            for dst, src in enumerate(perm):
                bit = dt(1)
                x = (states >> dt(src)) & bit
                o = (states >> dt(self.cells + src)) & bit
                out = out | (x << dt(dst)) | (o << dt(self.cells + dst))
            best = jnp.minimum(best, out)
        return best

    def _planes(self, states):
        x = states & self._plane_mask
        o = (states >> self._cells_shift) & self._plane_mask
        return x, o

    def _x_to_move(self, states):
        x, o = self._planes(states)
        return popcount(x) == popcount(o)

    def expand(self, states):
        x, o = self._planes(states)
        occupied = x | o
        x_to_move = self._x_to_move(states)
        zero = self.state_dtype(0)
        shift = jnp.where(x_to_move, zero, self._cells_shift)
        children = []
        masks = []
        for i in range(self.cells):
            bit = self._bits[i]
            empty = (occupied & bit) == 0
            child = states | (bit << shift)
            children.append(child)
            masks.append(empty)
        return jnp.stack(children, axis=-1), jnp.stack(masks, axis=-1)

    def _lined(self, stones):
        won = jnp.zeros(stones.shape, dtype=bool)
        for i in range(self._lines.shape[0]):
            line = self._lines[i]
            hit = (stones & line) == line
            if self._has_forbids:
                # exact-k (overline) rule: the window only wins when
                # neither on-board extension cell belongs to the mover.
                hit = hit & ((stones & self._forbids[i]) == 0)
            won = won | hit
        return won

    def primitive(self, states):
        x, o = self._planes(states)
        last = jnp.where(self._x_to_move(states), o, x)
        lined = self._lined(last)
        full = (x | o) == self._full
        lined_value = jnp.uint8(WIN if self.spec.misere else LOSE)
        return jnp.where(
            lined, lined_value,
            jnp.where(full, jnp.uint8(TIE), jnp.uint8(UNDECIDED)),
        )

    def level_of(self, states):
        return popcount(states)

    def describe(self, state) -> str:
        s = int(state)
        rows = []
        for r in range(self.m):
            row = ""
            for c in range(self.n):
                i = r * self.n + c
                if (s >> i) & 1:
                    row += "X"
                elif (s >> (self.cells + i)) & 1:
                    row += "O"
                else:
                    row += "."
            rows.append(row)
        return "\n".join(rows)
