"""GameSpec: the declarative half of the game compiler (jax-free).

A GameSpec describes a two-player perfect-information game on a
width x height grid in four orthogonal pieces — board topology, a move
family, a win predicate, and symmetry generators — instead of bespoke
JAX (docs/GAMEDSL.md has the schema and a worked example):

    {"gamedsl": 1,
     "name": "gomoku_4x3x3",
     "board": {"width": 4, "height": 3},
     "moves": {"family": "place"},
     "win": {"kind": "k_in_line", "k": 3, "exact": true},
     "symmetry": ["mirror_h", "mirror_v"]}

This module deliberately imports no jax (stdlib only): the static
validator (tools/spec_lint.py) and the gamesman-lint checker
(analysis/gamespec.py) parse and reason about specs without tracing a
kernel or touching an accelerator. The lowering to a TensorGame lives
in gamesmanmpi_tpu.gamedsl.compiler.

Identity: `spec_hash` is the sha256 of the canonical JSON form (all
defaults materialized, keys sorted, aliases resolved). The compiler
folds it into the generated game's `cache_key` — so the kernel caches in
solve/engine.py and solve/precompile.py can never reuse a kernel traced
for different rules — and db/writer.py records it in the manifest, so
`check_db --same-as` fails loudly when a DB was exported from different
rules than the spec now on disk.

Directions are named on the compass; opposite names denote the same
undirected line family and collapse to a canonical representative
(w->e, s->n, sw->ne, nw->se). Vectors are (dcol, drow) with rows
growing north.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json

SCHEMA_VERSION = 1

#: canonical direction name -> (dcol, drow)
DIRECTION_VECTORS = {
    "e": (1, 0),
    "n": (0, 1),
    "ne": (1, 1),
    "se": (1, -1),
}

#: compass aliases: the opposite ray is the same undirected line family
DIRECTION_ALIASES = {"w": "e", "s": "n", "sw": "ne", "nw": "se"}

DEFAULT_DIRECTIONS = ("e", "n", "ne", "se")

MOVE_FAMILIES = ("drop", "place")

WIN_KINDS = ("k_in_line",)
#: schema-reserved predicate kinds (documented, not yet compilable)
RESERVED_WIN_KINDS = ("count", "capture")

#: generator name -> (square_only, coord map (r, c, m, n) -> (r', c'))
SYMMETRY_GENERATORS = {
    "mirror_h": (False, lambda r, c, m, n: (r, n - 1 - c)),
    "mirror_v": (False, lambda r, c, m, n: (m - 1 - r, c)),
    "rot180": (False, lambda r, c, m, n: (m - 1 - r, n - 1 - c)),
    "transpose": (True, lambda r, c, m, n: (c, r)),
    "anti_transpose": (True, lambda r, c, m, n: (n - 1 - c, m - 1 - r)),
    "rot90": (True, lambda r, c, m, n: (c, m - 1 - r)),
    "rot270": (True, lambda r, c, m, n: (n - 1 - c, r)),
}

#: the only generator compatible with gravity (drop games): column mirror
DROP_SYMMETRY_GENERATORS = ("mirror_h",)

#: fused value-table backward gate (ops/fused.py `_bwdt`, default
#: GAMESMAN_FUSED_TABLE_BITS): wider states still solve, but lose that path
FUSED_TABLE_BITS = 26


class SpecError(ValueError):
    """A GameSpec document is structurally or semantically invalid."""


def _require(cond: bool, msg: str):
    if not cond:
        raise SpecError(msg)


def canonical_direction(name: str) -> str:
    n = str(name).strip().lower()
    n = DIRECTION_ALIASES.get(n, n)
    _require(
        n in DIRECTION_VECTORS,
        f"unknown direction {name!r} (use {sorted(DIRECTION_VECTORS)} "
        f"or aliases {sorted(DIRECTION_ALIASES)})",
    )
    return n


@dataclasses.dataclass(frozen=True)
class GameSpec:
    """A parsed, canonicalized game description (see module docstring)."""

    name: str
    width: int
    height: int
    family: str = "place"
    k: int = 3
    misere: bool = False
    exact: bool = False
    directions: tuple = DEFAULT_DIRECTIONS
    symmetry: tuple = ()

    # ---------------------------------------------------------- construction

    @staticmethod
    def from_dict(doc: dict) -> "GameSpec":
        """Strict parse of a spec document; SpecError on any problem."""
        _require(isinstance(doc, dict), "spec document must be a JSON object")
        known = {"gamedsl", "name", "board", "moves", "win", "symmetry"}
        extra = sorted(set(doc) - known)
        _require(not extra, f"unknown top-level spec keys: {extra}")
        version = doc.get("gamedsl", SCHEMA_VERSION)
        _require(
            version == SCHEMA_VERSION,
            f"unsupported gamedsl schema version {version!r} "
            f"(this build reads version {SCHEMA_VERSION})",
        )
        name = doc.get("name")
        _require(
            isinstance(name, str) and name.strip() != "",
            "spec needs a non-empty string 'name'",
        )
        name = name.strip()

        board = doc.get("board")
        _require(
            isinstance(board, dict), "spec needs a 'board' object"
        )
        bad = sorted(set(board) - {"width", "height"})
        _require(not bad, f"unknown board keys: {bad}")
        width, height = board.get("width"), board.get("height")
        for label, v in (("width", width), ("height", height)):
            _require(
                isinstance(v, int) and not isinstance(v, bool) and v >= 1,
                f"board.{label} must be an integer >= 1, got {v!r}",
            )

        moves = doc.get("moves", {"family": "place"})
        _require(isinstance(moves, dict), "'moves' must be an object")
        bad = sorted(set(moves) - {"family"})
        _require(not bad, f"unknown moves keys: {bad}")
        family = str(moves.get("family", "place")).strip().lower()
        _require(
            family in MOVE_FAMILIES,
            f"unknown move family {family!r} (supported: {MOVE_FAMILIES})",
        )

        win = doc.get("win")
        _require(isinstance(win, dict), "spec needs a 'win' object")
        bad = sorted(set(win) - {"kind", "k", "misere", "exact", "directions"})
        _require(not bad, f"unknown win keys: {bad}")
        kind = str(win.get("kind", "k_in_line")).strip().lower()
        if kind in RESERVED_WIN_KINDS:
            raise SpecError(
                f"win kind {kind!r} is schema-reserved but not yet "
                f"compilable (supported: {WIN_KINDS})"
            )
        _require(
            kind in WIN_KINDS,
            f"unknown win kind {kind!r} (supported: {WIN_KINDS})",
        )
        k = win.get("k", 3)
        _require(
            isinstance(k, int) and not isinstance(k, bool) and k >= 1,
            f"win.k must be an integer >= 1, got {k!r}",
        )
        misere = win.get("misere", False)
        exact = win.get("exact", False)
        for label, v in (("misere", misere), ("exact", exact)):
            _require(
                isinstance(v, bool), f"win.{label} must be a boolean"
            )
        raw_dirs = win.get("directions", list(DEFAULT_DIRECTIONS))
        _require(
            isinstance(raw_dirs, (list, tuple)) and len(raw_dirs) > 0,
            "win.directions must be a non-empty list of direction names",
        )
        directions = tuple(
            sorted(set(canonical_direction(d) for d in raw_dirs))
        )

        symmetry = doc.get("symmetry", [])
        _require(
            isinstance(symmetry, (list, tuple)),
            "'symmetry' must be a list of generator names",
        )
        gens = []
        for g in symmetry:
            gname = str(g).strip().lower()
            _require(
                gname in SYMMETRY_GENERATORS,
                f"unknown symmetry generator {g!r} "
                f"(supported: {sorted(SYMMETRY_GENERATORS)})",
            )
            gens.append(gname)
        return GameSpec(
            name=name, width=width, height=height, family=family, k=k,
            misere=misere, exact=exact, directions=directions,
            symmetry=tuple(sorted(set(gens))),
        )

    # ------------------------------------------------------------- identity

    def to_doc(self) -> dict:
        """The canonical document: every default materialized, every alias
        resolved. Parsing the result reproduces this spec exactly."""
        return {
            "gamedsl": SCHEMA_VERSION,
            "name": self.name,
            "board": {"width": self.width, "height": self.height},
            "moves": {"family": self.family},
            "win": {
                "kind": "k_in_line",
                "k": self.k,
                "misere": self.misere,
                "exact": self.exact,
                "directions": list(self.directions),
            },
            "symmetry": list(self.symmetry),
        }

    def canonical_json(self) -> str:
        return json.dumps(self.to_doc(), sort_keys=True,
                          separators=(",", ":"))

    @property
    def spec_hash(self) -> str:
        """sha256 of the canonical JSON — the rules' identity. Flows into
        the compiled game's cache_key and the DB manifest."""
        return hashlib.sha256(self.canonical_json().encode()).hexdigest()

    # ------------------------------------------------------------- geometry

    @property
    def cells(self) -> int:
        return self.width * self.height

    @property
    def state_bits(self) -> int:
        """Packed width of the compiled encoding (see compiler docstrings):
        drop = guard-column encoding, place = two bit-planes."""
        if self.family == "drop":
            return (self.height + 1) * self.width
        return 2 * self.cells

    def line_windows(self):
        """All k-windows of the win predicate as ((cells...), (forbid...))
        pairs of (r, c) coordinates, deduplicated.

        `cells` are the k stones of a line; `forbid` are the (on-board)
        extension cells immediately before and after the window — empty
        unless exact=True, where a window only wins if neither extension
        belongs to the mover (the gomoku overline rule).
        """
        m, n = self.height, self.width
        out = set()
        for d in self.directions:
            dc, dr = DIRECTION_VECTORS[d]
            for r in range(m):
                for c in range(n):
                    rr, cc = r + dr * (self.k - 1), c + dc * (self.k - 1)
                    if not (0 <= rr < m and 0 <= cc < n):
                        continue
                    cells = tuple(
                        (r + dr * i, c + dc * i) for i in range(self.k)
                    )
                    forbid = ()
                    if self.exact:
                        forbid = tuple(
                            (fr, fc)
                            for fr, fc in ((r - dr, c - dc),
                                           (r + dr * self.k, c + dc * self.k))
                            if 0 <= fr < m and 0 <= fc < n
                        )
                    out.add((tuple(sorted(cells)), tuple(sorted(forbid))))
        return sorted(out)

    def directions_with_windows(self):
        """The subset of self.directions that admits at least one k-window."""
        m, n = self.height, self.width
        alive = []
        for d in self.directions:
            dc, dr = DIRECTION_VECTORS[d]
            span_c = abs(dc) * (self.k - 1)
            span_r = abs(dr) * (self.k - 1)
            if span_c < n and span_r < m:
                alive.append(d)
        return tuple(alive)

    def symmetry_group(self):
        """Closure of the symmetry generators as cell permutations
        (cell = r * width + c), identity excluded, sorted.

        Matches games/tictactoe.py's `_board_symmetries` convention:
        perm[dst] = src, i.e. applying a perm p to a board reads bit p[dst]
        into position dst.
        """
        m, n = self.height, self.width
        ident = tuple(range(self.cells))
        gens = set()
        for gname in self.symmetry:
            _, f = SYMMETRY_GENERATORS[gname]
            perm = [0] * self.cells
            for r in range(m):
                for c in range(n):
                    sr, sc = f(r, c, m, n)
                    perm[r * n + c] = sr * n + sc
            gens.add(tuple(perm))
        group = {ident} | gens
        while True:
            new = {
                tuple(a[b[i]] for i in range(self.cells))
                for a in group for b in group
            }
            if new <= group:
                break
            group |= new
        return sorted(group - {ident})


def load_spec(path: str) -> GameSpec:
    """Parse a GameSpec JSON file; SpecError on malformed content."""
    with open(path) as fh:
        text = fh.read()
    try:
        doc = json.loads(text)
    except ValueError as e:
        raise SpecError(f"{path}: not valid JSON: {e}") from e
    return GameSpec.from_dict(doc)


# --------------------------------------------------------------- validation


def _problem(severity: str, code: str, message: str) -> dict:
    return {"severity": severity, "code": code, "message": message}


def spec_problems(spec: GameSpec) -> list:
    """Semantic findings for a parsed spec: list of {severity, code,
    message} dicts, errors first.

    Errors make the spec uncompilable (compile_spec refuses); warnings
    flag legal-but-suspect constructs. Codes are stable (GS1xx) — see
    docs/GAMEDSL.md for the catalogue.
    """
    problems = []
    bits = spec.state_bits
    if bits > 63:
        problems.append(_problem(
            "error", "GS101",
            f"packed state needs {bits} bits (> 63): the board does not "
            f"fit the engine's uint64 encoding — shrink the board",
        ))
    elif bits > FUSED_TABLE_BITS:
        problems.append(_problem(
            "warning", "GS102",
            f"packed state needs {bits} bits (> {FUSED_TABLE_BITS}): "
            f"outside the fused value-table backward's default "
            f"GAMESMAN_FUSED_TABLE_BITS gate — fused solves will take "
            f"the provenance backward instead",
        ))
    if spec.exact and spec.family == "drop":
        problems.append(_problem(
            "error", "GS108",
            "win.exact (the overline rule) is only compilable for the "
            "'place' family — drop games have no exact-k lowering",
        ))
    alive = spec.directions_with_windows()
    if not alive:
        problems.append(_problem(
            "error", "GS103",
            f"win predicate is unreachable: no direction fits a "
            f"{spec.k}-in-a-line window on a "
            f"{spec.width}x{spec.height} board",
        ))
    else:
        for d in sorted(set(spec.directions) - set(alive)):
            problems.append(_problem(
                "warning", "GS104",
                f"direction {d!r} admits no {spec.k}-window on a "
                f"{spec.width}x{spec.height} board (dead direction)",
            ))
    if spec.k == 1:
        problems.append(_problem(
            "warning", "GS109",
            "win.k == 1: the first move always wins — the predicate is "
            "trivial",
        ))

    if spec.family == "drop":
        bad = sorted(set(spec.symmetry) - set(DROP_SYMMETRY_GENERATORS))
        if bad:
            problems.append(_problem(
                "error", "GS105",
                f"symmetry generators {bad} do not commute with gravity: "
                f"drop games support only {list(DROP_SYMMETRY_GENERATORS)}",
            ))
    else:
        bad = sorted(
            g for g in spec.symmetry
            if SYMMETRY_GENERATORS[g][0] and spec.width != spec.height
        )
        if bad:
            problems.append(_problem(
                "error", "GS105",
                f"symmetry generators {bad} need a square board "
                f"(got {spec.width}x{spec.height})",
            ))

    # Closure check: every element of the generated group must map the win
    # predicate's window set onto itself, or canonicalize would merge
    # positions with different values.
    if spec.symmetry and not any(
        p["code"] in ("GS105", "GS103") for p in problems
    ):
        windows = set(spec.line_windows())
        m, n = spec.height, spec.width
        for perm in spec.symmetry_group():
            # perm[dst] = src; the image of src is dst
            image = [0] * spec.cells
            for dst, src in enumerate(perm):
                image[src] = dst
            mapped = set()
            for cells, forbid in windows:
                mapped.add((
                    tuple(sorted(
                        divmod(image[r * n + c], n) for r, c in cells
                    )),
                    tuple(sorted(
                        divmod(image[r * n + c], n) for r, c in forbid
                    )),
                ))
            if mapped != windows:
                problems.append(_problem(
                    "error", "GS106",
                    f"symmetry closure broken: a group element maps the "
                    f"win-line set off itself (directions "
                    f"{list(spec.directions)} are not closed under "
                    f"generators {list(spec.symmetry)}) — canonicalize "
                    f"would merge positions with different values",
                ))
                break
    order = {"error": 0, "warning": 1}
    problems.sort(key=lambda p: (order[p["severity"]], p["code"]))
    return problems


def lint_file(path: str) -> list:
    """spec_problems for a file on disk; parse failures come back as a
    single GS001 error finding instead of an exception (lint-friendly)."""
    try:
        spec = load_spec(path)
    except OSError as e:
        return [_problem("error", "GS001", f"cannot read spec: {e}")]
    except SpecError as e:
        return [_problem("error", "GS001", f"invalid spec: {e}")]
    return spec_problems(spec)
