"""gamedsl: declarative game descriptions compiled to solver kernels.

Split in two so static tooling stays light:

* gamedsl.spec — jax-free: GameSpec parsing, canonical hashing,
  validation (spec_problems / lint_file). tools/spec_lint.py and the
  gamesman-lint checker import only this half.
* gamedsl.compiler — the JAX lowering (compile_spec -> TensorGame).

`compile_spec` is re-exported lazily: importing gamedsl does not pull
jax until a spec is actually compiled.
"""

from gamesmanmpi_tpu.gamedsl.spec import (  # noqa: F401
    GameSpec,
    SpecError,
    lint_file,
    load_spec,
    spec_problems,
)

__all__ = [
    "GameSpec",
    "SpecError",
    "compile_spec",
    "lint_file",
    "load_spec",
    "spec_problems",
]


def __getattr__(name):
    if name == "compile_spec":
        from gamesmanmpi_tpu.gamedsl.compiler import compile_spec
        return compile_spec
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
