"""GM1xx — JAX tracing safety.

Finds host impurity and recompile hazards inside functions that run
under a trace: anything wrapped by ``jax.jit`` / ``shard_map`` /
``pl.pallas_call``, anything returned by a builder passed to the
engine's ``get_kernel``/``schedule_kernel`` kernel cache (the project's
jit funnel — every solver kernel reaches XLA through it), and anything
those functions call in the same module (taint-propagated through
direct calls, callbacks like ``jax.lax.scan`` bodies, and lambdas).

Within a traced function its parameters are *traced values* (minus
declared static args); locals derived from them are traced too, except
through the static accessors (``.shape``/``.dtype``/``.ndim``/
``.size``, ``len()``) which produce Python values at trace time.

| id | finding |
|---|---|
| GM101 | host clock call (``time.time``/``perf_counter``/...) under trace |
| GM102 | Python/numpy RNG call under trace (untraced randomness) |
| GM103 | host sync of a traced value (``int()``/``float()``/``bool()``/``.item()``/``.tolist()``) |
| GM104 | Python control flow on a traced value (``if``/``while``/``assert``/iteration) |
| GM105 | ``np.*`` host call applied to a traced value |
| GM106 | static arg with a non-hashable (list/dict/set) default — recompile/TypeError hazard |

The analysis is intra-module and name-based: it never imports the code
under test, so it is safe to run on kernel code whose import would grab
an accelerator.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from gamesmanmpi_tpu.analysis.diagnostics import Diagnostic
from gamesmanmpi_tpu.analysis.project import (
    Project,
    SourceFile,
    attr_chain,
    call_name,
)

#: Attribute reads that yield *static* (trace-time Python) values.
SANITIZER_ATTRS = {
    "shape", "dtype", "ndim", "size", "itemsize", "nbytes", "aval",
    "sharding", "weak_type",
}

#: builtins that force a concrete value out of a tracer.
HOST_CASTS = {"int", "float", "bool", "complex"}
HOST_SYNC_METHODS = {"item", "tolist", "__index__"}

CLOCK_CALLS = {
    "time.time", "time.perf_counter", "time.monotonic",
    "time.process_time", "time.time_ns", "time.sleep",
    "datetime.datetime.now", "datetime.datetime.utcnow",
}

#: Wrappers whose first argument runs under a trace.
_JIT_NAMES = {"jit"}
_TRACE_WRAPPERS = {"shard_map", "pallas_call", "checkpoint", "remat",
                   "vmap", "pmap", "grad"}
#: The project's kernel-cache funnel: builder(game) returns the function
#: that gets jitted (solve/engine.get_kernel / schedule_kernel).
_BUILDER_FUNNELS = {"get_kernel", "schedule_kernel"}

#: Host-callback funnels: a function passed into these from traced code
#: runs on the HOST with concrete numpy arrays, not tracers — its numpy
#: calls, branches and host syncs are the whole point (the fused dedup's
#: np.unique callback, compat/shim's scalar-game lifts). Without this
#: exemption the callback rule below would re-enqueue those bodies as
#: traced and flag every np.* call in them (GM105 false positives on the
#: ISSUE 14 fused kernels).
_HOST_CALLBACK_FUNNELS = {"pure_callback", "io_callback", "debug_callback"}

#: Per-module cap on (function, taint-set) walks — a loop breaker, set
#: far above what any real module needs.
_MAX_WALKS = 4000


def _last(name: str) -> str:
    return name.rsplit(".", 1)[-1]


class _Scopes(ast.NodeVisitor):
    """function node -> {local def name: node}, plus parent links for
    lexical resolution and a module-level table."""

    def __init__(self, tree: ast.AST):
        self.locals: Dict[ast.AST, Dict[str, ast.AST]] = {tree: {}}
        self.parent: Dict[ast.AST, ast.AST] = {}
        self._stack: List[ast.AST] = [tree]
        self.visit(tree)

    def _handle_def(self, node):
        self.locals[self._stack[-1]][node.name] = node
        self.parent[node] = self._stack[-1]
        self.locals[node] = {}
        self._stack.append(node)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _handle_def
    visit_AsyncFunctionDef = _handle_def

    def visit_ClassDef(self, node):
        # Methods resolve through the class body scope; treat the class
        # as a scope node so nested helpers stay findable.
        self.parent[node] = self._stack[-1]
        self.locals[node] = {}
        self._stack.append(node)
        self.generic_visit(node)
        self._stack.pop()

    def resolve(self, scope: ast.AST, name: str) -> Optional[ast.AST]:
        node: Optional[ast.AST] = scope
        while node is not None:
            fn = self.locals.get(node, {}).get(name)
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return fn
            node = self.parent.get(node)
        return None


def _numpy_aliases(tree: ast.AST) -> Tuple[Set[str], Set[str]]:
    """(numpy module aliases, python-random module aliases)."""
    np_alias, rng_alias = set(), set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy":
                    np_alias.add(a.asname or "numpy")
                elif a.name == "random":
                    rng_alias.add(a.asname or "random")
                elif a.name == "numpy.random":
                    rng_alias.add(a.asname or "numpy")
    return np_alias, rng_alias


def _param_names(fn) -> List[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


def _static_params(fn, keywords) -> Set[str]:
    """Params excluded from tracing by static_argnums/static_argnames."""
    a = fn.args
    positional = [p.arg for p in a.posonlyargs + a.args]
    out: Set[str] = set()
    for kw in keywords:
        if kw.arg == "static_argnums":
            for v in _const_list(kw.value):
                if isinstance(v, int) and 0 <= v < len(positional):
                    out.add(positional[v])
        elif kw.arg == "static_argnames":
            for v in _const_list(kw.value):
                if isinstance(v, str):
                    out.add(v)
    return out


def _const_list(node) -> list:
    if isinstance(node, ast.Constant):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [
            e.value for e in node.elts if isinstance(e, ast.Constant)
        ]
    return []


def _mutable_default_params(fn) -> Dict[str, int]:
    """{param name: default's line} for list/dict/set-literal defaults."""
    a = fn.args
    out: Dict[str, int] = {}
    pos = a.posonlyargs + a.args
    for p, d in zip(pos[len(pos) - len(a.defaults):], a.defaults):
        if isinstance(d, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                          ast.DictComp, ast.SetComp)):
            out[p.arg] = d.lineno
    for p, d in zip(a.kwonlyargs, a.kw_defaults):
        if d is not None and isinstance(
            d, (ast.List, ast.Dict, ast.Set)
        ):
            out[p.arg] = d.lineno
    return out


class _ModuleChecker:
    def __init__(self, src: SourceFile):
        self.src = src
        self.tree = src.tree
        self.scopes = _Scopes(self.tree)
        self.np_aliases, self.rng_aliases = _numpy_aliases(self.tree)
        self.diags: List[Diagnostic] = []
        self._seen_diag: Set[Tuple[str, int, str]] = set()
        self._queue: List[Tuple[ast.AST, FrozenSet[str]]] = []
        self._visited: Set[Tuple[int, FrozenSet[str]]] = set()
        self._walks = 0

    # ------------------------------------------------------------- reporting

    def report(self, id_: str, node: ast.AST, msg: str) -> None:
        key = (id_, node.lineno, msg)
        if key not in self._seen_diag:
            self._seen_diag.add(key)
            self.diags.append(
                Diagnostic(self.src.rel, node.lineno, id_, msg)
            )

    # ---------------------------------------------------------------- roots

    def find_roots(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._roots_from_decorators(node)
            elif isinstance(node, ast.Call):
                self._roots_from_call(node)

    def _jit_wrapper_kind(self, func_expr) -> Optional[str]:
        """'jit'/'wrapper' when ``func_expr`` is a tracing wrapper
        (possibly through functools.partial(jax.jit, ...))."""
        chain = attr_chain(func_expr)
        if chain:
            last = chain[-1]
            if last in _JIT_NAMES:
                return "jit"
            if last in _TRACE_WRAPPERS:
                return "wrapper"
        if isinstance(func_expr, ast.Call):
            inner = call_name(func_expr)
            if _last(inner) == "partial" and func_expr.args:
                return self._jit_wrapper_kind(func_expr.args[0])
        return None

    def _roots_from_decorators(self, fn) -> None:
        for dec in fn.decorator_list:
            if isinstance(dec, ast.Call):
                kind = self._jit_wrapper_kind(dec.func)
                if kind is None and self._jit_wrapper_kind(dec):
                    # @partial(jax.jit, static_argnums=...) arrives here
                    # as a Call whose func is partial.
                    kind = "jit"
                keywords = dec.keywords
            else:
                kind = self._jit_wrapper_kind(dec)
                keywords = []
            if kind is not None:
                self._enqueue_root(fn, keywords)

    def _roots_from_call(self, call: ast.Call) -> None:
        name = _last(call_name(call))
        scope = self._enclosing_scope(call)
        if name in _JIT_NAMES or name in _TRACE_WRAPPERS:
            if call.args:
                fn = self._resolve_arg(scope, call.args[0])
                if fn is not None:
                    self._enqueue_root(fn, call.keywords)
        elif name in _BUILDER_FUNNELS:
            builder_expr = None
            if len(call.args) >= 4:
                builder_expr = call.args[3]
            for kw in call.keywords:
                if kw.arg == "builder":
                    builder_expr = kw.value
            builder = self._resolve_arg(scope, builder_expr)
            if builder is not None:
                # The builder itself runs on host with static args (the
                # game); every function defined inside it is the traced
                # kernel it returns.
                for sub in self.scopes.locals.get(builder, {}).values():
                    if isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        self._enqueue_root(sub, [])

    def _resolve_arg(self, scope, expr) -> Optional[ast.AST]:
        if isinstance(expr, ast.Name):
            return self.scopes.resolve(scope, expr.id)
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
        ):
            # Method builders (`self._fwdp_builder` handed to get_kernel):
            # one name means one method across the module's classes — the
            # repo convention the whole lock checker also leans on.
            for owner, members in self.scopes.locals.items():
                if isinstance(owner, ast.ClassDef):
                    fn = members.get(expr.attr)
                    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        return fn
        return None

    def _enclosing_scope(self, node) -> ast.AST:
        # Cheap but exact: find the innermost function whose span holds
        # the node's position.
        best = self.tree
        for fn, _ in self.scopes.locals.items():
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if (
                    fn.lineno <= node.lineno
                    and node.lineno <= (fn.end_lineno or fn.lineno)
                ):
                    if (
                        best is self.tree
                        or fn.lineno >= best.lineno
                    ):
                        best = fn
        return best

    def _enqueue_root(self, fn, jit_keywords) -> None:
        static = _static_params(fn, jit_keywords)
        mutable = _mutable_default_params(fn)
        for p in sorted(static & set(mutable)):
            self.report(
                "GM106", fn,
                f"static arg {p!r} of {fn.name!r} has a non-hashable "
                "(list/dict/set) default — every call re-hashes it and "
                "fails or recompiles",
            )
        tainted = frozenset(set(_param_names(fn)) - static)
        self.enqueue(fn, tainted)

    # -------------------------------------------------------------- worklist

    def enqueue(self, fn, tainted: FrozenSet[str]) -> None:
        key = (id(fn), tainted)
        if key not in self._visited and self._walks < _MAX_WALKS:
            self._visited.add(key)
            self._walks += 1
            self._queue.append((fn, tainted))

    def run(self) -> List[Diagnostic]:
        if self.tree is None:
            return []
        self.find_roots()
        while self._queue:
            fn, tainted = self._queue.pop()
            _TaintWalker(self, fn, set(tainted)).walk()
        return self.diags


class _TaintWalker:
    """One traced function body: propagate taint, report impurity."""

    def __init__(self, mod: _ModuleChecker, fn, tainted: Set[str]):
        self.mod = mod
        self.fn = fn
        self.env = tainted

    def walk(self) -> None:
        for stmt in self.fn.body:
            self.stmt(stmt)

    # ------------------------------------------------------------ statements

    def stmt(self, node) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # walked when reached via a call/callback
        if isinstance(node, ast.Assign):
            t = self.tainted(node.value)
            for target in node.targets:
                self.assign(target, t)
        elif isinstance(node, ast.AnnAssign):
            if node.value is not None:
                self.assign(node.target, self.tainted(node.value))
        elif isinstance(node, ast.AugAssign):
            t = self.tainted(node.value) or self.tainted(node.target)
            self.assign(node.target, t)
        elif isinstance(node, (ast.If, ast.While)):
            if self.tainted(node.test):
                self.mod.report(
                    "GM104", node,
                    "Python branch on a traced value — under jit this "
                    "raises TracerBoolConversionError or bakes in one "
                    "path; use jnp.where/lax.cond",
                )
            for s in node.body + node.orelse:
                self.stmt(s)
        elif isinstance(node, ast.Assert):
            if self.tainted(node.test):
                self.mod.report(
                    "GM104", node,
                    "assert on a traced value — hosts a bool() sync; "
                    "use checkify or debug_assert",
                )
        elif isinstance(node, ast.For):
            if self.tainted(node.iter):
                self.mod.report(
                    "GM104", node,
                    "Python iteration over a traced value — unrolls or "
                    "fails under jit; use lax.scan/fori_loop",
                )
                self.assign(node.target, True)
            else:
                self.tainted(node.iter)
                self.assign(node.target, False)
            for s in node.body + node.orelse:
                self.stmt(s)
        elif isinstance(node, ast.With):
            for item in node.items:
                self.tainted(item.context_expr)
                if item.optional_vars is not None:
                    self.assign(item.optional_vars, False)
            for s in node.body:
                self.stmt(s)
        elif isinstance(node, ast.Try):
            for s in (
                node.body
                + [h_s for h in node.handlers for h_s in h.body]
                + node.orelse
                + node.finalbody
            ):
                self.stmt(s)
        elif isinstance(node, (ast.Return, ast.Expr)):
            if node.value is not None:
                self.tainted(node.value)
        elif isinstance(node, (ast.Raise,)):
            if node.exc is not None:
                self.tainted(node.exc)
        elif isinstance(node, ast.Delete):
            pass
        else:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    self.tainted(child)
                elif isinstance(child, ast.stmt):
                    self.stmt(child)

    def assign(self, target, tainted: bool) -> None:
        if isinstance(target, ast.Name):
            if tainted:
                self.env.add(target.id)
            else:
                self.env.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self.assign(e, tainted)
        elif isinstance(target, ast.Starred):
            self.assign(target.value, tainted)
        elif isinstance(target, (ast.Subscript, ast.Attribute)):
            self.tainted(target.value)

    # ----------------------------------------------------------- expressions

    def tainted(self, node) -> bool:
        """Evaluate an expression: report findings, return taintedness."""
        if node is None or isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Name):
            return node.id in self.env
        if isinstance(node, ast.Attribute):
            base = self.tainted(node.value)
            if node.attr in SANITIZER_ATTRS:
                return False
            return base
        if isinstance(node, ast.Call):
            return self.call(node)
        if isinstance(node, ast.Subscript):
            return self.tainted(node.value) or self.tainted(node.slice)
        if isinstance(node, (ast.BinOp,)):
            left = self.tainted(node.left)
            return self.tainted(node.right) or left
        if isinstance(node, ast.UnaryOp):
            return self.tainted(node.operand)
        if isinstance(node, ast.BoolOp):
            return any([self.tainted(v) for v in node.values])
        if isinstance(node, ast.Compare):
            t = self.tainted(node.left)
            for c in node.comparators:
                t = self.tainted(c) or t
            return t
        if isinstance(node, ast.IfExp):
            if self.tainted(node.test):
                self.mod.report(
                    "GM104", node,
                    "conditional expression on a traced value — use "
                    "jnp.where/lax.select",
                )
            a = self.tainted(node.body)
            return self.tainted(node.orelse) or a
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any([self.tainted(e) for e in node.elts])
        if isinstance(node, ast.Dict):
            t = any([self.tainted(k) for k in node.keys if k is not None])
            return any([self.tainted(v) for v in node.values]) or t
        if isinstance(node, ast.Starred):
            return self.tainted(node.value)
        if isinstance(node, ast.Slice):
            return any(
                self.tainted(p)
                for p in (node.lower, node.upper, node.step)
                if p is not None
            )
        if isinstance(node, ast.JoinedStr):
            for v in node.values:
                if isinstance(v, ast.FormattedValue):
                    self.tainted(v.value)
            return False
        if isinstance(
            node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)
        ):
            return self.comprehension(node)
        if isinstance(node, ast.Lambda):
            return False  # walked where it's passed as a callback
        if isinstance(node, ast.NamedExpr):
            t = self.tainted(node.value)
            self.assign(node.target, t)
            return t
        if isinstance(node, ast.Await):
            return self.tainted(node.value)
        return False

    def comprehension(self, node) -> bool:
        child = _TaintWalker(self.mod, self.fn, set(self.env))
        t = False
        for gen in node.generators:
            it = child.tainted(gen.iter)
            if it:
                self.mod.report(
                    "GM104", node,
                    "comprehension over a traced value — Python "
                    "iteration under jit",
                )
            child.assign(gen.target, it)
            t = t or it
            for cond in gen.ifs:
                child.tainted(cond)
        if isinstance(node, ast.DictComp):
            t = child.tainted(node.key) or t
            t = child.tainted(node.value) or t
        else:
            t = child.tainted(node.elt) or t
        return t

    # ----------------------------------------------------------------- calls

    def call(self, node: ast.Call) -> bool:
        name = call_name(node)
        last = _last(name)
        chain = attr_chain(node.func) or []
        arg_taints = [self.tainted(a) for a in node.args]
        kw_taints = {
            kw.arg: self.tainted(kw.value) for kw in node.keywords
        }
        any_tainted = any(arg_taints) or any(kw_taints.values())

        # --- impurity findings -------------------------------------------
        if name in CLOCK_CALLS or (
            chain[:1] == ["time"] and len(chain) == 2
        ):
            self.mod.report(
                "GM101", node,
                f"host clock call {name}() inside traced code — the "
                "value freezes at trace time (and differs per recompile)",
            )
            return False
        if chain and (
            chain[0] in self.mod.rng_aliases
            and (len(chain) == 2 or chain[1:2] == ["random"])
            or (chain[0] in self.mod.np_aliases and chain[1:2] == ["random"])
        ):
            self.mod.report(
                "GM102", node,
                f"untraced RNG call {name}() inside traced code — "
                "freezes at trace time; thread a jax.random key instead",
            )
            return False
        if last in HOST_CASTS and len(chain) == 1 and any_tainted:
            self.mod.report(
                "GM103", node,
                f"{last}() applied to a traced value — forces a host "
                "sync / ConcretizationTypeError under jit",
            )
            return False
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in HOST_SYNC_METHODS
            and self.tainted(node.func.value)
        ):
            self.mod.report(
                "GM103", node,
                f".{node.func.attr}() on a traced value — forces a "
                "host sync under jit",
            )
            return False
        if (
            chain
            and chain[0] in self.mod.np_aliases
            and len(chain) > 1
            and any_tainted
        ):
            self.mod.report(
                "GM105", node,
                f"numpy host call {name}() on a traced value — "
                "silently syncs (or fails) under jit; use jnp",
            )
            return True
        if last == "len" and len(chain) == 1:
            return False

        # --- propagation into local functions ----------------------------
        scope = self.fn
        is_funnel = last in _BUILDER_FUNNELS \
            or last in _HOST_CALLBACK_FUNNELS
        if isinstance(node.func, ast.Name):
            target = self.mod.scopes.resolve(scope, node.func.id)
            if target is not None:
                params = _param_names(target)
                tainted_params = set()
                offset = 1 if params[:1] == ["self"] else 0
                for i, t in enumerate(arg_taints):
                    if t and i + offset < len(params):
                        tainted_params.add(params[i + offset])
                for k, t in kw_taints.items():
                    if t and k in params:
                        tainted_params.add(k)
                self.mod.enqueue(target, frozenset(tainted_params))
        if not is_funnel:
            # Callback rule: a local function passed BY NAME into any
            # call inside traced code will be invoked with traced
            # operands (scan/while/cond bodies, custom combinators).
            for a in node.args:
                if isinstance(a, ast.Name) and a is not node.func:
                    cb = self.mod.scopes.resolve(scope, a.id)
                    if cb is not None:
                        self.mod.enqueue(
                            cb, frozenset(_param_names(cb))
                        )
                elif isinstance(a, ast.Lambda):
                    child = _TaintWalker(self.mod, self.fn, set(self.env))
                    for p in _param_names(a):
                        child.env.add(p)
                    child.tainted(a.body)

        # Taint of the call's result: conservative — tainted operands
        # (or a method on a tainted object) yield a tainted result.
        recv_tainted = isinstance(
            node.func, ast.Attribute
        ) and self.tainted(node.func.value)
        return any_tainted or recv_tainted


def check(project: Project) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    for src in project.files:
        if src.tree is not None:
            diags.extend(_ModuleChecker(src).run())
    return diags
