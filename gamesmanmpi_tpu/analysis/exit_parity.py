"""GM5xx (continued) — campaign exit-code parity.

``resilience/campaign.py``'s death-cause classifier, its
``CAMPAIGN_EXIT_CODES`` registry, and ``tools/run_campaign.py``'s
documented "Exit codes:" list are three views of ONE contract: which
process exit codes the campaign stack knows about. They drift the
classic way — someone adds a new ``*_EXIT_CODE`` constant (a new death
shape) and the classifier never learns it, so the death silently
classifies as ``crash`` and the campaign retries a failure it should
have degraded around; or the CLI docstring promises an exit code the
registry no longer produces.

| id | finding |
|---|---|
| GM506 | ``*_EXIT_CODE`` constant neither referenced by the campaign ``classify`` function nor registered in ``CAMPAIGN_EXIT_CODES`` — a death that silently classifies as ``crash`` |
| GM507 | a script's documented "Exit codes:" list disagrees with ``CAMPAIGN_EXIT_CODES`` (either direction) |

Anchors are structural, not path-based: the registry is the
module-level ``CAMPAIGN_EXIT_CODES`` dict literal (its module also
holds ``classify``); the documented list is any *script* module (one
with an ``if __name__ == "__main__"`` guard) whose docstring contains
an "Exit codes:" section. A project without the registry skips the
family entirely.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from gamesmanmpi_tpu.analysis.diagnostics import Diagnostic
from gamesmanmpi_tpu.analysis.project import Project, SourceFile

#: Numbers in an "Exit codes:" sentence look like "0 solved, 2 usage,
#: 75 campaign preempted": an integer followed by its one-word-or-more
#: meaning. The section runs to the docstring's next blank line.
_DOC_SECTION = re.compile(r"[Ee]xit codes?:(?P<body>.*?)(?:\n\s*\n|$)",
                          re.DOTALL)
_DOC_CODE = re.compile(r"(?<![\w.])(\d{1,3})\s+(?=[A-Za-z])")


def _exit_constants(
    project: Project,
) -> Dict[str, Tuple[int, str, int]]:
    """Every module-level ``NAME_EXIT_CODE = <int>`` in the project:
    ``{name: (value, rel_path, line)}`` (first definition wins)."""
    out: Dict[str, Tuple[int, str, int]] = {}
    for src in project.files:
        if src.tree is None:
            continue
        for node in src.tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id.endswith("_EXIT_CODE")
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, int)
                and not isinstance(node.value.value, bool)
            ):
                out.setdefault(
                    node.targets[0].id,
                    (node.value.value, src.rel, node.lineno),
                )
    return out


def _find_registry(project: Project):
    """The module-level ``CAMPAIGN_EXIT_CODES = {...}`` dict literal:
    -> (file, dict_node) or (None, None)."""
    for src in project.files:
        if src.tree is None:
            continue
        for node in src.tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "CAMPAIGN_EXIT_CODES"
                and isinstance(node.value, ast.Dict)
            ):
                return src, node.value
    return None, None


def _classify_refs(src: SourceFile) -> set:
    """``*_EXIT_CODE`` names referenced anywhere inside the registry
    module's ``classify`` function (method or plain def)."""
    refs: set = set()
    for node in ast.walk(src.tree):
        if isinstance(node, ast.FunctionDef) and node.name == "classify":
            for inner in ast.walk(node):
                if isinstance(inner, ast.Name) and inner.id.endswith(
                    "_EXIT_CODE"
                ):
                    refs.add(inner.id)
    return refs


def _is_script(src: SourceFile) -> bool:
    """Does the module run as a process (``if __name__ == "__main__"``
    at module level)? Process exit codes are a script contract; library
    docstrings describing return values must not trip GM507."""
    for node in src.tree.body:
        if isinstance(node, ast.If):
            test = ast.dump(node.test)
            if "__name__" in test and "__main__" in test:
                return True
    return False


def _documented_codes(src: SourceFile) -> Optional[List[int]]:
    doc = ast.get_docstring(src.tree, clean=False)
    if not doc:
        return None
    codes: List[int] = []
    found = False
    for m in _DOC_SECTION.finditer(doc):
        found = True
        for c in _DOC_CODE.findall(m.group("body")):
            codes.append(int(c))
    return sorted(set(codes)) if found else None


def check(project: Project) -> List[Diagnostic]:
    reg_src, reg_dict = _find_registry(project)
    if reg_src is None:
        return []  # project without a campaign exit-code registry
    diags: List[Diagnostic] = []
    constants = _exit_constants(project)
    classify_refs = _classify_refs(reg_src)
    reg_names: set = set()
    reg_values: set = set()
    for key in reg_dict.keys:
        if isinstance(key, ast.Name):
            reg_names.add(key.id)
            if key.id in constants:
                reg_values.add(constants[key.id][0])
        elif isinstance(key, ast.Constant) and isinstance(
            key.value, int
        ):
            reg_values.add(int(key.value))
    # GM506: a defined exit-code constant no campaign layer knows.
    for name, (value, rel, line) in sorted(constants.items()):
        if name in classify_refs or name in reg_names:
            continue
        if value in reg_values:
            continue  # registered by literal value
        diags.append(Diagnostic(
            rel, line, "GM506",
            f"{name} (= {value}) is neither handled by the campaign "
            "death classifier nor registered in CAMPAIGN_EXIT_CODES — "
            "an attempt exiting with it silently classifies as "
            "'crash'",
        ))
    # GM507: documented "Exit codes:" lists vs the registry, two-way.
    for src in project.files:
        if src.tree is None or not _is_script(src):
            continue
        documented = _documented_codes(src)
        if documented is None:
            continue
        for code in documented:
            if code not in reg_values:
                diags.append(Diagnostic(
                    src.rel, 1, "GM507",
                    f"documented exit code {code} is not in "
                    "CAMPAIGN_EXIT_CODES — the doc promises a code "
                    "the campaign never produces (or the registry "
                    "forgot it)",
                ))
        for value in sorted(reg_values):
            if value not in documented:
                diags.append(Diagnostic(
                    reg_src.rel, reg_dict.lineno, "GM507",
                    f"CAMPAIGN_EXIT_CODES value {value} is missing "
                    f"from {src.rel}'s documented \"Exit codes:\" "
                    "list",
                ))
    return diags
