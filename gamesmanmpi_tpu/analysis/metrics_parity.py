"""GM4xx — metrics registry parity.

Every series the package emits (``reg.counter("...")`` /
``.gauge("...")`` / ``.histogram("...")``) must follow the naming rules
and be documented in docs/OBSERVABILITY.md — a metric an operator
cannot look up is a metric nobody alerts on.

| id | finding |
|---|---|
| GM401 | metric name breaks the naming rules (``gamesman_`` prefix, lowercase snake, counters end ``_total``, gauges/histograms don't) |
| GM402 | emitted metric not documented in docs/OBSERVABILITY.md |
| GM403 | metric name not statically resolvable (not a literal or module constant) — the registry can't be audited |

Definition sites (the ``obs/registry.py`` methods themselves) are
skipped; names may be string literals or module-level constants
(``SPAN_SECONDS``).
"""

from __future__ import annotations

import ast
import re
from typing import List

from gamesmanmpi_tpu.analysis.diagnostics import Diagnostic
from gamesmanmpi_tpu.analysis.project import (
    OBSERVABILITY_MD,
    Project,
    const_str,
    module_string_consts,
)

_EMIT_METHODS = {"counter", "gauge", "histogram"}
_NAME_RE = re.compile(r"^gamesman_[a-z][a-z0-9_]*$")


def check(project: Project) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    doc = project.observability_md
    # Exact-token matching: 'gamesman_retries' must not count as
    # documented because 'gamesman_retries_total' appears in the doc.
    documented = set(re.findall(r"gamesman_[a-z][a-z0-9_]*", doc))
    for src in project.files:
        if src.tree is None or src.rel.endswith("obs/registry.py"):
            continue
        consts = module_string_consts(src.tree)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            if not isinstance(node.func, ast.Attribute):
                continue
            kind = node.func.attr
            if kind not in _EMIT_METHODS or not node.args:
                continue
            # Registry emission only: the receiver is a registry (reg /
            # self.registry / default_registry()); a positional-string
            # first arg is the series name either way.
            name = const_str(node.args[0], consts)
            if name is None:
                diags.append(Diagnostic(
                    src.rel, node.lineno, "GM403",
                    f".{kind}() metric name is not statically "
                    "resolvable — use a literal or a module-level "
                    "string constant so the registry stays auditable",
                ))
                continue
            if not _NAME_RE.match(name):
                diags.append(Diagnostic(
                    src.rel, node.lineno, "GM401",
                    f"metric {name!r} breaks naming rules: "
                    "gamesman_ prefix, lowercase snake_case",
                ))
                continue  # a misnamed series can't be documented per-token
            if kind == "counter" and not name.endswith("_total"):
                diags.append(Diagnostic(
                    src.rel, node.lineno, "GM401",
                    f"counter {name!r} must end in _total "
                    "(Prometheus counter convention)",
                ))
            elif kind != "counter" and name.endswith("_total"):
                diags.append(Diagnostic(
                    src.rel, node.lineno, "GM401",
                    f"{kind} {name!r} must not end in _total — that "
                    "suffix promises a counter",
                ))
            if name not in documented:
                diags.append(Diagnostic(
                    src.rel, node.lineno, "GM402",
                    f"metric {name!r} is emitted here but not "
                    f"documented in {OBSERVABILITY_MD}",
                ))
    return diags
