"""gamesman-lint command line (also ``python -m tools.lint``).

Exit status: 0 clean (no new findings), 1 new findings, 2 bad usage.
The default baseline is ``<root>/lint_baseline.json``; a missing file
is an empty baseline, which is the steady state this repo holds.
"""

from __future__ import annotations

import argparse
import json
import sys

from gamesmanmpi_tpu.analysis.diagnostics import write_baseline
from gamesmanmpi_tpu.analysis.runner import run_project

DEFAULT_BASELINE = "lint_baseline.json"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="gamesman-lint",
        description="Project-aware static analysis for gamesmanmpi_tpu "
                    "(checker catalogue: docs/ANALYSIS.md).",
    )
    ap.add_argument(
        "paths", nargs="*",
        help="files/directories to lint (default: every top-level "
             "package plus tools/)",
    )
    ap.add_argument(
        "--root", default=".",
        help="project root for discovery, registry docs, and "
             "path-relative reporting (default: cwd)",
    )
    ap.add_argument(
        "--baseline", default=None, metavar="FILE",
        help=f"baseline file (default: <root>/{DEFAULT_BASELINE})",
    )
    ap.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline: every finding is new",
    )
    ap.add_argument(
        "--update-baseline", action="store_true",
        help="accept all current findings into the baseline file and "
             "exit 0",
    )
    ap.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="diagnostic output format",
    )
    ap.add_argument(
        "--show-all", action="store_true",
        help="also list baselined and suppressed findings",
    )
    args = ap.parse_args(argv)

    import pathlib

    default_baseline = str(pathlib.Path(args.root) / DEFAULT_BASELINE)
    baseline_path = args.baseline
    if baseline_path is None and not args.no_baseline:
        baseline_path = default_baseline
    if args.no_baseline:
        baseline_path = None

    if args.update_baseline and args.paths:
        # A partial run sees a subset of findings; writing it back would
        # silently drop every accepted entry outside the scanned paths.
        print(
            "gamesman-lint: error: --update-baseline requires a "
            "whole-project run (no explicit paths)",
            file=sys.stderr,
        )
        return 2

    try:
        result = run_project(args.root, paths=args.paths or None,
                             baseline_path=baseline_path)
    except (FileNotFoundError, ValueError) as e:
        # Missing/outside-root targets and malformed baseline files are
        # usage errors, not tracebacks.
        print(f"gamesman-lint: error: {e}", file=sys.stderr)
        return 2

    if args.update_baseline:
        # Always anchored at --root (or the explicit --baseline), never
        # the process cwd — '--no-baseline --update-baseline' must not
        # scatter baseline files wherever the command happened to run.
        target = args.baseline or default_baseline
        write_baseline(target, result.fingerprints)
        print(
            f"wrote {len(result.fingerprints)} finding(s) to {target}",
            file=sys.stderr,
        )
        return 0

    if args.format == "json":
        payload = {
            "new": [d.to_json() for d in result.new],
            "baselined": [d.to_json() for d in result.baselined],
            "suppressed": [d.to_json() for d in result.suppressed],
            "ok": result.ok,
        }
        print(json.dumps(payload, indent=2))
    else:
        for d in result.new:
            print(d.format())
        if args.show_all:
            for d in result.baselined:
                print(f"{d.format()}  [baselined]")
            for d in result.suppressed:
                print(f"{d.format()}  [suppressed]")
        summary = (
            f"{len(result.new)} new, {len(result.baselined)} baselined, "
            f"{len(result.suppressed)} suppressed finding(s) over "
            f"{len(result.project.files)} file(s)"
        )
        print(summary, file=sys.stderr)
    return 0 if result.ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
