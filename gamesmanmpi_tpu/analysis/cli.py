"""gamesman-lint command line (also ``python -m tools.lint``).

Exit status: 0 clean (no new findings), 1 new findings, 2 bad usage.
The default baseline is ``<root>/lint_baseline.json``; a missing file
is an empty baseline, which is the steady state this repo holds.
"""

from __future__ import annotations

import argparse
import json
import sys

from gamesmanmpi_tpu.analysis.diagnostics import write_baseline
from gamesmanmpi_tpu.analysis.runner import run_project

DEFAULT_BASELINE = "lint_baseline.json"


def to_sarif(result) -> dict:
    """Minimal SARIF 2.1.0 log for CI annotation. Only *new* findings
    become results — baselined/suppressed dispositions stay a
    gamesman-lint concept; exit-code semantics are unchanged."""
    rule_ids = sorted({d.id for d in result.new})
    return {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
            "master/Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "gamesman-lint",
                "informationUri": "docs/ANALYSIS.md",
                "rules": [{"id": rid} for rid in rule_ids],
            }},
            "results": [{
                "ruleId": d.id,
                "level": "error",
                "message": {"text": d.message},
                "locations": [{
                    "physicalLocation": {
                        "artifactLocation": {"uri": d.path},
                        "region": {"startLine": d.line},
                    },
                }],
            } for d in result.new],
        }],
    }


def _changed_lint_targets(root: str, base_ref: str) -> list:
    """Root-relative paths of lint-scope files changed vs ``base_ref``
    (committed diffs + working tree + untracked). Raises RuntimeError
    on git failures — surfaced as usage errors, never tracebacks."""
    import pathlib
    import subprocess

    from gamesmanmpi_tpu.analysis.project import default_scope_rels

    def git(*argv):
        try:
            proc = subprocess.run(
                ["git", "-C", str(root), *argv],
                capture_output=True, text=True, timeout=60,
            )
        except (OSError, subprocess.TimeoutExpired) as e:
            raise RuntimeError(f"git {' '.join(argv)}: {e}") from e
        if proc.returncode != 0:
            raise RuntimeError(
                f"git {' '.join(argv)} failed: "
                f"{proc.stderr.strip() or proc.stdout.strip()}"
            )
        return [line.strip() for line in proc.stdout.splitlines()
                if line.strip()]

    # --relative: `git diff --name-only` prints TOPLEVEL-relative paths
    # by default; when --root is a subdirectory of a larger checkout
    # they would never match the root-relative scope below (ls-files
    # --others is cwd-relative already).
    changed = set(git("diff", "--name-only", "--relative", base_ref,
                      "--"))
    changed |= set(git("ls-files", "--others", "--exclude-standard"))
    scope = default_scope_rels(root)
    root_path = pathlib.Path(root).resolve()
    return sorted(
        rel for rel in changed
        if rel in scope and (root_path / rel).exists()
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="gamesman-lint",
        description="Project-aware static analysis for gamesmanmpi_tpu "
                    "(checker catalogue: docs/ANALYSIS.md).",
    )
    ap.add_argument(
        "paths", nargs="*",
        help="files/directories to lint (default: every top-level "
             "package plus tools/)",
    )
    ap.add_argument(
        "--root", default=".",
        help="project root for discovery, registry docs, and "
             "path-relative reporting (default: cwd)",
    )
    ap.add_argument(
        "--baseline", default=None, metavar="FILE",
        help=f"baseline file (default: <root>/{DEFAULT_BASELINE})",
    )
    ap.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline: every finding is new",
    )
    ap.add_argument(
        "--update-baseline", action="store_true",
        help="accept all current findings into the baseline file and "
             "exit 0",
    )
    ap.add_argument(
        "--changed-only", action="store_true",
        help="lint only files changed vs --base-ref (git diff + "
             "untracked), for fast local runs; baseline and exit-code "
             "semantics are unchanged",
    )
    ap.add_argument(
        "--base-ref", default="HEAD", metavar="REF",
        help="base ref for --changed-only (default: HEAD)",
    )
    ap.add_argument(
        "--format", choices=("text", "json", "sarif"), default="text",
        help="diagnostic output format",
    )
    ap.add_argument(
        "--show-all", action="store_true",
        help="also list baselined and suppressed findings",
    )
    args = ap.parse_args(argv)

    import pathlib

    default_baseline = str(pathlib.Path(args.root) / DEFAULT_BASELINE)
    baseline_path = args.baseline
    if baseline_path is None and not args.no_baseline:
        baseline_path = default_baseline
    if args.no_baseline:
        baseline_path = None

    if args.update_baseline and (args.paths or args.changed_only):
        # A partial run sees a subset of findings; writing it back would
        # silently drop every accepted entry outside the scanned paths.
        print(
            "gamesman-lint: error: --update-baseline requires a "
            "whole-project run (no explicit paths / --changed-only)",
            file=sys.stderr,
        )
        return 2

    paths = args.paths or None
    restrict = None
    if args.changed_only:
        if args.paths:
            print(
                "gamesman-lint: error: --changed-only and explicit "
                "paths are mutually exclusive",
                file=sys.stderr,
            )
            return 2
        try:
            restrict = _changed_lint_targets(args.root, args.base_ref)
        except RuntimeError as e:
            print(f"gamesman-lint: error: {e}", file=sys.stderr)
            return 2
        if not restrict:
            print(
                f"no lint targets changed vs {args.base_ref}",
                file=sys.stderr,
            )
            return 0

    try:
        result = run_project(args.root, paths=paths,
                             baseline_path=baseline_path,
                             restrict=restrict)
    except (FileNotFoundError, ValueError) as e:
        # Missing/outside-root targets and malformed baseline files are
        # usage errors, not tracebacks.
        print(f"gamesman-lint: error: {e}", file=sys.stderr)
        return 2

    if args.update_baseline:
        # Always anchored at --root (or the explicit --baseline), never
        # the process cwd — '--no-baseline --update-baseline' must not
        # scatter baseline files wherever the command happened to run.
        target = args.baseline or default_baseline
        write_baseline(target, result.fingerprints)
        print(
            f"wrote {len(result.fingerprints)} finding(s) to {target}",
            file=sys.stderr,
        )
        return 0

    if args.format == "sarif":
        print(json.dumps(to_sarif(result), indent=2))
    elif args.format == "json":
        payload = {
            "new": [d.to_json() for d in result.new],
            "baselined": [d.to_json() for d in result.baselined],
            "suppressed": [d.to_json() for d in result.suppressed],
            "ok": result.ok,
        }
        print(json.dumps(payload, indent=2))
    else:
        for d in result.new:
            print(d.format())
        if args.show_all:
            for d in result.baselined:
                print(f"{d.format()}  [baselined]")
            for d in result.suppressed:
                print(f"{d.format()}  [suppressed]")
        summary = (
            f"{len(result.new)} new, {len(result.baselined)} baselined, "
            f"{len(result.suppressed)} suppressed finding(s) over "
            f"{len(result.project.files)} file(s)"
        )
        print(summary, file=sys.stderr)
    return 0 if result.ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
