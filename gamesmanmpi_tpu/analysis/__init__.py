"""gamesman-lint: project-aware static analysis (docs/ANALYSIS.md).

The repo's correctness rests on conventions no generic linter knows:
jitted/shard_map'd kernels must stay trace-pure (host impurity inside a
traced function silently forces recompiles or host syncs — the class of
bug that sinks retrograde-solver ports), the serve/obs/resilience layers
are thread+lock code, and three registries (env vars vs docs/CONFIG.md,
metrics vs docs/OBSERVABILITY.md, fault points vs the chaos matrix)
drift unless a machine checks them. This package is that machine: an
AST-based checker suite run clean over the whole package as a tier-1
test (tests/test_lint.py), with inline suppressions and a checked-in
baseline for accepted findings.

Run it:

    python -m tools.lint              # or the gamesman-lint script

Checker families (ids are stable; catalogue in docs/ANALYSIS.md):

* ``GM1xx`` — JAX tracing safety (analysis/jax_tracing.py)
* ``GM2xx`` — lock discipline / race detection (analysis/locks.py)
* ``GM3xx`` — env-var registry parity (analysis/env_parity.py)
* ``GM4xx`` — metrics registry parity (analysis/metrics_parity.py)
* ``GM5xx`` — fault-point registry parity (analysis/faults_parity.py)
* ``GM6xx`` — SPMD / collective safety over the whole-program call
  graph (analysis/spmd.py)
* ``GM7xx`` — resource lifecycle & fork safety (analysis/lifecycle.py)
* ``GM8xx`` — atomic-write & seal discipline (analysis/atomic_write.py)

plus ``analysis/lockdep.py``, the runtime lock-order witness
(GAMESMAN_LOCKDEP=1) that validates the static lock model against real
acquisition edges and fails tests on witnessed cycles.
"""

from gamesmanmpi_tpu.analysis.diagnostics import Diagnostic
from gamesmanmpi_tpu.analysis.runner import run_project

__all__ = ["Diagnostic", "run_project"]
