"""GM8xx — atomic-write & seal discipline.

Checkpoint and DB directories survive preemption because every writer
follows one of two disciplines (docs/ARCHITECTURE.md):

* **tmp + os.replace** — write to a per-writer ``*.tmp`` name, then
  ``os.replace`` into place (``_savez``, ``write_manifest``): readers
  see the old bytes or the new bytes, never a torn file;
* **write-then-seal** — stream payload to its final name, then record
  it (count/crc/sha) in a manifest that is itself replaced atomically
  (``save_npy_hashed`` / ``save_blocks_hashed``): a file is real only
  once the manifest says so, so a death mid-write leaves an unsealed
  stray, not a corrupt database.

A direct write that follows neither is how "resume killed the run"
bugs are born (the torn in-place npz overwrites PR 3 fixed). These
checkers enforce the discipline in every module that practices it
(contains an ``os.replace`` or a ``# sealed-write:`` annotation —
modules that never write sealed state, e.g. report tools, are out of
scope by construction).

Conventions:

* ``# sealed-write: <why>`` on a ``def`` line (or the line above)
  declares a write-then-seal payload helper: its direct writes are
  exempt because a manifest seal follows at the call layer;
* a write is tmp+replace-compliant when its target is tmp-named
  (``tmp``/``*.tmp``) and the same function calls ``os.replace``;
* ``*.lock`` sentinel files are exempt — they carry no payload.

**store-io (GM803)**: since ISSUE 11, every sealed payload READ —
checkpoint/spill npz (``level_*``/``frontier*``/``edges_*``/
``dense_*``), DB block streams (``.gmb``) and level ``.npy`` pairs —
goes through ``gamesmanmpi_tpu/store/`` (crc-verified sealed reads,
the shared byte-budget cache, prefetch). A direct ``np.load`` /
``os.pread`` / ``open(..., "rb")`` of such a payload anywhere else
bypasses the cache AND the single quarantine/degrade door, which is
exactly how the three near-duplicate torn-read implementations this
refactor deleted grew in the first place. Deliberate escapes (the
integrity gate must read raw bytes) annotate with
``# store-io: <why>`` on the call line or the comment line above.

| id | finding |
|---|---|
| GM801 | direct write bypasses both atomic-write disciplines |
| GM802 | payload written after the manifest seal in the same function |
| GM803 | direct payload read bypasses the block store (store-io) |
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional

from gamesmanmpi_tpu.analysis.diagnostics import Diagnostic, directive_lines
from gamesmanmpi_tpu.analysis.project import (
    Project,
    SourceFile,
    attr_chain,
    call_name,
    walk_scoped,
)

_SEALED_WRITE_RE = re.compile(r"#\s*sealed-write:\s*(\S.*)")

#: callables that persist bytes; the checked target is their first arg
_WRITE_CALLS = {"save", "savez", "savez_compressed"}  # np.* tails
_WRITE_METHODS = {"write_text", "write_bytes"}  # target = receiver

#: call-name tails that seal a manifest / mark artifacts complete
_SEAL_RE = re.compile(r"(^|_)(seal|finish)|^_?write_manifest$")

#: payload-writing helpers for the GM802 ordering check
_PAYLOAD_HELPERS = re.compile(
    r"^_?savez$|^save_npy_hashed$|^save_blocks_hashed$|^save_"
)


def _has_annotation(src: SourceFile, lineno: int) -> bool:
    return any(_SEALED_WRITE_RE.search(t)
               for t in directive_lines(src.lines, lineno))


def _expr_mentions_tmp(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and "tmp" in n.id.lower():
            return True
        if isinstance(n, ast.Attribute) and "tmp" in n.attr.lower():
            return True
        if isinstance(n, ast.Constant) and isinstance(n.value, str) \
                and "tmp" in n.value.lower():
            return True
    return False


def _expr_mentions_lock(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, str) \
                and ".lock" in n.value:
            return True
    return False


def _write_target(call: ast.Call) -> Optional[ast.AST]:
    """The path expression a persistent-write call targets, or None
    when this call does not persist bytes."""
    chain = attr_chain(call.func) or []
    final = chain[-1] if chain else ""
    if final in _WRITE_CALLS and len(chain) >= 2 \
            and chain[0] in ("np", "numpy"):
        return call.args[0] if call.args else call
    if final in _WRITE_METHODS and len(chain) >= 2:
        return call.func.value
    if final == "open" and len(chain) == 1 and len(call.args) >= 2:
        mode = call.args[1]
        if isinstance(mode, ast.Constant) and isinstance(mode.value, str) \
                and any(c in mode.value for c in "wax"):
            return call.args[0]
    return None


def _walk_scoped_calls(fn):
    for node in walk_scoped(fn):
        if isinstance(node, ast.Call):
            yield node


def _module_participates(src: SourceFile) -> bool:
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Call) and call_name(node) == "os.replace":
            return True
    return any(_SEALED_WRITE_RE.search(line) for line in src.lines)


def _check_function(src: SourceFile, fn,
                    diags: List[Diagnostic]) -> None:
    if _has_annotation(src, fn.lineno):
        return  # declared write-then-seal payload helper
    calls = list(_walk_scoped_calls(fn))
    has_replace = any(call_name(c) == "os.replace" for c in calls)
    seal_lines: List[int] = []
    payload_lines: List[int] = []
    for call in calls:
        name = call_name(call)
        final = name.rsplit(".", 1)[-1]
        if _SEAL_RE.search(final):
            seal_lines.append(call.lineno)
        if _PAYLOAD_HELPERS.search(final):
            payload_lines.append(call.lineno)
        target = _write_target(call)
        if target is None:
            continue
        payload_lines.append(call.lineno)
        if _expr_mentions_lock(target):
            continue  # sentinel lockfile — no payload to tear
        if _expr_mentions_tmp(target) and has_replace:
            continue  # tmp + os.replace discipline
        diags.append(Diagnostic(
            src.rel, call.lineno, "GM801",
            "direct write bypasses the atomic-write discipline — "
            "write a *.tmp and os.replace it, or route through a "
            "sealed-write helper (_savez / save_blocks_hashed)",
        ))
    if seal_lines and payload_lines:
        first_seal = min(seal_lines)
        late = [ln for ln in payload_lines if ln > first_seal]
        for ln in late:
            diags.append(Diagnostic(
                src.rel, ln, "GM802",
                "payload written AFTER the manifest seal in this "
                "function — a death between the two leaves a sealed "
                "manifest pointing at missing/stale payload",
            ))


_STORE_IO_RE = re.compile(r"#\s*store-io:\s*(\S.*)")

#: Payload-name evidence for GM803: any of these in a read call's
#: string constants or source line marks the target as sealed payload.
#: Narrow on purpose — a generic ``np.load(path)`` of a user artifact
#: is not a finding; reading a checkpoint/DB payload by its naming
#: convention is.
_PAYLOAD_TOKEN_RE = re.compile(
    r"\.gmb|level_\d|level_\{|\blevel_key|\blevel_cell"
    r"|frontier|edges_|dense_|\.shard_"
    r"|rec\[[\"'](?:keys|cells)[\"']\]"
)

#: Read calls GM803 audits: np.load (mmap or whole-file), os.pread, and
#: binary open. (Writes are GM801's territory.)
_READ_CALLS = {"np.load", "numpy.load", "os.pread"}


def _is_payload_read(src: SourceFile, call: ast.Call) -> bool:
    name = call_name(call)
    is_open_rb = False
    if name == "open":
        # Positional or keyword mode — open(p, mode="rb") must not
        # slip past the rule.
        mode = call.args[1] if len(call.args) >= 2 else next(
            (kw.value for kw in call.keywords if kw.arg == "mode"), None
        )
        is_open_rb = (
            isinstance(mode, ast.Constant)
            and isinstance(mode.value, str)
            and "r" in mode.value and "b" in mode.value
        )
    if name not in _READ_CALLS and not is_open_rb:
        return False
    # Evidence: string constants inside the call, or the call's own
    # source line(s) — covers f-strings, Path /-joins, and rec["keys"].
    end = getattr(call, "end_lineno", call.lineno) or call.lineno
    text = "\n".join(src.lines[call.lineno - 1:end])
    if _PAYLOAD_TOKEN_RE.search(text):
        return True
    for n in ast.walk(call):
        if isinstance(n, ast.Constant) and isinstance(n.value, str) \
                and _PAYLOAD_TOKEN_RE.search(n.value):
            return True
    return False


def _check_store_io(src: SourceFile, diags: List[Diagnostic]) -> None:
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        if not _is_payload_read(src, node):
            continue
        if any(_STORE_IO_RE.search(t)
               for t in directive_lines(src.lines, node.lineno)):
            continue  # annotated deliberate escape
        diags.append(Diagnostic(
            src.rel, node.lineno, "GM803",
            "direct payload read bypasses the block store — route "
            "through gamesmanmpi_tpu/store (sealed_read/loadz/"
            "SealedBlockStream) or annotate a deliberate escape with "
            "# store-io: <why>",
        ))


def check(project: Project) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    for src in project.files:
        if src.tree is None:
            continue
        in_store = "store" in src.rel.replace("\\", "/").split("/")
        if not in_store:
            _check_store_io(src, diags)
        if not _module_participates(src):
            continue
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _check_function(src, node, diags)
    return diags
