"""GM3xx — environment-variable registry parity.

The degradation contract for config knobs lives in ``utils/env.py``
(warn-and-default) and ``utils/platform.py`` (platform-auto, strict);
the human registry is ``docs/CONFIG.md``. Three things drift without a
machine check:

| id | finding |
|---|---|
| GM301 | raw ``os.environ`` read (``.get``/``[...]``/``os.getenv``/``in``) outside ``utils/env.py`` — bypasses the shared parsing/degradation contract |
| GM302 | a ``GAMESMAN_*``/``BENCH_*`` var is read but missing from docs/CONFIG.md |
| GM303 | a var documented in CONFIG.md's tables is never read anywhere |

Reads are collected from helper calls (``env_int``/``env_float``/
``env_str``/``env_opt``/``platform_auto_flag``/``platform_auto_bool``,
leading underscores ignored so engine's ``_env_int`` re-export
matches) and from raw reads. Collect-only driver scripts (bench.py —
which deliberately cannot import this package) are scanned textually
for var tokens so their reads count toward GM303 without the scripts
being lint targets.

Writes (``os.environ[k] = v``, ``.setdefault``, ``.pop``) are not
findings: the CLI's flag-mirroring and test setup legitimately set the
environment; the contract under lint is how values are *read*.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from gamesmanmpi_tpu.analysis.diagnostics import Diagnostic
from gamesmanmpi_tpu.analysis.project import (
    CONFIG_MD,
    Project,
    SourceFile,
    attr_chain,
    call_name,
    const_str,
    module_string_consts,
)

#: Helper callables whose first argument is an env-var name.
ENV_HELPERS = {
    "env_int", "env_float", "env_int_strict", "env_str", "env_opt",
    "env_bool", "platform_auto_flag", "platform_auto_bool",
}

#: Files allowed to touch os.environ directly: the helper home and the
#: platform helpers built on it.
RAW_OK_SUFFIXES = ("utils/env.py",)

_VAR_RE = re.compile(r"\b((?:GAMESMAN|BENCH)_[A-Z0-9_]+)\b")

#: CONFIG.md table cells: | `GAMESMAN_X` | ... — the first cell of a
#: row documents the variable; prose mentions don't register a row.
_DOC_ROW_RE = re.compile(r"^\|\s*`((?:GAMESMAN|BENCH)_[A-Z0-9_]+)`\s*\|")


def _is_environ(node: ast.AST) -> bool:
    chain = attr_chain(node)
    if not chain or chain[-1] != "environ":
        return False
    return len(chain) == 1 or chain[-2] == "os"


def _raw_reads(tree: ast.AST):
    """Yield (node, name_or_None) for each raw environ *read*."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name in ("os.getenv", "getenv"):
                yield node, _first_str(node)
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and _is_environ(node.func.value)
            ):
                yield node, _first_str(node)
        elif isinstance(node, ast.Subscript) and _is_environ(node.value):
            if isinstance(node.ctx, ast.Load):
                name = None
                if isinstance(node.slice, ast.Constant) and isinstance(
                    node.slice.value, str
                ):
                    name = node.slice.value
                yield node, name
        elif isinstance(node, ast.Compare) and any(
            isinstance(op, (ast.In, ast.NotIn)) for op in node.ops
        ):
            for cmp_ in node.comparators:
                if _is_environ(cmp_):
                    name = None
                    if isinstance(node.left, ast.Constant) and isinstance(
                        node.left.value, str
                    ):
                        name = node.left.value
                    yield node, name


def _first_str(call: ast.Call):
    if call.args and isinstance(call.args[0], ast.Constant) and isinstance(
        call.args[0].value, str
    ):
        return call.args[0].value
    return None


def _helper_reads(src: SourceFile):
    consts = module_string_consts(src.tree)
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node).rsplit(".", 1)[-1].lstrip("_")
        if name in ENV_HELPERS and node.args:
            yield node, const_str(node.args[0], consts)


def check(project: Project) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    reads: Dict[str, Tuple[str, int]] = {}  # var -> first (file, line)

    def note(var, rel, line):
        if var is not None and var not in reads:
            reads[var] = (rel, line)

    for src in project.files:
        if src.tree is None:
            continue
        raw_ok = src.rel.endswith(RAW_OK_SUFFIXES)
        for node, var in _raw_reads(src.tree):
            note(var, src.rel, node.lineno)
            if not raw_ok:
                diags.append(Diagnostic(
                    src.rel, node.lineno, "GM301",
                    "raw os.environ read — go through "
                    "gamesmanmpi_tpu.utils.env (env_int/env_float/"
                    "env_str/env_opt) so parsing and degradation follow "
                    "the shared contract",
                ))
        for node, var in _helper_reads(src):
            note(var, src.rel, node.lineno)

    # Driver scripts outside the lint scope: token scan (their helpers
    # wrap os.environ locally, so AST call matching misses names).
    for src in project.collect_only:
        for i, line in enumerate(src.lines, 1):
            for var in _VAR_RE.findall(line):
                note(var, src.rel, i)

    doc_text = project.config_md
    # Exact-token matching, never substring: GAMESMAN_SORT must not count
    # as documented just because GAMESMAN_SORT_ROW's row contains it.
    # "Documented" = a table row (first cell) or any backticked mention.
    doc_rows: Set[str] = set()
    for line in doc_text.splitlines():
        m = _DOC_ROW_RE.match(line.strip())
        if m:
            doc_rows.add(m.group(1))
    documented = doc_rows | set(
        re.findall(r"`((?:GAMESMAN|BENCH)_[A-Z0-9_]+)`", doc_text)
    )

    for var, (rel, line) in sorted(reads.items()):
        if _VAR_RE.fullmatch(var) and var not in documented:
            diags.append(Diagnostic(
                rel, line, "GM302",
                f"env var {var} is read here but not documented in "
                f"{CONFIG_MD}",
            ))
    config_rel = CONFIG_MD
    for i, line in enumerate(doc_text.splitlines(), 1):
        m = _DOC_ROW_RE.match(line.strip())
        if m and m.group(1) not in reads:
            diags.append(Diagnostic(
                config_rel, i, "GM303",
                f"{m.group(1)} is documented as an env var but nothing "
                "reads it — stale doc row or dead knob",
            ))
    return diags
