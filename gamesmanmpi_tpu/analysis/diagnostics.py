"""Diagnostic records, inline suppressions, and the findings baseline.

One shared shape for every checker's output, plus the two escape
hatches a lint that gates tier-1 must have:

* inline suppression — ``# lint: disable=GM301`` on the flagged line
  (or the line directly above it) silences those ids there; a
  ``# lint: disable-file=GM301`` anywhere in a file's first
  ``FILE_DIRECTIVE_LINES`` lines silences the ids for the whole file.
  Suppressions are for findings that are *wrong or deliberate at that
  site* (say why in the same comment);
* baseline — a checked-in JSON file of accepted pre-existing findings.
  Baselined findings are reported as suppressed, everything new fails
  the run. Matching is by (id, path, fingerprint-of-source-line), not
  line number, so unrelated edits don't churn the file; duplicates are
  matched multiset-style.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import re

#: How deep into a file a ``disable-file`` directive may sit.
FILE_DIRECTIVE_LINES = 25

_INLINE_RE = re.compile(r"#\s*lint:\s*disable=([A-Za-z0-9_,\s]+)")
_FILE_RE = re.compile(r"#\s*lint:\s*disable-file=([A-Za-z0-9_,\s]+)")


@dataclasses.dataclass(frozen=True, order=True)
class Diagnostic:
    """One finding: stable checker id + location + message."""

    path: str  # project-root-relative, posix separators
    line: int  # 1-based
    id: str  # "GM301"
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.id} {self.message}"

    def to_json(self) -> dict:
        return {
            "path": self.path, "line": self.line,
            "id": self.id, "message": self.message,
        }


def _ids(match_group: str) -> set:
    return {t.strip() for t in match_group.split(",") if t.strip()}


def directive_lines(lines: list, line: int) -> list:
    """The lines a comment directive may sit on to apply to 1-based
    ``line``: the line itself, and a comment-ONLY line directly above.
    The shared placement rule for ``# lint: disable`` and the lock
    checker's ``# guarded-by``/``# requires-lock`` annotations — a
    trailing directive on the previous statement's line never bleeds
    onto the next."""
    out = []
    if 1 <= line <= len(lines):
        out.append(lines[line - 1])
    above = line - 1
    if 1 <= above <= len(lines) and lines[above - 1].lstrip().startswith("#"):
        out.append(lines[above - 1])
    return out


def suppressed_ids(lines: list, line: int) -> set:
    """Ids silenced at 1-based ``line``: inline directives (placement per
    ``directive_lines``) plus file-level directives. ``all`` silences
    everything (use sparingly)."""
    out: set = set()
    for text in directive_lines(lines, line):
        m = _INLINE_RE.search(text)
        if m:
            out |= _ids(m.group(1))
    for text in lines[:FILE_DIRECTIVE_LINES]:
        m = _FILE_RE.search(text)
        if m:
            out |= _ids(m.group(1))
    return out


def is_suppressed(diag: Diagnostic, lines: list) -> bool:
    ids = suppressed_ids(lines, diag.line)
    return diag.id in ids or "all" in ids


# ------------------------------------------------------------------ baseline


def fingerprint(diag: Diagnostic, lines: list) -> str:
    """Line-number-independent identity of a finding: the checker id,
    the file, and the whitespace-normalized source line it points at.
    Messages are excluded — wording improvements must not churn the
    baseline."""
    src = ""
    if 1 <= diag.line <= len(lines):
        src = " ".join(lines[diag.line - 1].split())
    digest = hashlib.sha256(
        f"{diag.id}\n{diag.path}\n{src}".encode()
    ).hexdigest()
    return digest[:16]


def load_baseline(path) -> list:
    """[{id, path, fingerprint}, ...]; a missing file is an empty
    baseline (the desired steady state)."""
    try:
        with open(path) as fh:
            data = json.load(fh)
    except FileNotFoundError:
        return []
    if not isinstance(data, dict) or not isinstance(
        data.get("findings"), list
    ):
        raise ValueError(f"malformed baseline file {path}")
    return data["findings"]


def write_baseline(path, diags_with_fp) -> None:
    findings = [
        {
            "id": d.id, "path": d.path, "fingerprint": fp,
            # line + message are documentation for the human reading the
            # baseline; matching ignores them.
            "line": d.line, "message": d.message,
        }
        for d, fp in sorted(diags_with_fp, key=lambda t: t[0])
    ]
    with open(path, "w") as fh:
        json.dump({"version": 1, "findings": findings}, fh, indent=2)
        fh.write("\n")


def split_by_baseline(diags_with_fp, baseline: list):
    """Partition findings into (new, baselined). Baseline entries are a
    multiset: two identical findings need two entries."""
    budget: dict = {}
    for e in baseline:
        key = (e.get("id"), e.get("path"), e.get("fingerprint"))
        budget[key] = budget.get(key, 0) + 1
    new, old = [], []
    for d, fp in diags_with_fp:
        key = (d.id, d.path, fp)
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            old.append(d)
        else:
            new.append(d)
    return new, old
