"""Project model: which files the checkers see, parsed once.

Discovery is convention-based so the same runner lints both the real
repo and the miniature fixture projects tests/test_lint.py builds:

* lint scope — every ``*.py`` under top-level packages (directories
  with an ``__init__.py``) plus ``tools/``;
* collect-only scope — top-level driver scripts (``bench.py``,
  ``solve_launcher.py``, ...): scanned by the registry-parity checkers
  (their env reads count) but never linted themselves — bench.py's
  parent process deliberately avoids importing this package (jax import
  cost), so it cannot use the utils/env helpers the lint enforces;
* registries — ``docs/CONFIG.md``, ``docs/OBSERVABILITY.md``, the
  module defining ``KNOWN_POINTS`` (fault points), and the chaos matrix
  ``tests/test_resilience.py``, located by those relative names.

Everything is parsed exactly once here; checkers share the index.
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
from typing import List, Optional

from gamesmanmpi_tpu.analysis.diagnostics import Diagnostic

EXCLUDED_DIRS = {"__pycache__", ".git", ".jax_compile_cache", "artifacts"}

#: Top-level scripts whose env reads feed the parity checkers without the
#: files themselves being lint targets (see module docstring).
COLLECT_ONLY = ("bench.py", "solve_launcher.py")

CONFIG_MD = "docs/CONFIG.md"
OBSERVABILITY_MD = "docs/OBSERVABILITY.md"
CHAOS_TEST = "tests/test_resilience.py"


@dataclasses.dataclass
class SourceFile:
    rel: str  # root-relative posix path
    text: str
    lines: List[str]
    tree: Optional[ast.AST]  # None when the file fails to parse
    parse_error: Optional[Diagnostic]


@dataclasses.dataclass
class Project:
    root: pathlib.Path
    files: List[SourceFile]  # lint scope
    collect_only: List[SourceFile]  # registry-parity scope only
    config_md: str  # "" when absent
    observability_md: str
    chaos_text: str

    def file(self, rel: str) -> Optional[SourceFile]:
        for f in self.files + self.collect_only:
            if f.rel == rel:
                return f
        return None


def _load(root: pathlib.Path, p: pathlib.Path) -> SourceFile:
    rel = p.relative_to(root).as_posix()
    text = p.read_text(encoding="utf-8", errors="replace")
    lines = text.splitlines()
    tree, err = None, None
    try:
        tree = ast.parse(text, filename=rel)
    except SyntaxError as e:
        err = Diagnostic(rel, e.lineno or 1, "GM001",
                         f"syntax error: {e.msg}")
    return SourceFile(rel, text, lines, tree, err)


def _read(root: pathlib.Path, rel: str) -> str:
    p = root / rel
    try:
        return p.read_text(encoding="utf-8", errors="replace")
    except FileNotFoundError:
        return ""


def _iter_py(d: pathlib.Path):
    for p in sorted(d.rglob("*.py")):
        if not any(part in EXCLUDED_DIRS for part in p.parts):
            yield p


def load_project(root, paths=None) -> Project:
    """Build the project index.

    ``paths``: explicit lint targets (files or directories) overriding
    the default scope — the registry files and collect-only scripts are
    still picked up from ``root`` so parity checks stay whole-project.
    """
    root = pathlib.Path(root).resolve()
    targets: List[pathlib.Path] = []
    if paths:
        for raw in paths:
            p = pathlib.Path(raw)
            if not p.is_absolute():
                p = root / p
            p = p.resolve()
            if not p.exists():
                # A typo'd explicit target is a usage error the CLI turns
                # into exit 2 — never a traceback from read_text.
                raise FileNotFoundError(f"lint target not found: {raw}")
            if not p.is_relative_to(root):
                # Everything reports root-relative paths; a target outside
                # the root has no spelling in that scheme.
                raise ValueError(
                    f"lint target {raw} is outside --root {root}"
                )
            if p.is_dir():
                targets.extend(_iter_py(p))
            else:
                targets.append(p)
    else:
        for child in sorted(root.iterdir()):
            if child.name in EXCLUDED_DIRS or not child.is_dir():
                continue
            if (child / "__init__.py").exists() or child.name == "tools":
                targets.extend(_iter_py(child))
    seen = set()
    files = []
    for p in targets:
        rel = p.relative_to(root).as_posix()
        if rel not in seen:
            seen.add(rel)
            files.append(_load(root, p))
    collect = [
        _load(root, root / name)
        for name in COLLECT_ONLY
        if (root / name).exists() and name not in seen
    ]
    return Project(
        root=root,
        files=files,
        collect_only=collect,
        config_md=_read(root, CONFIG_MD),
        observability_md=_read(root, OBSERVABILITY_MD),
        chaos_text=_read(root, CHAOS_TEST),
    )


# ---------------------------------------------------------- shared AST utils


def attr_chain(node: ast.AST) -> Optional[List[str]]:
    """["os", "environ", "get"] for os.environ.get; None when the
    expression is not a pure Name/Attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


def call_name(node: ast.Call) -> str:
    """Dotted name of a call's callee ("" when not a name chain)."""
    chain = attr_chain(node.func)
    return ".".join(chain) if chain else ""


def const_str(node: ast.AST, module_consts=None) -> Optional[str]:
    """A string literal, or a Name resolving to a module-level string
    constant (``module_consts``: {name: value})."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if (
        module_consts is not None
        and isinstance(node, ast.Name)
        and isinstance(module_consts.get(node.id), str)
    ):
        return module_consts[node.id]
    return None


def module_string_consts(tree: ast.AST) -> dict:
    """Module-level NAME = "literal" assignments (single target, assigned
    exactly once — reassigned names are dropped as unreliable)."""
    out: dict = {}
    dropped = set()
    for node in getattr(tree, "body", []):
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            target = node.target
            value = node.value
        else:
            continue
        if not isinstance(target, ast.Name):
            continue
        name = target.id
        if name in out or name in dropped:
            out.pop(name, None)
            dropped.add(name)
            continue
        if isinstance(value, ast.Constant) and isinstance(value.value, str):
            out[name] = value.value
    return out
