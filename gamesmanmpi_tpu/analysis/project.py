"""Project model: which files the checkers see, parsed once.

Discovery is convention-based so the same runner lints both the real
repo and the miniature fixture projects tests/test_lint.py builds:

* lint scope — every ``*.py`` under top-level packages (directories
  with an ``__init__.py``) plus ``tools/``;
* collect-only scope — top-level driver scripts (``bench.py``,
  ``solve_launcher.py``, ...): scanned by the registry-parity checkers
  (their env reads count) but never linted themselves — bench.py's
  parent process deliberately avoids importing this package (jax import
  cost), so it cannot use the utils/env helpers the lint enforces;
* registries — ``docs/CONFIG.md``, ``docs/OBSERVABILITY.md``, the
  module defining ``KNOWN_POINTS`` (fault points), and the chaos matrix
  ``tests/test_resilience.py``, located by those relative names.

Everything is parsed exactly once here; checkers share the index.
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
from typing import List, Optional

from gamesmanmpi_tpu.analysis.diagnostics import Diagnostic

EXCLUDED_DIRS = {"__pycache__", ".git", ".jax_compile_cache", "artifacts"}

#: Top-level scripts whose env reads feed the parity checkers without the
#: files themselves being lint targets (see module docstring).
COLLECT_ONLY = ("bench.py", "solve_launcher.py")

CONFIG_MD = "docs/CONFIG.md"
OBSERVABILITY_MD = "docs/OBSERVABILITY.md"
CHAOS_TEST = "tests/test_resilience.py"


@dataclasses.dataclass
class SourceFile:
    rel: str  # root-relative posix path
    text: str
    lines: List[str]
    tree: Optional[ast.AST]  # None when the file fails to parse
    parse_error: Optional[Diagnostic]


@dataclasses.dataclass
class Project:
    root: pathlib.Path
    files: List[SourceFile]  # lint scope
    collect_only: List[SourceFile]  # registry-parity scope only
    config_md: str  # "" when absent
    observability_md: str
    chaos_text: str
    #: Expensive derived indexes, built lazily and exactly ONCE per run,
    #: shared by every checker (the tier-1 60 s budget depends on it).
    _callgraph: Optional["CallGraph"] = None
    #: How many times the call graph was built — the runtime-budget test
    #: asserts this stays 1 however many checkers consume it.
    callgraph_builds: int = 0
    #: rel -> per-module lock inventory (locks._ModuleLocks), shared by
    #: the GM2xx and GM6xx checkers. Typed loosely to avoid an import
    #: cycle (locks.py imports this module).
    _module_locks: dict = dataclasses.field(default_factory=dict)

    def file(self, rel: str) -> Optional[SourceFile]:
        for f in self.files + self.collect_only:
            if f.rel == rel:
                return f
        return None

    def callgraph(self) -> "CallGraph":
        if self._callgraph is None:
            self._callgraph = CallGraph(self)
            self.callgraph_builds += 1
        return self._callgraph

    def module_locks(self, src: SourceFile):
        """Memoized lock inventory for one module (see module_locks in
        analysis/locks.py — the builder is injected there to keep the
        import direction project <- locks)."""
        if src.rel not in self._module_locks:
            from gamesmanmpi_tpu.analysis.locks import _ModuleLocks

            mod = _ModuleLocks(src)
            mod.compute_acquires()
            self._module_locks[src.rel] = mod
        return self._module_locks[src.rel]


def _load(root: pathlib.Path, p: pathlib.Path) -> SourceFile:
    rel = p.relative_to(root).as_posix()
    text = p.read_text(encoding="utf-8", errors="replace")
    lines = text.splitlines()
    tree, err = None, None
    try:
        tree = ast.parse(text, filename=rel)
    except SyntaxError as e:
        err = Diagnostic(rel, e.lineno or 1, "GM001",
                         f"syntax error: {e.msg}")
    return SourceFile(rel, text, lines, tree, err)


def _read(root: pathlib.Path, rel: str) -> str:
    p = root / rel
    try:
        return p.read_text(encoding="utf-8", errors="replace")
    except FileNotFoundError:
        return ""


def _iter_py(d: pathlib.Path):
    for p in sorted(d.rglob("*.py")):
        if not any(part in EXCLUDED_DIRS for part in p.parts):
            yield p


def default_scope_rels(root) -> set:
    """Root-relative posix paths of every file the default (whole-
    project) discovery would lint — the filter ``--changed-only`` uses
    so a git-scoped run never lints files (tests, docs scripts) the
    full run would not."""
    root = pathlib.Path(root).resolve()
    out = set()
    for child in sorted(root.iterdir()):
        if child.name in EXCLUDED_DIRS or not child.is_dir():
            continue
        if (child / "__init__.py").exists() or child.name == "tools":
            for p in _iter_py(child):
                out.add(p.relative_to(root).as_posix())
    return out


def load_project(root, paths=None) -> Project:
    """Build the project index.

    ``paths``: explicit lint targets (files or directories) overriding
    the default scope — the registry files and collect-only scripts are
    still picked up from ``root`` so parity checks stay whole-project.
    """
    root = pathlib.Path(root).resolve()
    targets: List[pathlib.Path] = []
    if paths:
        for raw in paths:
            p = pathlib.Path(raw)
            if not p.is_absolute():
                p = root / p
            p = p.resolve()
            if not p.exists():
                # A typo'd explicit target is a usage error the CLI turns
                # into exit 2 — never a traceback from read_text.
                raise FileNotFoundError(f"lint target not found: {raw}")
            if not p.is_relative_to(root):
                # Everything reports root-relative paths; a target outside
                # the root has no spelling in that scheme.
                raise ValueError(
                    f"lint target {raw} is outside --root {root}"
                )
            if p.is_dir():
                targets.extend(_iter_py(p))
            else:
                targets.append(p)
    else:
        # One discovery rule, shared with --changed-only's reporting
        # filter: the two must never diverge or a git-scoped run would
        # drop findings the full run reports.
        targets = [root / rel for rel in sorted(default_scope_rels(root))]
    seen = set()
    files = []
    for p in targets:
        rel = p.relative_to(root).as_posix()
        if rel not in seen:
            seen.add(rel)
            files.append(_load(root, p))
    collect = [
        _load(root, root / name)
        for name in COLLECT_ONLY
        if (root / name).exists() and name not in seen
    ]
    return Project(
        root=root,
        files=files,
        collect_only=collect,
        config_md=_read(root, CONFIG_MD),
        observability_md=_read(root, OBSERVABILITY_MD),
        chaos_text=_read(root, CHAOS_TEST),
    )


# ---------------------------------------------------------- shared AST utils


def attr_chain(node: ast.AST) -> Optional[List[str]]:
    """["os", "environ", "get"] for os.environ.get; None when the
    expression is not a pure Name/Attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


def call_name(node: ast.Call) -> str:
    """Dotted name of a call's callee ("" when not a name chain)."""
    chain = attr_chain(node.func)
    return ".".join(chain) if chain else ""


def const_str(node: ast.AST, module_consts=None) -> Optional[str]:
    """A string literal, or a Name resolving to a module-level string
    constant (``module_consts``: {name: value})."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if (
        module_consts is not None
        and isinstance(node, ast.Name)
        and isinstance(module_consts.get(node.id), str)
    ):
        return module_consts[node.id]
    return None


def from_import_map(tree: ast.AST) -> dict:
    """local name -> dotted origin for ``from mod import name [as n]``,
    the shared resolver the GM7xx/GM8xx checkers use so
    ``from subprocess import Popen`` reads the same as
    ``subprocess.Popen``. (CallGraph keeps its own richer two-map form
    for cross-module function resolution.)"""
    out: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module \
                and node.level == 0:
            for alias in node.names:
                out[alias.asname or alias.name] = \
                    f"{node.module}.{alias.name}"
    return out


def walk_scoped(fn):
    """All nodes of ``fn`` excluding nested function/class/lambda
    bodies — those belong to their own scope and are audited there.
    The shared traversal for per-function checkers."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop(0)
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def stmt_terminates(stmts: list) -> str:
    """How a statement list exits early: "return" (also break/continue —
    control leaves the list), "raise", or "" when it falls through."""
    if not stmts:
        return ""
    last = stmts[-1]
    if isinstance(last, (ast.Return, ast.Break, ast.Continue)):
        return "return"
    if isinstance(last, ast.Raise):
        return "raise"
    return ""


# ------------------------------------------------------------- call graph
#
# A name-based whole-program index: every function/method (including
# nested defs) keyed as "<rel>::<qualname>", with its call sites resolved
# through imports, self-dispatch, and enclosing-scope nesting. Functions
# *passed* as arguments (builders, retry thunks, thread targets) become
# callback events tagged with the receiving callee's name, so checkers
# can decide which funnels propagate behavior (get_kernel dispatches the
# built kernel at the call site; schedule_kernel only compiles it).
# Resolution is conventional, not perfect — same spirit as the rest of
# the suite: one name means one thing in this repo.


@dataclasses.dataclass
class CallEvent:
    """One call site (or callback argument) inside a function body."""

    lineno: int
    node: ast.AST  # the ast.Call
    callee: Optional[str]  # resolved function key, None when external
    external: str  # dotted text of an unresolved callee ("jax.lax.psum")
    final: str  # last segment of the callee name ("psum")
    chain: tuple  # full attr chain as written, () when not a name chain
    via: str = ""  # "" = direct call; else the name of the function this
    #               one was passed TO as an argument (callback edge)


@dataclasses.dataclass
class FunctionNode:
    key: str
    rel: str
    qualname: str  # "Class.method", "outer.inner", "func"
    name: str
    cls: Optional[str]  # enclosing class name, None for plain functions
    node: ast.AST
    lineno: int
    events: List[CallEvent] = dataclasses.field(default_factory=list)


def _module_dotted(rel: str) -> str:
    name = rel[:-3] if rel.endswith(".py") else rel
    if name.endswith("/__init__"):
        name = name[: -len("/__init__")]
    return name.replace("/", ".")


class CallGraph:
    """Cross-module call graph + per-function ordered call events."""

    def __init__(self, project: Project):
        self.project = project
        self.functions: dict = {}
        self.by_module: dict = {}
        self._module_of: dict = {}  # dotted module name -> rel
        self._imports: dict = {}  # rel -> {alias: dotted module}
        self._from_imports: dict = {}  # rel -> {name: (module, attr)}
        self._toplevel: dict = {}  # rel -> {func name: key}
        self._methods: dict = {}  # (rel, cls) -> {method name: key}
        for src in project.files:
            if src.tree is not None:
                self._module_of[_module_dotted(src.rel)] = src.rel
        for src in project.files:
            if src.tree is not None:
                self._collect_imports(src)
                self._register_functions(src)
        for src in project.files:
            if src.tree is not None:
                self._collect_events(src)

    # ------------------------------------------------------------ indexing

    def _collect_imports(self, src: SourceFile) -> None:
        imports: dict = {}
        froms: dict = {}
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        imports[alias.asname] = alias.name
                    else:
                        # "import a.b" binds "a"; chains through it
                        # resolve segment-wise against known modules.
                        head = alias.name.split(".")[0]
                        imports.setdefault(head, head)
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for alias in node.names:
                    froms[alias.asname or alias.name] = (
                        node.module, alias.name
                    )
        self._imports[src.rel] = imports
        self._from_imports[src.rel] = froms

    @staticmethod
    def _scoped_defs(body):
        """def/class statements in ``body``, including ones nested in
        loops/ifs/trys, but NOT inside other defs or classes (those are
        a deeper scope)."""
        stack = list(body)
        while stack:
            node = stack.pop(0)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                yield node
                continue
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.stmt):
                    stack.append(child)

    def _register_functions(self, src: SourceFile) -> None:
        top: dict = {}
        self._toplevel[src.rel] = top
        self.by_module[src.rel] = []

        def visit(body, prefix: str, cls: Optional[str]):
            for node in self._scoped_defs(body):
                if isinstance(node, ast.ClassDef):
                    self._methods.setdefault((src.rel, node.name), {})
                    visit(node.body, f"{prefix}{node.name}.", node.name)
                else:
                    qual = f"{prefix}{node.name}"
                    key = f"{src.rel}::{qual}"
                    self.functions[key] = FunctionNode(
                        key, src.rel, qual, node.name, cls, node,
                        node.lineno,
                    )
                    self.by_module[src.rel].append(key)
                    if prefix == "":
                        top[node.name] = key
                    elif cls is not None and prefix == f"{cls}.":
                        self._methods[(src.rel, cls)][node.name] = key
                    visit(node.body, f"{qual}.", cls)

        visit(src.tree.body, "", None)

    # ---------------------------------------------------------- resolution

    def _module_func(self, rel: Optional[str], name: str) -> Optional[str]:
        if rel is None:
            return None
        return self._toplevel.get(rel, {}).get(name)

    def _resolve_dotted(self, rel: str, chain: List[str]) -> Optional[str]:
        """Resolve ["mod", ..., "func"] through this module's imports to
        a project function key (longest module prefix wins)."""
        head = chain[0]
        dotted = None
        if head in self._imports.get(rel, {}):
            dotted = self._imports[rel][head]
        elif head in self._from_imports.get(rel, {}):
            mod, attr = self._from_imports[rel][head]
            dotted = f"{mod}.{attr}"
        if dotted is None:
            return None
        parts = dotted.split(".") + chain[1:]
        mod_rel = self._module_of.get(".".join(parts[:-1]))
        return self._module_func(mod_rel, parts[-1])

    def resolve(self, src: SourceFile, scope: List[str],
                chain: List[str]) -> Optional[str]:
        """Resolve a call's attr chain to a function key. ``scope`` is
        the qualname chain of enclosing functions (innermost last)."""
        if not chain:
            return None
        if len(chain) == 1:
            name = chain[0]
            for i in range(len(scope), 0, -1):
                # Only function scopes host bare-name-visible nested defs
                # (a class scope's methods need self./cls.).
                parent = f"{src.rel}::{'.'.join(scope[:i])}"
                if parent not in self.functions:
                    continue
                nested = f"{parent}.{name}"
                if nested in self.functions:
                    return nested
            local = self._toplevel.get(src.rel, {}).get(name)
            if local is not None:
                return local
            frm = self._from_imports.get(src.rel, {}).get(name)
            if frm is not None:
                mod, attr = frm
                return self._module_func(self._module_of.get(mod), attr)
            return None
        if chain[0] in ("self", "cls") and len(chain) == 2:
            cls = self._enclosing_class(src, scope)
            if cls is not None:
                return self._methods.get((src.rel, cls), {}).get(chain[1])
            return None
        return self._resolve_dotted(src.rel, chain)

    def _enclosing_class(self, src: SourceFile,
                         scope: List[str]) -> Optional[str]:
        key = f"{src.rel}::{'.'.join(scope)}"
        fn = self.functions.get(key)
        return fn.cls if fn is not None else None

    # -------------------------------------------------------------- events

    def _collect_events(self, src: SourceFile) -> None:
        graph = self

        def event_for(call: ast.Call, scope: List[str]) -> CallEvent:
            chain = attr_chain(call.func) or []
            callee = graph.resolve(src, scope, chain)
            return CallEvent(
                lineno=call.lineno,
                node=call,
                callee=callee,
                external="" if callee else ".".join(chain),
                final=chain[-1] if chain else "",
                chain=tuple(chain),
            )

        def walk_fn(fn_key: str, body, scope: List[str]) -> None:
            events = graph.functions[fn_key].events

            def visit(node):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    inner_scope = scope + [node.name]
                    inner_key = f"{src.rel}::{'.'.join(inner_scope)}"
                    if inner_key in graph.functions:
                        walk_fn(inner_key, node.body, inner_scope)
                    return
                if isinstance(node, ast.ClassDef):
                    for item in graph._scoped_defs(node.body):
                        if isinstance(item, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)):
                            m_scope = scope + [node.name, item.name]
                            m_key = f"{src.rel}::{'.'.join(m_scope)}"
                            if m_key in graph.functions:
                                walk_fn(m_key, item.body, m_scope)
                    return
                if isinstance(node, ast.Call):
                    ev = event_for(node, scope)
                    events.append(ev)
                    # callback edges: functions passed as arguments
                    receiver = ev.final
                    args = list(node.args) + [
                        kw.value for kw in node.keywords
                    ]
                    for arg in args:
                        a_chain = attr_chain(arg)
                        if not a_chain:
                            continue
                        target = graph.resolve(src, scope, a_chain)
                        if target is not None:
                            events.append(CallEvent(
                                lineno=getattr(arg, "lineno", node.lineno),
                                node=arg,
                                callee=target,
                                external="",
                                final=a_chain[-1],
                                chain=tuple(a_chain),
                                via=receiver or "<call>",
                            ))
                for child in ast.iter_child_nodes(node):
                    visit(child)

            for stmt in body:
                visit(stmt)

        for node in self._scoped_defs(src.tree.body):
            self._visit_top(src, node, [], walk_fn)

    def _visit_top(self, src, node, scope, walk_fn) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            key = f"{src.rel}::{'.'.join(scope + [node.name])}"
            if key in self.functions:
                walk_fn(key, node.body, scope + [node.name])
        elif isinstance(node, ast.ClassDef):
            for item in self._scoped_defs(node.body):
                self._visit_top(src, item, scope + [node.name], walk_fn)

    # ------------------------------------------------------------ reach

    def reach(self, direct: dict, exclude_vias=frozenset()) -> dict:
        """Transitive closure: ``direct`` maps function key -> truthy
        mark for functions that directly exhibit a behavior; returns
        {key: True} for every function that can reach one through call
        or callback edges (minus ``exclude_vias`` callback funnels)."""
        reached = {k: True for k, v in direct.items() if v}
        changed = True
        while changed:
            changed = False
            for key, fn in self.functions.items():
                if key in reached:
                    continue
                for ev in fn.events:
                    if ev.via and ev.via in exclude_vias:
                        continue
                    if ev.callee is not None and ev.callee in reached:
                        reached[key] = True
                        changed = True
                        break
        return reached


def module_string_consts(tree: ast.AST) -> dict:
    """Module-level NAME = "literal" assignments (single target, assigned
    exactly once — reassigned names are dropped as unreliable)."""
    out: dict = {}
    dropped = set()
    for node in getattr(tree, "body", []):
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            target = node.target
            value = node.value
        else:
            continue
        if not isinstance(target, ast.Name):
            continue
        name = target.id
        if name in out or name in dropped:
            out.pop(name, None)
            dropped.add(name)
            continue
        if isinstance(value, ast.Constant) and isinstance(value.value, str):
            out[name] = value.value
    return out
