"""GM5xx — fault-point registry parity.

``resilience/faults.py`` KNOWN_POINTS is the chaos contract: every
woven ``faults.fire("point")`` call site must be registered, every
registered point must actually be woven somewhere, and every point must
be exercised by the chaos matrix (tests/test_resilience.py) — a fault
point without chaos coverage is failure handling that has never run.

| id | finding |
|---|---|
| GM501 | ``fire()`` on a point not in KNOWN_POINTS |
| GM502 | KNOWN_POINTS entry with no ``fire()`` site anywhere |
| GM503 | duplicate key in the KNOWN_POINTS dict literal (silently collapses) |
| GM504 | registered point never referenced by the chaos matrix |
| GM505 | ``fire()`` whose point is not statically resolvable |
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from gamesmanmpi_tpu.analysis.diagnostics import Diagnostic
from gamesmanmpi_tpu.analysis.project import (
    CHAOS_TEST,
    Project,
    SourceFile,
    call_name,
    const_str,
    module_string_consts,
)


def _find_registry(
    project: Project,
) -> Tuple[Optional[SourceFile], Dict[str, int], List[Diagnostic]]:
    """Locate the module-level ``KNOWN_POINTS = {...}`` dict: returns
    (file, {point: line}, duplicate-key findings)."""
    diags: List[Diagnostic] = []
    for src in project.files:
        if src.tree is None:
            continue
        for node in src.tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "KNOWN_POINTS"
                and isinstance(node.value, ast.Dict)
            ):
                points: Dict[str, int] = {}
                for k in node.value.keys:
                    if isinstance(k, ast.Constant) and isinstance(
                        k.value, str
                    ):
                        if k.value in points:
                            diags.append(Diagnostic(
                                src.rel, k.lineno, "GM503",
                                f"duplicate fault point {k.value!r} in "
                                "KNOWN_POINTS — the first entry is "
                                "silently overwritten",
                            ))
                        points[k.value] = k.lineno
                return src, points, diags
    return None, {}, diags


def check(project: Project) -> List[Diagnostic]:
    reg_src, points, diags = _find_registry(project)
    if reg_src is None:
        return diags  # project without a fault registry: nothing to check
    fired: Dict[str, Tuple[str, int]] = {}
    for src in project.files:
        if src.tree is None or src is reg_src:
            continue
        consts = module_string_consts(src.tree)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            if call_name(node).rsplit(".", 1)[-1] != "fire":
                continue
            if not node.args:
                continue
            point = const_str(node.args[0], consts)
            if point is None:
                diags.append(Diagnostic(
                    src.rel, node.lineno, "GM505",
                    "fire() with a non-literal fault point — the chaos "
                    "registry can't be audited statically",
                ))
            elif point not in points:
                diags.append(Diagnostic(
                    src.rel, node.lineno, "GM501",
                    f"fire({point!r}) is not registered in "
                    "KNOWN_POINTS — it can never be armed and gets no "
                    "chaos coverage",
                ))
            else:
                fired.setdefault(point, (src.rel, node.lineno))
    for point, line in sorted(points.items()):
        if point not in fired:
            diags.append(Diagnostic(
                reg_src.rel, line, "GM502",
                f"fault point {point!r} is registered but never "
                "woven into any call site",
            ))
        # Exact-token match (dot/word boundaries): 'engine.forward' must
        # not count as covered because 'engine.forward_edges' appears.
        covered = re.search(
            rf"(?<![\w.]){re.escape(point)}(?![\w.])", project.chaos_text
        )
        if not covered:
            diags.append(Diagnostic(
                reg_src.rel, line, "GM504",
                f"fault point {point!r} has no chaos coverage — "
                f"{CHAOS_TEST} never references it",
            ))
    return diags
