"""GM9xx — committed GameSpec validity.

A GameSpec .json under ``examples/specs/`` is executable configuration:
the CLI compiles it straight into solver kernels (docs/GAMEDSL.md). A
committed spec that fails validation is therefore dead-on-arrival docs —
`gamesman solve --spec` would refuse it with the same findings this
checker reports. The checker runs gamedsl's static validator
(gamesmanmpi_tpu.gamedsl.spec — stdlib-only, no kernel tracing, in
keeping with the runner's never-import-the-code rule for accelerator
safety) over every committed spec and reports error-severity findings;
warnings (e.g. GS102's fused-table-gate note) are advisory and stay out
of CI.

| id | finding |
|---|---|
| GM901 | committed GameSpec file fails gamedsl validation (the GS* code and message are embedded) |
"""

from __future__ import annotations

from typing import List

from gamesmanmpi_tpu.analysis.diagnostics import Diagnostic
from gamesmanmpi_tpu.analysis.project import Project
from gamesmanmpi_tpu.gamedsl.spec import lint_file

#: repo-relative directory holding the committed spec files
SPEC_DIR = ("examples", "specs")


def check(project: Project) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    spec_dir = project.root.joinpath(*SPEC_DIR)
    if not spec_dir.is_dir():
        return out
    for path in sorted(spec_dir.glob("*.json")):
        rel = path.relative_to(project.root).as_posix()
        for finding in lint_file(str(path)):
            if finding["severity"] != "error":
                continue
            out.append(Diagnostic(
                rel, 1, "GM901",
                f"{finding['code']}: {finding['message']}",
            ))
    return out
