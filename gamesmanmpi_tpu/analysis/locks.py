"""GM2xx — lock discipline / race detection.

Opt-in annotations (comments, so the runtime never sees them):

* ``# guarded-by: _lock`` on a field's declaring assignment (usually in
  ``__init__``; same line or the line above) — every later read/write
  of that attribute in the module must happen inside a
  ``with self._lock:`` region (any receiver whose attribute chain ends
  in the lock's name counts: ``with reg._lock:`` guards
  ``fam.values``);
* ``# requires-lock: _lock`` on a ``def`` line (or the line above) —
  the method's body is checked as if the lock were held, and *callers*
  must hold it.

Lock inventory is read from ``__init__``: ``threading.Lock()`` /
``RLock()`` / ``Condition(self._lock)``; a Condition constructed over a
lock is an alias for it (holding the condition holds the lock — the
batcher's ``_cond`` pattern). ``__init__`` itself is exempt from
guarded-field checks: construction is single-threaded by contract.

| id | finding |
|---|---|
| GM201 | guarded field accessed without its lock held |
| GM202 | non-reentrant lock re-acquired while held (with-block or a call that acquires it) — deadlock |
| GM203 | blocking call (queue.get / socket I/O / np.load / .result() / thread join / sleep / subprocess) while a lock is held |
| GM204 | method annotated requires-lock called without the lock held |
| GM205 | lock acquisition reachable from a registered signal handler |

GM201-GM204 are lexical and name-based per module (the repo convention:
one lock name means one lock), so they need no imports and no types.
GM205 is whole-program: CPython delivers signals on the main thread, so
a handler that (transitively, through the cross-module call graph)
acquires a lock can interrupt the very ``with lock:`` region it then
blocks on — the self-deadlock class PR 7's fourth review pass fixed by
hand in the serve supervisor's ``request_stop``. Handlers must stay
lock-free: set a flag, write a pipe, ``os.kill`` a child. Functions a
handler only *spawns* (``Thread``/``Timer`` targets — their bodies run
on another thread's program order) do not propagate.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from gamesmanmpi_tpu.analysis.diagnostics import Diagnostic, directive_lines
from gamesmanmpi_tpu.analysis.project import (
    Project,
    SourceFile,
    attr_chain,
    call_name,
)

_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_]\w*)")
_REQUIRES_RE = re.compile(r"#\s*requires-lock:\s*([A-Za-z_]\w*)")

#: Call shapes that block the calling thread. Receiver-name patterns
#: keep dict.get and str.join out of the match.
_BLOCKING_SIMPLE = {
    "time.sleep", "np.load", "numpy.load", "subprocess.run",
    "subprocess.check_call", "subprocess.check_output", "os.waitpid",
}
_SOCKET_METHODS = {"recv", "recvfrom", "accept", "connect", "sendall",
                   "makefile"}
_QUEUEISH_RE = re.compile(r"(queue|_q$|^q$)", re.IGNORECASE)
_THREADISH_RE = re.compile(
    r"(thread|worker|proc|process|child|future)", re.IGNORECASE
)


def _comment_annotation(lines: List[str], lineno: int, rx) -> Optional[str]:
    """First annotation applying to ``lineno`` (placement rule shared
    with inline suppressions: diagnostics.directive_lines)."""
    for text in directive_lines(lines, lineno):
        m = rx.search(text)
        if m:
            return m.group(1)
    return None


def _final_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


class _ModuleLocks:
    """Per-module inventory: locks, aliases, guarded fields, and which
    locks each function/method/property may acquire."""

    def __init__(self, src: SourceFile):
        self.src = src
        self.lock_kind: Dict[str, str] = {}  # name -> lock|rlock
        self.alias: Dict[str, str] = {}  # condition name -> lock name
        self.guarded: Dict[str, Tuple[str, int]] = {}  # field -> (lock, line)
        self.requires: Dict[ast.AST, str] = {}  # function node -> lock
        #: class name -> {method name: node}; properties included.
        self.methods: Dict[str, Dict[str, ast.AST]] = {}
        self.properties: Dict[str, Set[str]] = {}
        self.acquires: Dict[ast.AST, Set[str]] = {}
        self._collect()

    def canonical(self, name: str) -> str:
        return self.alias.get(name, name)

    def _collect(self) -> None:
        lines = self.src.lines
        for node in ast.walk(self.src.tree):
            if isinstance(node, ast.ClassDef):
                ms: Dict[str, ast.AST] = {}
                props: Set[str] = set()
                for item in node.body:
                    if isinstance(
                        item, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        ms[item.name] = item
                        if any(
                            (attr_chain(d) or [])[-1:] == ["property"]
                            for d in item.decorator_list
                        ):
                            props.add(item.name)
                self.methods[node.name] = ms
                self.properties[node.name] = props
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                req = _comment_annotation(lines, node.lineno, _REQUIRES_RE)
                if req is not None:
                    self.requires[node] = req
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                self._collect_assign(node)
            if isinstance(node, ast.AnnAssign):
                self._collect_target(node.target, node, node.value)

    def _collect_assign(self, node: ast.Assign) -> None:
        self._collect_target(node.targets[0], node, node.value)

    def _collect_target(self, target, node, value) -> None:
        field = None
        if (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
        ):
            field = target.attr
        elif isinstance(target, ast.Name):
            field = target.id
        if field is None:
            return
        guard = _comment_annotation(
            self.src.lines, node.lineno, _GUARDED_RE
        )
        if guard is not None:
            self.guarded[field] = (guard, node.lineno)
        if isinstance(value, ast.Call):
            name = call_name(value)
            last = name.rsplit(".", 1)[-1]
            if last == "Lock":
                self.lock_kind[field] = "lock"
            elif last == "RLock":
                self.lock_kind[field] = "rlock"
            elif last == "Condition":
                self.lock_kind[field] = "lock"  # Condition wraps a Lock
                if value.args:
                    inner = _final_name(value.args[0])
                    if inner is not None:
                        self.alias[field] = inner
                        self.lock_kind.setdefault(inner, "lock")

    # -------------------------------------------------- acquire-set closure

    def compute_acquires(self) -> None:
        """Which canonical locks each function may take (via ``with``),
        closed transitively over same-class method calls."""
        funcs = [
            n for n in ast.walk(self.src.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        direct: Dict[ast.AST, Set[str]] = {}
        calls: Dict[ast.AST, Set[str]] = {}
        for fn in funcs:
            acq: Set[str] = set()
            called: Set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.With):
                    for item in node.items:
                        ln = self.with_lock(item.context_expr)
                        if ln is not None:
                            acq.add(ln)
                elif isinstance(node, ast.Call):
                    chain = attr_chain(node.func)
                    if (
                        chain
                        and len(chain) == 3
                        and chain[0] == "self"
                        and chain[2] == "acquire"
                        and self.canonical(chain[1]) in self.lock_kind
                    ):
                        acq.add(self.canonical(chain[1]))
                    if chain and chain[:1] == ["self"] and len(chain) == 2:
                        called.add(chain[1])
                elif (
                    isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                ):
                    # property loads count as calls to their getter
                    called.add(node.attr)
            direct[fn] = acq
            calls[fn] = called
        name_map: Dict[str, List[ast.AST]] = {}
        for cls, ms in self.methods.items():
            for mname, mnode in ms.items():
                name_map.setdefault(mname, []).append(mnode)
        changed = True
        while changed:
            changed = False
            for fn in funcs:
                acq = direct[fn]
                for callee_name in calls[fn]:
                    for callee in name_map.get(callee_name, []):
                        extra = direct.get(callee, set()) - acq
                        if extra:
                            acq |= extra
                            changed = True
        self.acquires = direct

    def with_lock(self, ctx_expr) -> Optional[str]:
        """Canonical lock name acquired by ``with <expr>:`` when the
        expression's attribute chain ends in a known lock name."""
        name = _final_name(ctx_expr)
        if name is None:
            return None
        canon = self.canonical(name)
        if canon in self.lock_kind or name in self.lock_kind:
            return canon
        return None


class _FunctionWalker:
    def __init__(self, mod: _ModuleLocks, fn, cls_name: Optional[str],
                 diags: List[Diagnostic]):
        self.mod = mod
        self.fn = fn
        self.cls = cls_name
        self.diags = diags
        held: Set[str] = set()
        req = mod.requires.get(fn)
        if req is not None:
            held.add(mod.canonical(req))
        self.exempt_fields = fn.name in ("__init__", "__new__", "__del__")
        self.walk_body(fn.body, held)

    def report(self, id_: str, node, msg: str) -> None:
        self.diags.append(
            Diagnostic(self.mod.src.rel, node.lineno, id_, msg)
        )

    def walk_body(self, stmts, held: Set[str]) -> None:
        for s in stmts:
            self.stmt(s, held)

    def stmt(self, node, held: Set[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested defs are separate functions
        if isinstance(node, ast.With):
            inner = set(held)
            for item in node.items:
                ln = self.mod.with_lock(item.context_expr)
                if ln is not None:
                    if (
                        ln in held
                        and self.mod.lock_kind.get(ln) != "rlock"
                    ):
                        self.report(
                            "GM202", node,
                            f"re-acquiring non-reentrant lock {ln!r} "
                            "already held here — self-deadlock",
                        )
                    inner.add(ln)
                else:
                    self.expr(item.context_expr, held)
            self.walk_body(node.body, inner)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                self.stmt(child, held)
            elif isinstance(child, ast.expr):
                self.expr(child, held)

    # ------------------------------------------------------------------ expr

    def expr(self, node, held: Set[str]) -> None:
        for n in ast.walk(node):
            if isinstance(n, ast.Attribute):
                self.check_field(n, held)
            elif isinstance(n, ast.Call):
                self.check_call(n, held)

    def check_field(self, node: ast.Attribute, held: Set[str]) -> None:
        info = self.mod.guarded.get(node.attr)
        if info is None or self.exempt_fields:
            return
        lock, decl_line = info
        if node.lineno == decl_line:
            return  # the declaring assignment itself
        if self.mod.canonical(lock) in held:
            return
        self.report(
            "GM201", node,
            f"field {node.attr!r} is guarded-by {lock!r} but accessed "
            "without it held",
        )

    def check_call(self, node: ast.Call, held: Set[str]) -> None:
        name = call_name(node)
        chain = attr_chain(node.func)
        # GM202/GM204 through same-class calls and property loads are
        # handled via acquire/requires sets:
        if chain and chain[:1] == ["self"] and len(chain) == 2:
            self._check_self_call(node, chain[1], held)
        if not held:
            return
        # ---- GM203: blocking while holding any lock
        if name in _BLOCKING_SIMPLE:
            self.report(
                "GM203", node,
                f"blocking call {name}() while holding a lock",
            )
            return
        if isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            recv = _final_name(node.func.value)
            if attr == "get" and recv and _QUEUEISH_RE.search(recv):
                self.report(
                    "GM203", node,
                    f"queue get on {recv!r} while holding a lock",
                )
            elif attr in _SOCKET_METHODS and recv not in ("requests",):
                self.report(
                    "GM203", node,
                    f"socket I/O .{attr}() while holding a lock",
                )
            elif attr == "result":
                self.report(
                    "GM203", node,
                    "future .result() while holding a lock",
                )
            elif attr == "join" and recv and _THREADISH_RE.search(recv):
                self.report(
                    "GM203", node,
                    f"thread join on {recv!r} while holding a lock",
                )
            elif attr == "wait":
                # Condition.wait releases the lock it wraps — only an
                # Event-style wait blocks with the lock held.
                canon = self.mod.with_lock(node.func.value)
                if canon is None:
                    self.report(
                        "GM203", node,
                        "event wait while holding a lock (a Condition "
                        "over the lock would release it)",
                    )

    def _check_self_call(self, node, mname: str, held: Set[str]) -> None:
        if self.cls is None:
            return
        callee = self.mod.methods.get(self.cls, {}).get(mname)
        if callee is None:
            return
        req = self.mod.requires.get(callee)
        if req is not None and self.mod.canonical(req) not in held:
            self.report(
                "GM204", node,
                f"call to {mname}() which requires-lock {req!r} "
                "without holding it",
            )
        if held:
            for ln in self.mod.acquires.get(callee, set()):
                if ln in held and self.mod.lock_kind.get(ln) != "rlock":
                    self.report(
                        "GM202", node,
                        f"call to {mname}() acquires non-reentrant "
                        f"lock {ln!r} already held here — deadlock",
                    )


def _walk_functions(mod: _ModuleLocks, diags: List[Diagnostic]) -> None:
    def visit(body, cls_name):
        for node in body:
            if isinstance(node, ast.ClassDef):
                visit(node.body, node.name)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _FunctionWalker(mod, node, cls_name, diags)
                visit(node.body, cls_name)

    visit(mod.src.tree.body, None)


#: Callback funnels that do NOT propagate lock reach to the registered
#: handler: a handler that merely SPAWNS a locking function runs it on
#: another thread (Thread/Timer targets), which cannot deadlock the
#: interrupted main thread.
_HANDLER_SAFE_VIAS = frozenset({"Thread", "Timer"})


def _direct_acquires(mod: _ModuleLocks, fn_node) -> Set[str]:
    """Locks ``fn_node`` acquires IN ITS OWN BODY (``with`` blocks and
    explicit ``.acquire()``), nested defs excluded. GM205 must not use
    the module inventory's transitively-closed acquire sets: that
    closure counts every ``self.x`` mention as a call, so a handler
    merely passing a locking method as a Thread target would be marked
    — the cross-module call graph (which knows callback funnels) does
    the closing instead."""
    from gamesmanmpi_tpu.analysis.project import walk_scoped

    acq: Set[str] = set()
    for node in walk_scoped(fn_node):
        if isinstance(node, ast.With):
            for item in node.items:
                ln = mod.with_lock(item.context_expr)
                if ln is not None:
                    acq.add(ln)
        elif isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if (
                chain
                and chain[-1] == "acquire"
                and len(chain) >= 2
                and mod.canonical(chain[-2]) in mod.lock_kind
            ):
                acq.add(mod.canonical(chain[-2]))
    return acq


def _signal_handler_findings(project: Project) -> List[Diagnostic]:
    """GM205: whole-program — every function registered via
    ``signal.signal(sig, handler)`` must not reach a lock acquisition
    through the call graph (see module docstring)."""
    cg = project.callgraph()
    direct: dict = {}
    lock_names: dict = {}
    for src in project.files:
        if src.tree is None:
            continue
        mod = project.module_locks(src)
        if not mod.lock_kind:
            continue
        for key in cg.by_module.get(src.rel, []):
            acq = _direct_acquires(mod, cg.functions[key].node)
            if acq:
                direct[key] = True
                lock_names[key] = sorted(acq)
    if not direct:
        return []
    reached = cg.reach(direct, exclude_vias=_HANDLER_SAFE_VIAS)

    def locks_reached(start: str) -> List[str]:
        """Names of the locks ``start`` can reach — BFS over the same
        edges reach() closed, so the finding names the actual hazard."""
        seen, queue, found = {start}, [start], set()
        while queue:
            key = queue.pop(0)
            found.update(lock_names.get(key, ()))
            for ev in cg.functions[key].events:
                if ev.via and ev.via in _HANDLER_SAFE_VIAS:
                    continue
                if ev.callee is not None and ev.callee in reached \
                        and ev.callee not in seen:
                    seen.add(ev.callee)
                    queue.append(ev.callee)
        return sorted(found)

    diags: List[Diagnostic] = []
    for fn in cg.functions.values():
        for ev in fn.events:
            # Callback edges into signal.signal: the handler argument.
            if ev.via != "signal" or ev.callee is None:
                continue
            if ev.callee in reached:
                locks = ", ".join(locks_reached(ev.callee)) or "a lock"
                handler = cg.functions[ev.callee].qualname
                diags.append(Diagnostic(
                    fn.rel, ev.lineno, "GM205",
                    f"signal handler {handler!r} can reach acquisition "
                    f"of {locks} — a handler interrupting a thread that "
                    "holds it deadlocks; keep handlers lock-free (set a "
                    "flag, write a pipe, signal a child)",
                ))
    return diags


def check(project: Project) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    for src in project.files:
        if src.tree is None:
            continue
        # Shared with the GM6xx collective-under-lock checker via the
        # project cache: one lock inventory per module per run.
        mod = project.module_locks(src)
        if not mod.guarded and not mod.requires and not mod.lock_kind:
            continue
        _walk_functions(mod, diags)
    diags.extend(_signal_handler_findings(project))
    return diags
