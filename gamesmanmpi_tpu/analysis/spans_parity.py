"""GM4xx (continued) — span-name registry parity (GM405).

``Span``/``trace_span`` names are the phase vocabulary of every
observability surface at once: the JSONL ``phase`` records bench.py
parses, the ``gamesman_span_seconds{span=...}`` series, the Chrome
trace events, the flight recorder's ring, and the per-level rows
``tools/obs_report.py`` folds. A span name an operator cannot look up
in docs/OBSERVABILITY.md is a phase nobody can interpret in a
post-mortem — the same drift GM402 closes for metric names, enforced
the same TWO-WAY shape as GM302/GM303 closes for env vars:

| id | finding |
|---|---|
| GM405 | a ``Span(...)``/``trace_span(...)`` name used in the codebase is missing from docs/OBSERVABILITY.md's "Span name registry" table — or a registered name is used nowhere (stale doc row); also a span name that is not statically resolvable (the registry can't be audited) |

The doc anchor is the "Span name registry" section of
docs/OBSERVABILITY.md: every table row whose first cell is a
backticked name registers one span. A project whose OBSERVABILITY.md
has no such section skips the family entirely (same opt-in shape as
the exit-code registry). Conditional names
(``Span("backward_edges" if want_edges else "backward")``) resolve to
both branches. The definition site (``obs/tracing.py``) is skipped.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from gamesmanmpi_tpu.analysis.diagnostics import Diagnostic
from gamesmanmpi_tpu.analysis.project import (
    OBSERVABILITY_MD,
    Project,
    call_name,
    const_str,
    module_string_consts,
)

#: Call names that start a span (last dotted component). ``qspan`` and
#: ``add_span`` are the query-trace twins (obs/qtrace.py): different
#: sink (per-request trace ring, not the span histogram), same registry
#: contract — a span name an operator meets in ``GET /traces`` must be
#: documented like every other.
_SPAN_CALLS = {"Span", "trace_span", "qspan", "add_span"}

_SECTION_RE = re.compile(r"^#+\s.*span name registry", re.IGNORECASE)
_ROW_RE = re.compile(r"^\|\s*`([a-z_][a-z0-9_]*)`\s*\|")


def _doc_registry(doc: str) -> Optional[Dict[str, int]]:
    """Registered span names -> 1-based doc line, or None when the doc
    has no "Span name registry" section (family opt-out)."""
    rows: Dict[str, int] = {}
    in_section = False
    found = False
    for i, line in enumerate(doc.splitlines(), 1):
        stripped = line.strip()
        if _SECTION_RE.match(stripped):
            in_section = True
            found = True
            continue
        if in_section and stripped.startswith("#"):
            in_section = False
            continue
        if in_section:
            m = _ROW_RE.match(stripped)
            if m:
                rows.setdefault(m.group(1), i)
    return rows if found else None


def _resolve_span_names(node: ast.AST, consts) -> Optional[List[str]]:
    """The statically-resolvable name(s) a span-call first argument can
    take: a literal/constant, or an IfExp whose branches both resolve
    (the mixed-mode backward span). None = not resolvable."""
    got = const_str(node, consts)
    if got is not None:
        return [got]
    if isinstance(node, ast.IfExp):
        a = _resolve_span_names(node.body, consts)
        b = _resolve_span_names(node.orelse, consts)
        if a is not None and b is not None:
            return a + b
    return None


def check(project: Project) -> List[Diagnostic]:
    registry = _doc_registry(project.observability_md)
    if registry is None:
        return []  # project without a span-name registry section
    diags: List[Diagnostic] = []
    used: Dict[str, Tuple[str, int]] = {}  # name -> first (file, line)
    for src in project.files:
        if src.tree is None or src.rel.endswith(
            ("obs/tracing.py", "obs/qtrace.py")
        ):
            continue
        consts = module_string_consts(src.tree)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            if call_name(node).rsplit(".", 1)[-1] not in _SPAN_CALLS:
                continue
            names = _resolve_span_names(node.args[0], consts)
            if names is None:
                diags.append(Diagnostic(
                    src.rel, node.lineno, "GM405",
                    "span name is not statically resolvable — use a "
                    "literal (or a conditional over literals) so the "
                    "span registry stays auditable",
                ))
                continue
            for name in names:
                used.setdefault(name, (src.rel, node.lineno))
                if name not in registry:
                    diags.append(Diagnostic(
                        src.rel, node.lineno, "GM405",
                        f"span {name!r} is used here but not registered "
                        f"in {OBSERVABILITY_MD}'s \"Span name registry\" "
                        "table",
                    ))
    for name, line in sorted(registry.items()):
        if name not in used:
            diags.append(Diagnostic(
                OBSERVABILITY_MD, line, "GM405",
                f"span {name!r} is registered in the span-name registry "
                "but no Span/trace_span call uses it — stale doc row",
            ))
    return diags
