"""Runtime lock-order witness: the dynamic half of GM2xx/GM6xx.

The static lock checkers reason about names and lexical scopes; this
module validates that model against *reality*. With
``GAMESMAN_LOCKDEP=1`` in the environment (or an explicit
:func:`install`), every ``threading.Lock`` / ``RLock`` / ``Condition``
constructed from the watched packages (``obs/``, ``serve/``,
``resilience/`` by default) is wrapped in a recording proxy. Each time
a thread acquires lock B while holding lock A, the edge ``A -> B`` is
added to a global acquisition-order graph, keyed by the locks'
construction sites (``serve/batcher.py:87``). A cycle in that graph is
a lock-order inversion — two threads interleaving those paths can
deadlock — and :func:`assert_acyclic` turns it into a test failure
with the witnessed cycle spelled out.

Wiring: ``tests/conftest.py`` installs the witness when
``GAMESMAN_LOCKDEP=1`` and asserts acyclicity at session teardown;
``tests/test_lint.py`` holds the unit tests (cycle detection, RLock
reentrancy, Condition wait/notify accounting) and an integration test
driving the real obs/serve/resilience lock users under a witness.

The proxy is Condition-compatible: ``Condition.wait`` releases the
wrapped lock through ``_release_save`` (held-state drops, correctly)
and re-acquires through ``_acquire_restore`` (edges record against
whatever the thread holds at wake-up). Reentrant RLock acquisitions
record no edges — only the 0->1 transition does.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional, Tuple

#: Construction sites are instrumented only under these path fragments
#: (posix separators) — the thread+lock packages the static checkers
#: model. Everything else gets a plain lock: zero overhead, no noise.
DEFAULT_WATCH = (
    "gamesmanmpi_tpu/obs/",
    "gamesmanmpi_tpu/serve/",
    "gamesmanmpi_tpu/resilience/",
)

_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock


class LockOrderError(AssertionError):
    """A witnessed lock-order cycle (potential deadlock)."""


class _Graph:
    """The global acquisition-order graph (thread-safe via an original,
    uninstrumented lock)."""

    def __init__(self):
        self._lock = _ORIG_LOCK()
        self.edges: Dict[str, Dict[str, str]] = {}  # a -> {b: thread}

    def add(self, a: str, b: str, thread: str) -> None:
        with self._lock:
            self.edges.setdefault(a, {}).setdefault(b, thread)

    def snapshot(self) -> List[Tuple[str, str]]:
        with self._lock:
            return sorted(
                (a, b) for a, bs in self.edges.items() for b in bs
            )

    def clear(self) -> None:
        with self._lock:
            self.edges.clear()

    def cycles(self) -> List[List[str]]:
        """Every elementary cycle reachable in the edge set (DFS with a
        color map; one representative per back edge)."""
        with self._lock:
            adj = {a: sorted(bs) for a, bs in self.edges.items()}
        WHITE, GRAY, BLACK = 0, 1, 2
        color = {n: WHITE for n in adj}
        out: List[List[str]] = []
        path: List[str] = []

        def dfs(n: str) -> None:
            color[n] = GRAY
            path.append(n)
            for m in adj.get(n, ()):
                if color.get(m, WHITE) == GRAY:
                    out.append(path[path.index(m):] + [m])
                elif color.get(m, WHITE) == WHITE:
                    color[m] = WHITE
                    dfs(m)
            path.pop()
            color[n] = BLACK

        for n in list(adj):
            if color.get(n, WHITE) == WHITE:
                dfs(n)
        return out


_GRAPH = _Graph()
_TLS = threading.local()
#: construction sites of every lock the witness instrumented this
#: session — the coverage observable (edges exist only when locks NEST,
#: which healthy single-lock designs never do).
_SITES: set = set()
_SITES_LOCK = _ORIG_LOCK()


def _held():
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []  # [(id(lock), name)] in acquire order
        _TLS.counts = {}  # id(lock) -> recursion depth
    return stack, _TLS.counts


class _LockProxy:
    """Recording wrapper around a Lock/RLock instance."""

    def __init__(self, inner, name: str):
        self._inner = inner
        self._name = name

    # ------------------------------------------------------- accounting

    def _note_acquired(self) -> None:
        stack, counts = _held()
        key = id(self)
        counts[key] = counts.get(key, 0) + 1
        if counts[key] == 1:
            me = threading.current_thread().name
            for _, held_name in stack:
                if held_name != self._name:
                    _GRAPH.add(held_name, self._name, me)
            stack.append((key, self._name))

    def _note_released(self, full: bool = False) -> None:
        stack, counts = _held()
        key = id(self)
        if key not in counts:
            return  # released by a thread that never noted the acquire
        counts[key] = 0 if full else counts[key] - 1
        if counts[key] <= 0:
            counts.pop(key, None)
            for i in range(len(stack) - 1, -1, -1):
                if stack[i][0] == key:
                    del stack[i]
                    break

    # ------------------------------------------------------- lock API

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._note_acquired()
        return ok

    def release(self) -> None:
        self._note_released()
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __getattr__(self, name: str):
        # Condition compatibility: expose _release_save /
        # _acquire_restore / _is_owned ONLY when the inner lock has
        # them (RLock), wrapped so wait()'s full release and the
        # wake-up re-acquire keep the held-state honest. The saved
        # state carries OUR recursion depth alongside the inner
        # lock's, so waiting on a Condition over a reentrantly-held
        # RLock restores the proxy to the true depth (not 1) and
        # later releases keep the accounting exact.
        if name == "_release_save":
            inner = self._inner._release_save

            def _release_save():
                _, counts = _held()
                depth = counts.get(id(self), 0)
                self._note_released(full=True)
                return (inner(), depth)

            return _release_save
        if name == "_acquire_restore":
            inner = self._inner._acquire_restore

            def _acquire_restore(state):
                inner_state, depth = state
                inner(inner_state)
                self._note_acquired()
                if depth > 1:
                    _held()[1][id(self)] = depth

            return _acquire_restore
        return getattr(self._inner, name)


class _Installed:
    watch: tuple = DEFAULT_WATCH
    active: bool = False


def _caller_site() -> Optional[str]:
    """repo-relative construction site of the first frame outside this
    module and the threading machinery."""
    f = sys._getframe(2)
    while f is not None:
        fname = f.f_code.co_filename.replace(os.sep, "/")
        if "analysis/lockdep" not in fname and "/threading" not in fname:
            return f"{fname}:{f.f_lineno}"
        f = f.f_back
    return None


def _should_instrument(site: Optional[str]) -> bool:
    return site is not None and any(w in site for w in _Installed.watch)


def _short(site: str) -> str:
    for w in _Installed.watch:
        i = site.find(w)
        if i >= 0:
            return site[i:]
    return site.rsplit("/", 2)[-1]


#: per-construction-site instance counters: distinct locks born at the
#: same line (a loop, one per object) must keep distinct graph nodes,
#: or an inversion BETWEEN them would merge into one self-edge-free
#: name and never be witnessed.
_SITE_SEQ: dict = {}


def _note_site(site: str) -> str:
    with _SITES_LOCK:
        n = _SITE_SEQ.get(site, 0)
        _SITE_SEQ[site] = n + 1
        name = site if n == 0 else f"{site}#{n}"
        _SITES.add(name)
        return name


def _make_lock():
    site = _caller_site()
    if not _Installed.active or not _should_instrument(site):
        return _ORIG_LOCK()
    return _LockProxy(_ORIG_LOCK(), _note_site(_short(site)))


def _make_rlock():
    site = _caller_site()
    if not _Installed.active or not _should_instrument(site):
        return _ORIG_RLOCK()
    return _LockProxy(_ORIG_RLOCK(), _note_site(_short(site)))


def install(watch=None) -> None:
    """Patch the threading lock factories (idempotent). ``Condition``
    needs no patching: built over a patched lock it routes every
    acquire/release through the proxy, and a bare ``Condition()``
    constructs its RLock through the patched factory."""
    if watch is not None:
        _Installed.watch = tuple(watch)
    if _Installed.active:
        return
    _Installed.active = True
    threading.Lock = _make_lock
    threading.RLock = _make_rlock


def uninstall() -> None:
    if not _Installed.active:
        return
    threading.Lock = _ORIG_LOCK
    threading.RLock = _ORIG_RLOCK
    _Installed.active = False
    _Installed.watch = DEFAULT_WATCH


def reset() -> None:
    _GRAPH.clear()
    with _SITES_LOCK:
        _SITES.clear()
        _SITE_SEQ.clear()


def edges() -> List[Tuple[str, str]]:
    """Witnessed (held, acquired) pairs, sorted."""
    return _GRAPH.snapshot()


def instrumented() -> List[str]:
    """Construction sites of every lock wrapped this session."""
    with _SITES_LOCK:
        return sorted(_SITES)


def cycles() -> List[List[str]]:
    return _GRAPH.cycles()


def assert_acyclic() -> None:
    cy = _GRAPH.cycles()
    if cy:
        lines = [" -> ".join(c) for c in cy]
        raise LockOrderError(
            "lock-order cycle(s) witnessed at runtime (deadlock "
            "potential):\n  " + "\n  ".join(lines)
        )


def enabled_by_env() -> bool:
    # Deliberately a raw default-free read: this runs at conftest import,
    # before any package code, and the knob is documented in CONFIG.md.
    from gamesmanmpi_tpu.utils.env import env_str

    return env_str("GAMESMAN_LOCKDEP", "0") == "1"


class witness:
    """Context manager for tests: install + clean slate on entry,
    acyclicity assertion (optional) on exit.

    Nestable over a session-wide install (GAMESMAN_LOCKDEP=1 via
    conftest): the prior installation state, watch list, edge graph,
    and site registry are snapshotted on entry and restored on exit —
    a scoped witness must never blind the session witness for the
    tests that run after it.

    >>> with lockdep.witness():
    ...     exercise_locks()
    """

    def __init__(self, watch=None, check: bool = True):
        self.watch = watch
        self.check = check

    def __enter__(self):
        self._was_active = _Installed.active
        self._prev_watch = _Installed.watch
        with _GRAPH._lock:
            self._prev_edges = {a: dict(bs)
                                for a, bs in _GRAPH.edges.items()}
        with _SITES_LOCK:
            self._prev_sites = set(_SITES)
            self._prev_seq = dict(_SITE_SEQ)
        install(self.watch)
        reset()
        return sys.modules[__name__]

    def __exit__(self, exc_type, exc, tb) -> None:
        try:
            if exc_type is None and self.check:
                assert_acyclic()
        finally:
            if not self._was_active:
                uninstall()
            _Installed.watch = self._prev_watch
            with _GRAPH._lock:
                _GRAPH.edges.clear()
                _GRAPH.edges.update(self._prev_edges)
            with _SITES_LOCK:
                _SITES.clear()
                _SITES.update(self._prev_sites)
                _SITE_SEQ.clear()
                _SITE_SEQ.update(self._prev_seq)
