"""Runtime wire-conformance witness: the dynamic half of GM10xx.

The static wire checkers (analysis/wire.py) extract each handler
class's contract — the status codes its dispatch can emit and the
response-header rules it declares via ``# wire:`` — from source. This
module validates that model against *live responses*. With
``GAMESMAN_WIRECHECK=1`` in the environment (or an explicit
:func:`install`), the ``BaseHTTPRequestHandler`` send path is wrapped:
every response a watched handler class finishes (``end_headers``) is
checked against the statically extracted contract, and a status code
outside the extracted set, a 503/429 shed without ``Retry-After``, an
``ETag`` without ``Cache-Control``, or a swallowed inbound
``traceparent`` is recorded as a violation. :func:`assert_conformant`
turns the session's violations into a test failure.

Wiring: ``tests/conftest.py`` installs the witness when
``GAMESMAN_WIRECHECK=1`` and asserts conformance at session teardown
(exit 3 on violations, like lockdep); ``tests/test_lint.py`` holds the
unit tests — a live server driven under a scoped :class:`witness`, and
a violation test against a deliberately non-conformant fixture
handler.

Contracts are loaded by re-parsing the four fleet server modules with
:func:`analysis.wire.extract_server_classes` — a pure AST pass, no
project load, so install costs milliseconds at conftest import. Codes
the stdlib ``http.server`` machinery emits on its own (malformed
request line, oversized headers: ``wire.IMPLICIT_CODES``) are always
allowed.
"""

from __future__ import annotations

import ast
import pathlib
import sys
import threading
from http.server import BaseHTTPRequestHandler
from typing import Dict, List, Optional, Set

from gamesmanmpi_tpu.analysis import wire

#: The fleet server modules whose handler classes are watched, relative
#: to the package root's parent (the repo layout the witness runs in).
WATCHED_MODULES = (
    "gamesmanmpi_tpu/serve/server.py",
    "gamesmanmpi_tpu/serve/supervisor.py",
    "gamesmanmpi_tpu/registry/server.py",
    "gamesmanmpi_tpu/obs/status.py",
)


class WireConformanceError(AssertionError):
    """A live response fell outside the statically extracted contract."""


class Contract:
    """What one handler class is allowed to do on the wire."""

    def __init__(self, codes: Optional[Set[int]], rules: Set[str]):
        #: allowed status codes; None = the static extractor saw a
        #: computed code (open set) and code checking is skipped.
        self.codes = codes
        self.rules = set(rules)


def load_repo_contracts() -> Dict[str, Contract]:
    """Class-name -> :class:`Contract` for every watched fleet module,
    by pure AST extraction (shared with gamesman-lint)."""
    root = pathlib.Path(wire.__file__).resolve().parents[2]
    out: Dict[str, Contract] = {}
    for rel in WATCHED_MODULES:
        path = root / rel
        try:
            text = path.read_text(encoding="utf-8", errors="replace")
            tree = ast.parse(text)
        except (OSError, SyntaxError):
            continue
        for sc in wire.extract_server_classes(
            tree, text.splitlines(), rel
        ):
            out[sc.name] = Contract(
                None if sc.open_codes else set(sc.codes),
                sc.rules & wire.HANDLER_RULES,
            )
    return out


class _Installed:
    active: bool = False
    contracts: Optional[Dict[str, Contract]] = None


_ORIG_SEND_RESPONSE = BaseHTTPRequestHandler.send_response
_ORIG_SEND_HEADER = BaseHTTPRequestHandler.send_header
_ORIG_END_HEADERS = BaseHTTPRequestHandler.end_headers

_LOCK = threading.Lock()
_VIOLATIONS: List[str] = []
#: handler class names that answered at least one checked response —
#: the coverage observable (a clean run over zero responses proves
#: nothing).
_CHECKED: Set[str] = set()


def _record(msg: str) -> None:
    with _LOCK:
        _VIOLATIONS.append(msg)


def _send_response(self, code, message=None):
    self._wirecheck_code = int(code)
    self._wirecheck_headers = set()
    return _ORIG_SEND_RESPONSE(self, code, message)


def _send_header(self, keyword, value):
    pending = getattr(self, "_wirecheck_headers", None)
    if pending is not None:
        pending.add(str(keyword).lower())
    return _ORIG_SEND_HEADER(self, keyword, value)


def _end_headers(self):
    try:
        _validate(self)
    finally:
        self._wirecheck_code = None
        self._wirecheck_headers = None
    return _ORIG_END_HEADERS(self)


def _validate(handler) -> None:
    contracts = _Installed.contracts or {}
    cname = type(handler).__name__
    contract = contracts.get(cname)
    code = getattr(handler, "_wirecheck_code", None)
    headers = getattr(handler, "_wirecheck_headers", None)
    if contract is None or code is None or headers is None:
        return
    with _LOCK:
        _CHECKED.add(cname)
    where = f"{cname} {getattr(handler, 'path', '?')}"
    if contract.codes is not None and code not in contract.codes \
            and code not in wire.IMPLICIT_CODES:
        _record(
            f"{where}: live status {code} is outside the statically "
            f"extracted set {sorted(contract.codes)}"
        )
    for rule, shed in (("503-retry-after", 503),
                       ("429-retry-after", 429)):
        if rule in contract.rules and code == shed \
                and "retry-after" not in headers:
            _record(
                f"{where}: {shed} shed without Retry-After "
                f"(class promises {rule})"
            )
    if "etag-cache-control" in contract.rules and "etag" in headers \
            and "cache-control" not in headers:
        _record(f"{where}: ETag without Cache-Control")
    if "echo-traceparent" in contract.rules:
        try:
            inbound = handler.headers.get("traceparent")
        except AttributeError:
            inbound = None
        if inbound and "traceparent" not in headers:
            _record(
                f"{where}: inbound traceparent was not echoed "
                f"(class promises echo-traceparent)"
            )


def install(contracts: Optional[Dict[str, Contract]] = None) -> None:
    """Wrap the handler send path (idempotent). ``contracts`` overrides
    the repo-extracted map — the violation tests' hook."""
    if contracts is not None:
        _Installed.contracts = dict(contracts)
    elif _Installed.contracts is None:
        _Installed.contracts = load_repo_contracts()
    if _Installed.active:
        return
    _Installed.active = True
    BaseHTTPRequestHandler.send_response = _send_response
    BaseHTTPRequestHandler.send_header = _send_header
    BaseHTTPRequestHandler.end_headers = _end_headers


def uninstall() -> None:
    if not _Installed.active:
        return
    BaseHTTPRequestHandler.send_response = _ORIG_SEND_RESPONSE
    BaseHTTPRequestHandler.send_header = _ORIG_SEND_HEADER
    BaseHTTPRequestHandler.end_headers = _ORIG_END_HEADERS
    _Installed.active = False
    _Installed.contracts = None


def reset() -> None:
    with _LOCK:
        _VIOLATIONS.clear()
        _CHECKED.clear()


def violations() -> List[str]:
    with _LOCK:
        return list(_VIOLATIONS)


def checked_classes() -> List[str]:
    """Handler classes that answered at least one checked response."""
    with _LOCK:
        return sorted(_CHECKED)


def assert_conformant() -> None:
    vio = violations()
    if vio:
        raise WireConformanceError(
            "live response(s) outside the static wire contract:\n  "
            + "\n  ".join(vio)
        )


def enabled_by_env() -> bool:
    # Raw default-free read, like lockdep: this runs at conftest
    # import; the knob is documented in CONFIG.md.
    from gamesmanmpi_tpu.utils.env import env_str

    return env_str("GAMESMAN_WIRECHECK", "0") == "1"


class witness:
    """Context manager for tests: install + clean slate on entry,
    conformance assertion (optional) on exit.

    Nestable over a session-wide install (GAMESMAN_WIRECHECK=1 via
    conftest): prior installation state, contract map, and recorded
    violations are snapshotted on entry and restored on exit, so a
    scoped witness never blinds the session witness.

    >>> with wirecheck.witness():
    ...     drive_live_server()
    """

    def __init__(self, contracts: Optional[Dict[str, Contract]] = None,
                 check: bool = True):
        self.contracts = contracts
        self.check = check

    def __enter__(self):
        self._was_active = _Installed.active
        self._prev_contracts = _Installed.contracts
        with _LOCK:
            self._prev_violations = list(_VIOLATIONS)
            self._prev_checked = set(_CHECKED)
        if self.contracts is not None:
            _Installed.contracts = dict(self.contracts)
        install()
        reset()
        return sys.modules[__name__]

    def __exit__(self, exc_type, exc, tb) -> None:
        try:
            if exc_type is None and self.check:
                assert_conformant()
        finally:
            if not self._was_active:
                uninstall()
            else:
                _Installed.contracts = self._prev_contracts
            with _LOCK:
                _VIOLATIONS.clear()
                _VIOLATIONS.extend(self._prev_violations)
                _CHECKED.clear()
                _CHECKED.update(self._prev_checked)
