"""GM7xx — resource lifecycle & fork safety.

The serve supervisor (PR 7) and the distributed harness (PR 6) live and
die by two disciplines no generic linter enforces:

* every acquired OS resource — file handle, mmap, socket, subprocess,
  thread — must have its release **guaranteed on all paths**: a ``with``
  block, a ``try/finally``, ownership transfer (returned, passed to a
  tracking registry/constructor/container), or a ``self.`` field the
  module demonstrably releases somewhere. A bare ``f = open(...); ...;
  f.close()`` leaks on the first exception between the two — exactly the
  fd/zombie creep that kills a fleet after days, not minutes;
* in a module that forks (``os.fork``), nothing may start threads or
  take locks earlier in the forking function: the child inherits the
  lock state of a thread that no longer exists (the classic
  fork-after-threads deadlock the supervisor's fork spawn mode dodges
  by forking before any jax/thread activity).

| id | finding |
|---|---|
| GM701 | acquired resource whose release is not guaranteed on all paths |
| GM702 | thread started / lock created before ``os.fork()`` in the same function |

Daemon threads are exempt from GM701 (never joined by design — they die
with the process). Analysis is per-function and name-based, same spirit
as the rest of the suite.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from gamesmanmpi_tpu.analysis.diagnostics import Diagnostic
from gamesmanmpi_tpu.analysis.project import (
    Project,
    SourceFile,
    attr_chain,
    call_name,
    from_import_map,
    walk_scoped as _walk_scoped,
)

#: dotted-name (or bare-name) acquisition calls -> (kind, release attrs)
_ACQUIRE = {
    "open": ("file", {"close"}),
    "io.open": ("file", {"close"}),
    "os.fdopen": ("file", {"close"}),
    "gzip.open": ("file", {"close"}),
    "mmap.mmap": ("mmap", {"close"}),
    "socket.socket": ("socket", {"close"}),
    "socket.create_connection": ("socket", {"close"}),
    "subprocess.Popen": ("process",
                         {"wait", "communicate", "kill", "terminate"}),
    "threading.Thread": ("thread", {"join"}),
}

#: All release attribute names, for the tracked-self-field escape.
_ALL_RELEASES = {"close", "join", "wait", "communicate", "kill",
                 "terminate", "stop", "shutdown", "unlink", "release"}

#: Thread/lock factories that must not run before a fork point.
_PRE_FORK_HAZARDS = {"Thread", "Lock", "RLock", "Condition", "Semaphore",
                     "BoundedSemaphore", "Event", "Timer"}


def _acquire_kind(node: ast.Call, from_map: Optional[dict] = None):
    """(kind, releases) when this call acquires a resource, else None."""
    name = call_name(node)
    if from_map and name and "." not in name and name != "open":
        name = from_map.get(name, name)
    hit = _ACQUIRE.get(name)
    if hit is None and "." in name:
        # tolerate aliased module roots ("sp.Popen", "thr.Thread")
        tail = name.rsplit(".", 1)[-1]
        for dotted, info in _ACQUIRE.items():
            if "." in dotted and dotted.rsplit(".", 1)[-1] == tail \
                    and tail in ("Popen", "Thread", "mmap"):
                hit = info
                break
    if hit is None:
        return None
    if hit[0] == "thread" and _is_daemon_thread(node):
        return None
    if hit[0] == "file" and not _is_write_or_read_handle(node):
        return None
    return hit


def _is_daemon_thread(node: ast.Call) -> bool:
    for kw in node.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
            return bool(kw.value.value)
    return False


def _is_write_or_read_handle(node: ast.Call) -> bool:
    """open() in any mode counts; this hook exists so future tuning can
    exempt modes centrally."""
    return True


class _FnScan:
    """One function's resource-acquisition audit."""

    def __init__(self, src: SourceFile, fn, self_released: Set[str],
                 diags: List[Diagnostic], from_map: dict):
        self.src = src
        self.fn = fn
        self.self_released = self_released
        self.diags = diags
        self.from_map = from_map
        #: child node id -> parent node, for this function only (built
        #: once here — no module-global id()-keyed cache to go stale
        #: across runs when node ids are recycled)
        self.parents: dict = {}
        for node in ast.walk(fn):
            for child in ast.iter_child_nodes(node):
                self.parents[id(child)] = node
        #: locals released inside some finally body / with-as binding
        self.finally_released: Set[str] = set()
        self.with_bound: Set[str] = set()
        self._collect_guards(fn)
        self._scan(fn)

    # ------------------------------------------------------------- guards

    def _collect_guards(self, fn) -> None:
        for node in _walk_scoped(fn):
            if isinstance(node, ast.Try):
                for name in self._released_names(node.finalbody):
                    self.finally_released.add(name)
            elif isinstance(node, ast.With):
                for item in node.items:
                    if isinstance(item.optional_vars, ast.Name):
                        self.with_bound.add(item.optional_vars.id)

    def _released_names(self, stmts) -> Set[str]:
        """Local names released (or handed off) inside ``stmts``."""
        out: Set[str] = set()
        for s in stmts:
            for node in ast.walk(s):
                if not isinstance(node, ast.Call):
                    continue
                chain = attr_chain(node.func)
                if chain and len(chain) >= 2 \
                        and chain[-1] in _ALL_RELEASES:
                    out.add(chain[0])
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        out.add(arg.id)
        return out

    # --------------------------------------------------------------- scan

    def _scan(self, fn) -> None:
        for node in _walk_scoped(fn):
            if isinstance(node, ast.Call):
                hit = _acquire_kind(node, self.from_map)
                if hit is not None:
                    self._judge(node, *hit)

    def _judge(self, call: ast.Call, kind: str, releases: Set[str]):
        ctx = self._context_of(call)
        if ctx == "ok":
            return
        if ctx is None:
            self.diags.append(Diagnostic(
                self.src.rel, call.lineno, "GM701",
                f"{kind} acquired and discarded — release is not "
                "guaranteed on any path (use `with`, try/finally, or "
                "a tracked registry)",
            ))
            return
        # ctx is the bound name (local or "self.X")
        if ctx.startswith("self."):
            field = ctx[len("self."):]
            if field in self.self_released:
                return
            self.diags.append(Diagnostic(
                self.src.rel, call.lineno, "GM701",
                f"{kind} stored on {ctx} but nothing in this module "
                f"ever releases it ({'/'.join(sorted(releases))})",
            ))
            return
        if ctx in self.finally_released or ctx in self.with_bound:
            return
        if self._escapes(ctx, call):
            return
        self.diags.append(Diagnostic(
            self.src.rel, call.lineno, "GM701",
            f"{kind} bound to {ctx!r} but its release "
            f"({'/'.join(sorted(releases))}) is not guaranteed on all "
            "paths — use `with` or try/finally",
        ))

    def _context_of(self, call: ast.Call) -> Optional[str]:
        """How the acquired value is consumed: "ok" (with/return/
        argument/yield), a binding name, or None (discarded)."""
        node: ast.AST = call
        parent = self.parents.get(id(node))
        # unwrap await: `f = await aopen(...)` binds the awaited value
        while isinstance(parent, (ast.Await,)):
            node, parent = parent, self.parents.get(id(parent))
        if isinstance(parent, ast.withitem):
            return "ok"
        if isinstance(parent, (ast.Return, ast.Yield, ast.YieldFrom)):
            return "ok"
        if isinstance(parent, ast.Call) and parent is not call:
            return "ok"  # argument: ownership transferred
        if isinstance(parent, (ast.List, ast.Tuple, ast.Dict, ast.Set)):
            return "ok"  # stored in a container literal
        if isinstance(parent, (ast.Assign, ast.AnnAssign, ast.NamedExpr)):
            target = (parent.targets[0]
                      if isinstance(parent, ast.Assign)
                      else parent.target)
            if isinstance(target, ast.Name):
                return target.id
            if (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
            ):
                return f"self.{target.attr}"
            return "ok"  # subscript/other attribute: escaped to a registry
        if isinstance(parent, ast.Expr):
            # bare `Thread(...).start()`-style chains land here via the
            # Attribute parent below; a truly bare acquisition is a leak
            return None
        if isinstance(parent, ast.Attribute):
            # e.g. open(p).read() — acquired, used, dropped: leak
            return None
        return None

    def _escapes(self, name: str, call: ast.Call) -> bool:
        """True when the named local is handed off within this function:
        passed as an argument, returned, yielded, re-stored onto
        self/container, or re-bound into a with."""
        for node in _walk_scoped(self.fn):
            if isinstance(node, ast.Call):
                for arg in list(node.args) + [k.value for k in
                                              node.keywords]:
                    if _value_carries(arg, name):
                        return True
            elif isinstance(node, (ast.Return, ast.Yield)):
                if node.value is not None \
                        and _value_carries(node.value, name):
                    return True
            elif isinstance(node, ast.Assign):
                # the VALUE must be the resource itself (or a container
                # holding it) — `x = f.read()` does not hand f off
                if _value_carries(node.value, name) and not (
                    len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == name
                ):
                    return True
            elif isinstance(node, ast.With):
                for item in node.items:
                    for sub in ast.walk(item.context_expr):
                        if isinstance(sub, ast.Name) and sub.id == name:
                            return True
        return False


def _value_carries(expr: ast.AST, name: str) -> bool:
    """True when evaluating ``expr`` yields the named resource itself:
    the bare name, or a container literal holding it (``(proc, t0)``).
    ``proc.pid`` / ``f.read()`` do NOT carry the resource."""
    if isinstance(expr, ast.Name):
        return expr.id == name
    if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
        return any(_value_carries(e, name) for e in expr.elts)
    if isinstance(expr, ast.Dict):
        return any(v is not None and _value_carries(v, name)
                   for v in expr.values)
    return False


def _self_released_fields(src: SourceFile) -> Set[str]:
    """Attribute names on which some method in this module calls a
    release (``self._sock.close()``, ``w._thread.join()``, ...) or
    passes to a closer (``_close_readers(self._readers)``)."""
    out: Set[str] = set()
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        chain = attr_chain(node.func)
        if chain and len(chain) >= 3 and chain[-1] in _ALL_RELEASES:
            out.add(chain[-2])
        for arg in node.args:
            a = attr_chain(arg)
            if a and len(a) >= 2:
                out.add(a[-1])
    return out


def _check_fork_ordering(src: SourceFile, diags: List[Diagnostic],
                         from_map: dict) -> None:
    """GM702 within each function that calls os.fork()."""
    funcs = [n for n in ast.walk(src.tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for fn in funcs:
        fork_lines = [
            node.lineno for node in _walk_scoped(fn)
            if isinstance(node, ast.Call)
            and call_name(node).endswith("os.fork")
        ]
        if not fork_lines:
            continue
        fork_line = min(fork_lines)
        for node in _walk_scoped(fn):
            if not isinstance(node, ast.Call) \
                    or node.lineno >= fork_line:
                continue
            name = call_name(node)
            if name and "." not in name:
                name = from_map.get(name, name)
            tail = name.rsplit(".", 1)[-1]
            if tail in _PRE_FORK_HAZARDS and "." in name:
                diags.append(Diagnostic(
                    src.rel, node.lineno, "GM702",
                    f"{tail} created before os.fork() in the same "
                    "function — the child inherits lock/thread state "
                    "that no longer has an owner",
                ))
            elif tail == "start" and len(attr_chain(node.func) or []) >= 2:
                recv = (attr_chain(node.func) or ["?"])[-2]
                if "thread" in recv.lower():
                    diags.append(Diagnostic(
                        src.rel, node.lineno, "GM702",
                        f"thread {recv!r} started before os.fork() in "
                        "the same function — fork-unsafe",
                    ))


def check(project: Project) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    for src in project.files:
        if src.tree is None:
            continue
        self_released = _self_released_fields(src)
        from_map = from_import_map(src.tree)
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _FnScan(src, node, self_released, diags, from_map)
        _check_fork_ordering(src, diags, from_map)
    return diags
