"""Checker orchestration: one pass over the project, one result.

``run_project`` loads the project index once, runs every checker family
over it, then applies the two escape hatches in order: inline
``# lint: disable=...`` suppressions drop a finding entirely (the
author vouched for that site), the baseline file demotes a finding from
*new* (fails the run) to *baselined* (reported, tolerated). The runner
never imports the code under analysis — linting kernel modules must not
grab an accelerator.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from gamesmanmpi_tpu.analysis import (
    atomic_write,
    env_parity,
    exit_parity,
    faults_parity,
    gamespec,
    jax_tracing,
    lifecycle,
    locks,
    metrics_parity,
    spans_parity,
    spmd,
    wire,
)
from gamesmanmpi_tpu.analysis.diagnostics import (
    Diagnostic,
    fingerprint,
    is_suppressed,
    load_baseline,
    split_by_baseline,
)
from gamesmanmpi_tpu.analysis.project import Project, load_project

#: Checker families in reporting order. Each is ``check(project) ->
#: [Diagnostic]``; parse failures (GM001) come from the loader itself.
CHECKERS = (
    jax_tracing.check,
    locks.check,
    env_parity.check,
    metrics_parity.check,
    spans_parity.check,
    faults_parity.check,
    exit_parity.check,
    spmd.check,
    lifecycle.check,
    atomic_write.check,
    gamespec.check,
    wire.check,
)


@dataclasses.dataclass
class LintResult:
    """Findings partitioned by disposition.

    * ``new`` — fail the run (exit 1);
    * ``baselined`` — matched an accepted-findings entry;
    * ``suppressed`` — silenced by an inline directive;
    * ``fingerprints`` — fingerprint per non-suppressed finding, the
      material ``--update-baseline`` writes back.
    """

    new: List[Diagnostic]
    baselined: List[Diagnostic]
    suppressed: List[Diagnostic]
    fingerprints: List[Tuple[Diagnostic, str]]
    project: Project

    @property
    def ok(self) -> bool:
        return not self.new


def _lines_for(project: Project, cache: Dict[str, List[str]],
               rel: str) -> List[str]:
    """Source lines for any path a diagnostic may point at — lint-scope
    files from the index, registry docs (CONFIG.md rows for GM303) read
    off disk once."""
    if rel not in cache:
        src = project.file(rel)
        if src is not None:
            cache[rel] = src.lines
        else:
            try:
                cache[rel] = (
                    (project.root / rel)
                    .read_text(encoding="utf-8", errors="replace")
                    .splitlines()
                )
            except OSError:
                cache[rel] = []
    return cache[rel]


def run_project(root, paths=None, baseline_path: Optional[str] = None,
                restrict=None) -> LintResult:
    """``paths`` narrows what is *scanned* (fixture subsets);
    ``restrict`` narrows what is *reported* while the whole project is
    still scanned — the ``--changed-only`` contract, where the
    registry-parity checkers must keep seeing every reader or every
    unchanged read would look stale."""
    project = load_project(root, paths)
    diags: List[Diagnostic] = []
    for src in project.files:
        if src.parse_error is not None:
            diags.append(src.parse_error)
    for check in CHECKERS:
        diags.extend(check(project))
    if restrict is not None:
        keep = {str(r).replace("\\", "/") for r in restrict}
        diags = [d for d in diags if d.path in keep]
    diags.sort()

    lines_cache: Dict[str, List[str]] = {}
    kept: List[Diagnostic] = []
    suppressed: List[Diagnostic] = []
    for d in diags:
        lines = _lines_for(project, lines_cache, d.path)
        (suppressed if is_suppressed(d, lines) else kept).append(d)

    with_fp = [
        (d, fingerprint(d, _lines_for(project, lines_cache, d.path)))
        for d in kept
    ]
    baseline = load_baseline(baseline_path) if baseline_path else []
    new, old = split_by_baseline(with_fp, baseline)
    return LintResult(
        new=new,
        baselined=old,
        suppressed=suppressed,
        fingerprints=with_fp,
        project=project,
    )
