"""gamesman-lint GM10xx: whole-fleet wire-contract analysis.

The fleet speaks hand-written HTTP/TCP — query server, supervisor
control port, DB registry, per-rank status servers, coordination
barriers — with no type system between client and server call sites.
This family extracts both halves of the contract statically and checks
them against each other:

* **server side** — every ``BaseHTTPRequestHandler`` subclass yields a
  route table (string compares / ``startswith`` on the request path in
  each ``do_*`` dispatch closure), the status codes it emits (constant
  first args to the ``_send*``/``send_response`` helpers), the response
  headers it sets, and the JSON payload keys it produces (dict
  literals). The coordination server contributes its ``op`` vocabulary
  (``req.get("op") == "..."`` compares).
* **client side** — every ``urlopen``/``http.client``/
  ``create_connection`` call site (and every call into a *wire-fetch*
  wrapper: a function whose body contains both an outbound primitive
  and ``json.loads``), with method, extractable path constants, status
  codes branched on (``e.code``/``resp.status`` compares), JSON keys
  consumed (subscript/`.get` reads on names fed from the wire), and
  timeout arguments.

| id     | finding                                                     |
|--------|-------------------------------------------------------------|
| GM1001 | client route/method (or coordination op) no server defines  |
| GM1002 | status-code parity: client branches on a code no server     |
|        | emits / server sheds 304/429/503 no client handles          |
| GM1003 | outbound network call without an explicit finite timeout    |
| GM1004 | declared response-header contract violated (``# wire:``)    |
| GM1005 | cross-process JSON key parity: a consumed key no producer   |
|        | ever writes                                                 |
| GM1006 | endpoint docs parity: route undocumented in the             |
|        | SERVING.md/OBSERVABILITY.md endpoint tables, or a           |
|        | documented endpoint no server defines                       |

The ``# wire:`` annotation convention (placed on the ``class``/``def``
line or the comment line above, like ``# guarded-by:``):

* on a handler class — response-header rules the class promises:
  ``etag-cache-control`` (any response carrying ``ETag`` must carry
  ``Cache-Control``), ``503-retry-after`` / ``429-retry-after`` (shed
  responses must carry ``Retry-After``), ``echo-traceparent`` (the
  class echoes the request's ``traceparent``).
* on a function — wire roles the extractor cannot infer:
  ``producer`` (its dict literals / ``.send(**kw)`` keys cross a
  process boundary), ``consumer`` (its parameters and
  ``json.loads`` reads come off the wire), ``fetch`` (returns a wire-decoded dict; callers'
  assignments from it are tracked like ``json.loads``).

Deliberate narrowness (false negatives over false positives): paths
are only extracted where a ``/``-leading string constant is visible in
the URL expression; key consumption is only tracked through direct
assignments/loops from ``json.loads``/wire-fetch calls (a read through
``retry_call(lambda: ...)`` is invisible); coordination ``op`` literals
are only collected from modules that open sockets themselves (the job
ledger's ``{"op": ...}`` records never touch the network).

GM1004/GM1005/GM1006 checks are *opt-in by evidence*: with no handler
classes there is no route table to check against, with no producers no
key pool, with no endpoint-table rows no docs contract — the checkers
stay silent rather than guess.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from gamesmanmpi_tpu.analysis.diagnostics import Diagnostic, directive_lines
from gamesmanmpi_tpu.analysis.project import (
    Project,
    SourceFile,
    attr_chain,
)

_WIRE_RE = re.compile(r"#\s*wire:\s*([A-Za-z0-9_,\- ]+)")

#: Header rules a handler class may declare.
HANDLER_RULES = frozenset(
    {"etag-cache-control", "503-retry-after", "429-retry-after",
     "echo-traceparent"}
)
#: Role tokens a function may declare.
ROLE_TOKENS = frozenset({"producer", "consumer", "fetch"})

#: Response-emitting call names inside handler classes. The leading-
#: underscore names are the repo's send helpers (serve/server.py
#: idiom); the bare ones are the stdlib API itself.
_SEND_FINALS = frozenset(
    {"_send_json", "_send_text", "_send_status", "_send",
     "send_response", "send_error"}
)
#: Outbound primitives GM1003 demands an explicit timeout on, mapped to
#: the positional index their ``timeout`` parameter lives at.
_PRIMITIVES = {
    "urlopen": 2,  # urlopen(url, data=None, timeout=...)
    "create_connection": 1,  # create_connection(address, timeout=...)
    "HTTPConnection": 2,  # HTTPConnection(host, port=None, timeout=...)
    "HTTPSConnection": 2,
}
#: Codes the stdlib http.server machinery emits on its own (malformed
#: request line, oversized headers, unsupported method/version) — part
#: of every handler's de-facto contract even though no dispatch source
#: line mentions them.
IMPLICIT_CODES = frozenset({400, 408, 414, 431, 501, 505})
#: Server-initiated backpressure/staleness codes a fleet client must
#: understand (GM1002's server->client direction).
_SHED_CODES = (304, 429, 503)

_HTTP_VERBS = frozenset({"GET", "POST", "PUT", "DELETE", "HEAD", "PATCH"})


# --------------------------------------------------------------- helpers


def _const_str(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _const_int(node) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    return None


def _final(call: ast.Call) -> str:
    chain = attr_chain(call.func)
    return chain[-1] if chain else ""


def _is_json_loads(call: ast.Call) -> bool:
    chain = attr_chain(call.func)
    return bool(chain) and chain[-1] == "loads"


def _dict_keys(node: ast.Dict) -> Set[str]:
    out: Set[str] = set()
    for k in node.keys:
        s = _const_str(k) if k is not None else None
        if s is not None:
            out.add(s)
    return out


def _wire_tokens(lines: List[str], lineno: int) -> Optional[List[str]]:
    """``# wire:`` tokens attached to a def/class line, or None."""
    for text in directive_lines(lines, lineno):
        m = _WIRE_RE.search(text)
        if m:
            return [t for t in re.split(r"[,\s]+", m.group(1).strip())
                    if t]
    return None


# ------------------------------------------------------ server extraction


class ServerClass:
    """The statically extracted contract of one handler class."""

    def __init__(self, rel: str, name: str, line: int):
        self.rel = rel
        self.name = name
        self.line = line
        #: (method, path, is_prefix) -> first source line.
        self.routes: Dict[Tuple[str, str, bool], int] = {}
        #: emitted status code -> first source line.
        self.codes: Dict[int, int] = {}
        #: a dispatch method passes a non-constant code to a send
        #: helper — the code set is open, skip emitted-code checks.
        self.open_codes = False
        #: ``send_header("Name", ...)`` literals anywhere in the class,
        #: lowercased.
        self.header_names: Set[str] = set()
        #: ``# wire:`` rule tokens on the class.
        self.rules: Set[str] = set()
        #: (line, code-or-None, header-keys-or-None) per send call; the
        #: header set is None when a non-literal ``headers=`` argument
        #: could not be resolved to a dict literal.
        self.send_sites: List[Tuple[int, Optional[int],
                                    Optional[Set[str]]]] = []
        #: JSON keys this class writes (dict literals + subscript
        #: assignments anywhere in its body).
        self.produced: Set[str] = set()
        #: every dict literal in the class, for etag-cache-control.
        self.dicts: List[Tuple[int, Set[str]]] = []


def _class_methods(cls: ast.ClassDef) -> Dict[str, ast.FunctionDef]:
    out: Dict[str, ast.FunctionDef] = {}
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
    return out


def _dispatch_closure(methods: Dict[str, ast.FunctionDef],
                      entry: str) -> List[ast.FunctionDef]:
    """``entry`` plus every same-class method reachable through
    ``self.<name>`` references (calls AND callback mentions — the
    ``_run_traced(self._handle_post)`` shape)."""
    seen = {entry}
    queue = [entry]
    while queue:
        fn = methods.get(queue.pop())
        if fn is None:
            continue
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and node.attr in methods
                and node.attr not in seen
            ):
                seen.add(node.attr)
                queue.append(node.attr)
    return [methods[n] for n in seen if n in methods]


def _routes_in(fn: ast.FunctionDef) -> List[Tuple[str, bool, int]]:
    """(path, is_prefix, line) for every request-path compare in fn."""
    out = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Compare) and len(node.ops) == 1 \
                and isinstance(node.ops[0], ast.Eq):
            for side in (node.left, node.comparators[0]):
                s = _const_str(side)
                if s is not None and s.startswith("/"):
                    out.append((s.partition("?")[0], False, node.lineno))
        elif isinstance(node, ast.Call) and node.args \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "startswith":
            s = _const_str(node.args[0])
            if s is not None and s.startswith("/"):
                out.append((s.partition("?")[0], True, node.lineno))
    return out


def _send_headers(call: ast.Call,
                  enclosing: ast.FunctionDef) -> Optional[Set[str]]:
    """Lowercased header names a send call attaches: the ``headers=``
    dict literal, a same-function name assigned a dict literal, or the
    third positional arg of ``_send_status(code, headers)``. Returns an
    empty set when no headers argument exists, None when one exists but
    cannot be resolved to a literal."""
    hdr_expr = None
    for kw in call.keywords:
        if kw.arg == "headers":
            hdr_expr = kw.value
    if hdr_expr is None and _final(call) == "_send_status" \
            and len(call.args) >= 2:
        hdr_expr = call.args[1]
    if hdr_expr is None:
        return set()
    if isinstance(hdr_expr, ast.Dict):
        return {k.lower() for k in _dict_keys(hdr_expr)}
    if isinstance(hdr_expr, ast.Name):
        for node in ast.walk(enclosing):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == hdr_expr.id
                and isinstance(node.value, ast.Dict)
            ):
                return {k.lower() for k in _dict_keys(node.value)}
    return None


def _subscript_assign_keys(scope: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(scope):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    s = _const_str(t.slice)
                    if s is not None:
                        out.add(s)
    return out


def extract_server_classes(tree: ast.AST, lines: List[str],
                           rel: str) -> List[ServerClass]:
    """Every ``BaseHTTPRequestHandler`` subclass in ``tree`` with its
    extracted contract. Pure AST — reused by the runtime witness
    (analysis/wirecheck.py), which must not load the whole project."""
    out: List[ServerClass] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        is_handler = any(
            (chain := attr_chain(base)) is not None
            and chain[-1] == "BaseHTTPRequestHandler"
            for base in node.bases
        )
        if not is_handler:
            continue
        sc = ServerClass(rel, node.name, node.lineno)
        tokens = _wire_tokens(lines, node.lineno)
        if tokens:
            sc.rules = set(tokens)
        methods = _class_methods(node)
        for name, fn in methods.items():
            if name.startswith("do_"):
                verb = name[3:].upper()
                for member in _dispatch_closure(methods, name):
                    for path, prefix, line in _routes_in(member):
                        sc.routes.setdefault((verb, path, prefix), line)
        for name, fn in methods.items():
            for sub in ast.walk(fn):
                if not isinstance(sub, ast.Call):
                    continue
                final = _final(sub)
                if final == "send_header" and sub.args:
                    s = _const_str(sub.args[0])
                    if s is not None:
                        sc.header_names.add(s.lower())
                if final not in _SEND_FINALS:
                    continue
                arg0 = sub.args[0] if sub.args else None
                codes: List[int] = []
                if isinstance(arg0, ast.IfExp):
                    for branch in (arg0.body, arg0.orelse):
                        c = _const_int(branch)
                        if c is not None:
                            codes.append(c)
                else:
                    c = _const_int(arg0)
                    if c is not None:
                        codes.append(c)
                if codes:
                    for c in codes:
                        sc.codes.setdefault(c, sub.lineno)
                    sc.send_sites.append(
                        (sub.lineno, codes[0], _send_headers(sub, fn))
                    )
                elif name not in _SEND_FINALS:
                    # A dispatch method forwarding a computed code: the
                    # emitted-code set is open. (The same shape inside a
                    # ``_send*`` helper is just the forwarding itself.)
                    sc.open_codes = True
        sc.produced |= _subscript_assign_keys(node)
        for sub in ast.walk(node):
            if isinstance(sub, ast.Dict):
                keys = _dict_keys(sub)
                sc.produced |= keys
                if keys:
                    sc.dicts.append((sub.lineno, {k.lower()
                                                  for k in keys}))
        out.append(sc)
    return out


# ------------------------------------------------------ client extraction


class ClientCall:
    def __init__(self, rel: str, line: int, method: str, path: str,
                 prefix: bool):
        self.rel = rel
        self.line = line
        self.method = method
        self.path = path
        self.prefix = prefix


def _url_pieces(expr) -> List[Tuple[str, Optional[str]]]:
    if isinstance(expr, ast.JoinedStr):
        out: List[Tuple[str, Optional[str]]] = []
        for v in expr.values:
            s = _const_str(v)
            out.append(("const", s) if s is not None else ("var", None))
        return out
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        return _url_pieces(expr.left) + _url_pieces(expr.right)
    s = _const_str(expr)
    if s is not None:
        return [("const", s)]
    return [("var", None)]


def _path_from_url(expr) -> Optional[Tuple[str, bool]]:
    """(path, is_prefix) from a URL expression, or None when no
    ``/``-leading path constant is visible."""
    if expr is None:
        return None
    pieces = _url_pieces(expr)
    for i, (kind, text) in enumerate(pieces):
        if kind != "const" or text is None:
            continue
        if "://" in text:
            after = text.split("://", 1)[1]
            slash = after.find("/")
            if slash < 0:
                continue  # scheme+host piece only; path comes later
            text = after[slash:]
        elif not text.startswith("/"):
            continue
        path, q, _rest = text.partition("?")
        prefix = not q and any(k == "var" for k, _ in pieces[i + 1:])
        return path, prefix
    return None


def _request_method(call: ast.Call) -> str:
    """Method of a ``urllib.request.Request(...)`` constructor."""
    for kw in call.keywords:
        if kw.arg == "method":
            s = _const_str(kw.value)
            if s is not None:
                return s.upper()
        if kw.arg == "data" and not (
            isinstance(kw.value, ast.Constant) and kw.value.value is None
        ):
            return "POST"
    if len(call.args) >= 2:
        return "POST"
    return "GET"


class _FnInfo:
    """Per-function wire facts gathered in one walk."""

    def __init__(self, src: SourceFile, qualname: str, node,
                 lint_scope: bool):
        self.src = src
        self.qualname = qualname
        self.node = node
        self.lint_scope = lint_scope
        self.tokens = _wire_tokens(src.lines, node.lineno) or []
        self.has_primitive = False
        self.has_loads = False
        self.request_method = "GET"
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                final = _final(sub)
                if final in _PRIMITIVES:
                    self.has_primitive = True
                if final == "Request":
                    self.request_method = _request_method(sub)
                if _is_json_loads(sub):
                    self.has_loads = True

    @property
    def is_fetch(self) -> bool:
        return "fetch" in self.tokens or (
            self.has_primitive and self.has_loads
        )


class _Extraction:
    """Everything GM1001-GM1006 consume, built in one project pass."""

    def __init__(self, project: Project):
        self.project = project
        self.servers: List[ServerClass] = []
        self.clients: List[ClientCall] = []
        #: (code, exact, rel, line) for client `e.code`/`resp.status`
        #: compares. ``exact`` False = a ``>=`` open range.
        self.client_codes: List[Tuple[int, bool, str, int]] = []
        self.produced: Set[str] = set()
        #: (key, rel, line) consumed reads.
        self.consumed: List[Tuple[str, str, int]] = []
        self.coord_server_ops: Set[str] = set()
        #: (op, rel, line) dict-literal ops from socket modules.
        self.coord_client_ops: List[Tuple[str, str, int]] = []
        self.bad_tokens: List[Diagnostic] = []
        self._fns: Dict[str, _FnInfo] = {}  # "rel::qualname" -> info
        self._module_fns: Dict[str, Dict[str, str]] = {}
        self._build()

    # -- function index -------------------------------------------------

    def _iter_defs(self, src: SourceFile):
        """(qualname, class name, node) for every def, the call-graph
        registration order (collect_only files are not in the call
        graph, so the walk is done locally)."""

        def visit(body, prefix, cls):
            stack = list(body)
            while stack:
                node = stack.pop(0)
                if isinstance(node, ast.ClassDef):
                    visit(node.body, f"{prefix}{node.name}.", node.name)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    qual = f"{prefix}{node.name}"
                    yield_list.append((qual, cls, node))
                    visit(node.body, f"{qual}.", cls)
                else:
                    stack.extend(
                        c for c in ast.iter_child_nodes(node)
                        if isinstance(c, ast.stmt)
                    )

        yield_list: list = []
        visit(src.tree.body, "", None)
        return yield_list

    def _build(self) -> None:
        project = self.project
        cg = project.callgraph()  # shared, memoized (built exactly once)
        handlers_by_rel: Dict[str, Set[str]] = {}
        sources = [(s, True) for s in project.files] + [
            (s, False) for s in project.collect_only
        ]
        for src, lint_scope in sources:
            if src.tree is None:
                continue
            classes = extract_server_classes(src.tree, src.lines, src.rel)
            self.servers.extend(classes)
            handlers_by_rel[src.rel] = {c.name for c in classes}
            for qual, cls, node in self._iter_defs(src):
                info = _FnInfo(src, qual, node, lint_scope)
                self._fns[f"{src.rel}::{qual}"] = info
                self._module_fns.setdefault(src.rel, {})[qual] = (
                    f"{src.rel}::{qual}"
                )
            self._collect_module(src, handlers_by_rel[src.rel])
        self._cg = cg
        for key, info in self._fns.items():
            self._collect_fn(key, info,
                             handlers_by_rel.get(info.src.rel, set()))
        self._collect_annotation_errors(handlers_by_rel)

    # -- resolution -----------------------------------------------------

    def _resolve_fetch(self, info: _FnInfo,
                       call: ast.Call) -> Optional[_FnInfo]:
        chain = attr_chain(call.func)
        if not chain:
            return None
        src = info.src
        key = None
        if info.lint_scope:
            key = self._cg.resolve(src, info.qualname.split("."),
                                   list(chain))
        if key is None:
            # collect-only files (and anything the call graph cannot
            # see): local top-level names + from-imports.
            if len(chain) == 1:
                key = self._module_fns.get(src.rel, {}).get(chain[0])
                if key is None:
                    frm = self._from_imports(src).get(chain[0])
                    if frm is not None:
                        mod, attr = frm
                        rel = self._module_rel(mod)
                        if rel is not None:
                            key = self._module_fns.get(rel, {}).get(attr)
            elif chain[0] in ("self", "cls") and len(chain) == 2:
                for qual, fkey in self._module_fns.get(src.rel,
                                                       {}).items():
                    if qual.endswith(f".{chain[1]}"):
                        key = fkey
                        break
        if key is None:
            return None
        target = self._fns.get(key)
        return target if target is not None and target.is_fetch else None

    def _from_imports(self, src: SourceFile) -> Dict[str, tuple]:
        cache = getattr(src, "_wire_from_imports", None)
        if cache is None:
            cache = {}
            for node in ast.walk(src.tree):
                if isinstance(node, ast.ImportFrom) and node.module \
                        and node.level == 0:
                    for alias in node.names:
                        cache[alias.asname or alias.name] = (
                            node.module, alias.name
                        )
            src._wire_from_imports = cache  # type: ignore[attr-defined]
        return cache

    def _module_rel(self, dotted: str) -> Optional[str]:
        rel = dotted.replace(".", "/") + ".py"
        if rel in self._module_fns:
            return rel
        init = dotted.replace(".", "/") + "/__init__.py"
        return init if init in self._module_fns else None

    # -- per-module facts ----------------------------------------------

    def _collect_module(self, src: SourceFile,
                        handler_names: Set[str]) -> None:
        has_socket = False
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call):
                final = _final(node)
                if final in ("create_connection", "socket"):
                    has_socket = True
                # coordination server vocabulary: X.get("op") == "lit"
            if isinstance(node, ast.Compare) and len(node.ops) == 1 \
                    and isinstance(node.ops[0], (ast.Eq, ast.NotEq)):
                for a, b in ((node.left, node.comparators[0]),
                             (node.comparators[0], node.left)):
                    if (
                        isinstance(a, ast.Call)
                        and isinstance(a.func, ast.Attribute)
                        and a.func.attr == "get"
                        and a.args
                        and _const_str(a.args[0]) == "op"
                    ):
                        s = _const_str(b)
                        if s is not None:
                            self.coord_server_ops.add(s)
                # client status-code branches: e.code / resp.status
                chain = attr_chain(node.left)
                if chain and len(chain) >= 2 \
                        and chain[-1] in ("code", "status"):
                    c = _const_int(node.comparators[0])
                    if c is not None:
                        self.client_codes.append(
                            (c, True, src.rel, node.lineno)
                        )
            elif isinstance(node, ast.Compare) and len(node.ops) == 1:
                chain = attr_chain(node.left)
                if chain and len(chain) >= 2 \
                        and chain[-1] in ("code", "status"):
                    op, comp = node.ops[0], node.comparators[0]
                    if isinstance(op, ast.GtE):
                        c = _const_int(comp)
                        if c is not None:
                            self.client_codes.append(
                                (c, False, src.rel, node.lineno)
                            )
                    elif isinstance(op, ast.In) \
                            and isinstance(comp, (ast.Tuple, ast.Set,
                                                  ast.List)):
                        for elt in comp.elts:
                            c = _const_int(elt)
                            if c is not None:
                                self.client_codes.append(
                                    (c, True, src.rel, node.lineno)
                                )
        if has_socket:
            for node in ast.walk(src.tree):
                if isinstance(node, ast.Dict):
                    for k, v in zip(node.keys, node.values):
                        if k is not None and _const_str(k) == "op":
                            s = _const_str(v)
                            if s is not None:
                                self.coord_client_ops.append(
                                    (s, src.rel, node.lineno)
                                )

    # -- per-function facts --------------------------------------------

    def _collect_fn(self, key: str, info: _FnInfo,
                    handler_names: Set[str]) -> None:
        fetch_calls: List[ast.Call] = []
        fetch_of: Dict[ast.Call, _FnInfo] = {}
        for node in ast.walk(info.node):
            if isinstance(node, ast.Call):
                target = self._resolve_fetch(info, node)
                if target is not None:
                    fetch_calls.append(node)
                    fetch_of[node] = target
        in_handler = info.qualname.split(".")[0] in handler_names
        is_producer = (
            in_handler or info.has_primitive or bool(fetch_calls)
            or "producer" in info.tokens
        )
        is_consumer = (
            in_handler or info.has_primitive or bool(fetch_calls)
            or "consumer" in info.tokens
        )
        if is_producer and not in_handler:
            # handler classes pool their keys via extract_server_classes
            for node in ast.walk(info.node):
                if isinstance(node, ast.Dict):
                    self.produced |= _dict_keys(node)
                elif isinstance(node, ast.Call):
                    chain = attr_chain(node.func)
                    if chain and chain[-1] == "send":
                        for kw in node.keywords:
                            if kw.arg:
                                self.produced.add(kw.arg)
            self.produced |= _subscript_assign_keys(info.node)
        if is_consumer:
            self._collect_consumption(info, fetch_of)
        self._collect_routes(info, fetch_of)

    def _collect_routes(self, info: _FnInfo,
                        fetch_of: Dict[ast.Call, _FnInfo]) -> None:
        seen: Set[int] = set()
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call) or node.lineno in seen:
                continue
            final = _final(node)
            url_expr = None
            method = None
            if final == "urlopen" and node.args:
                url_expr = node.args[0]
                method = "GET"
                if isinstance(url_expr, ast.Name):
                    req = self._local_request(info.node, url_expr.id)
                    if req is not None:
                        method = _request_method(req)
                        url_expr = req.args[0] if req.args else None
                elif isinstance(url_expr, ast.Call) \
                        and _final(url_expr) == "Request":
                    method = _request_method(url_expr)
                    url_expr = (url_expr.args[0] if url_expr.args
                                else None)
            elif node in fetch_of:
                url_expr = node.args[0] if node.args else None
                method = fetch_of[node].request_method
            elif final == "request" and len(node.args) >= 2:
                verb = _const_str(node.args[0])
                if verb is not None and verb.upper() in _HTTP_VERBS:
                    method = verb.upper()
                    url_expr = node.args[1]
            if url_expr is None or method is None:
                continue
            got = _path_from_url(url_expr)
            if got is None:
                continue
            path, prefix = got
            seen.add(node.lineno)
            self.clients.append(
                ClientCall(info.src.rel, node.lineno, method, path,
                           prefix)
            )

    @staticmethod
    def _local_request(fn, name: str) -> Optional[ast.Call]:
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == name
                and isinstance(node.value, ast.Call)
                and _final(node.value) == "Request"
            ):
                return node.value
        return None

    def _collect_consumption(self, info: _FnInfo,
                             fetch_of: Dict[ast.Call, _FnInfo]) -> None:
        wire_names: Set[str] = set()
        if "consumer" in info.tokens:
            # An annotated consumer's parameters ARE the wire payload
            # (the supervisor's _on_msg(slot, msg, now) shape, where
            # json.loads happens in the read loop one frame up).
            args = info.node.args
            for a in (args.posonlyargs + args.args + args.kwonlyargs):
                if a.arg not in ("self", "cls"):
                    wire_names.add(a.arg)

        def is_wire(expr) -> bool:
            if isinstance(expr, ast.Name):
                return expr.id in wire_names
            if isinstance(expr, ast.Call):
                if _is_json_loads(expr) or expr in fetch_of:
                    return True
                # w.get("k") chains stay on the wire
                if isinstance(expr.func, ast.Attribute) \
                        and expr.func.attr == "get":
                    return is_wire(expr.func.value)
                return False
            if isinstance(expr, ast.Subscript):
                return is_wire(expr.value)
            return False

        changed = True
        while changed:
            changed = False
            for node in ast.walk(info.node):
                tgt = val = None
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    tgt, val = node.targets[0].id, node.value
                elif isinstance(node, (ast.For, ast.comprehension)):
                    t = node.target
                    if isinstance(t, ast.Name):
                        tgt, val = t.id, node.iter
                if tgt is None or tgt in wire_names or val is None:
                    continue
                if is_wire(val):
                    wire_names.add(tgt)
                    changed = True
        for node in ast.walk(info.node):
            if isinstance(node, ast.Subscript) \
                    and isinstance(node.ctx, ast.Load) \
                    and is_wire(node.value):
                s = _const_str(node.slice)
                if s is not None:
                    self.consumed.append((s, info.src.rel, node.lineno))
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "get" and node.args \
                    and is_wire(node.func.value):
                s = _const_str(node.args[0])
                if s is not None:
                    self.consumed.append((s, info.src.rel, node.lineno))

    # -- annotation validation -----------------------------------------

    def _collect_annotation_errors(
            self, handlers_by_rel: Dict[str, Set[str]]) -> None:
        for src, lint_scope in [(s, True) for s in self.project.files]:
            if src.tree is None:
                continue
            handler_names = handlers_by_rel.get(src.rel, set())
            for node in ast.walk(src.tree):
                if isinstance(node, ast.ClassDef):
                    allowed = (
                        HANDLER_RULES | {"producer", "consumer"}
                        if node.name in handler_names else ROLE_TOKENS
                    )
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    allowed = ROLE_TOKENS
                else:
                    continue
                tokens = _wire_tokens(src.lines, node.lineno)
                for t in tokens or []:
                    if t not in allowed:
                        self.bad_tokens.append(Diagnostic(
                            src.rel, node.lineno, "GM1004",
                            f"unknown or misplaced '# wire:' token "
                            f"{t!r} (allowed here: "
                            f"{', '.join(sorted(allowed))})",
                        ))


# ----------------------------------------------------------- docs tables

_DOC_ROW_RE = re.compile(
    r"^\s*\|\s*(GET|POST|PUT|DELETE|HEAD|PATCH)\s*\|\s*([^|]+)\|"
)


def _doc_rows(text: str, rel: str) -> List[Tuple[str, str, bool, str,
                                                 int]]:
    """(method, path, is_prefix, rel, line) per endpoint-table row."""
    out = []
    for i, line in enumerate(text.splitlines(), 1):
        m = _DOC_ROW_RE.match(line)
        if not m:
            continue
        cell = m.group(2).strip().strip("`").strip()
        if not cell.startswith("/"):
            continue
        cut = cell.find("<")
        if cut >= 0:
            out.append((m.group(1), cell[:cut], True, rel, i))
        else:
            out.append((m.group(1), cell, False, rel, i))
    return out


def _paths_overlap(p1: str, pre1: bool, p2: str, pre2: bool) -> bool:
    if not pre1 and not pre2:
        return p1 == p2
    if pre1 and not pre2:
        return p2.startswith(p1)
    if pre2 and not pre1:
        return p1.startswith(p2)
    return p1.startswith(p2) or p2.startswith(p1)


# --------------------------------------------------------------- checkers


def _check_routes(ex: _Extraction) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    if ex.servers:
        table = [
            (method, path, prefix)
            for sc in ex.servers
            for (method, path, prefix) in sc.routes
        ]
        for call in ex.clients:
            ok = any(
                call.method == m
                and _paths_overlap(call.path, call.prefix, p, pre)
                for (m, p, pre) in table
            )
            if not ok:
                diags.append(Diagnostic(
                    call.rel, call.line, "GM1001",
                    f"client calls {call.method} "
                    f"{call.path}{'...' if call.prefix else ''} but no "
                    f"server defines that route/method",
                ))
    if ex.coord_server_ops:
        seen: Set[Tuple[str, str, int]] = set()
        for op, rel, line in ex.coord_client_ops:
            if op not in ex.coord_server_ops \
                    and (op, rel, line) not in seen:
                seen.add((op, rel, line))
                diags.append(Diagnostic(
                    rel, line, "GM1001",
                    f"wire op {op!r} is sent but no coordination "
                    f"server compares against it",
                ))
    return diags


def _check_status_parity(ex: _Extraction) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    if not ex.servers:
        return diags
    emitted: Set[int] = set()
    any_open = False
    for sc in ex.servers:
        emitted |= set(sc.codes)
        any_open = any_open or sc.open_codes
    emitted |= IMPLICIT_CODES
    if not any_open:
        seen: Set[Tuple[str, int, int]] = set()
        for code, exact, rel, line in ex.client_codes:
            if exact and code not in emitted \
                    and (rel, line, code) not in seen:
                seen.add((rel, line, code))
                diags.append(Diagnostic(
                    rel, line, "GM1002",
                    f"client branches on HTTP {code}, which no server "
                    f"ever emits",
                ))
    if ex.clients and ex.client_codes:
        exacts = {c for c, exact, _r, _l in ex.client_codes if exact}
        floors = [c for c, exact, _r, _l in ex.client_codes
                  if not exact]
        for shed in _SHED_CODES:
            handled = shed in exacts or any(shed >= f for f in floors)
            if handled:
                continue
            for sc in ex.servers:
                if shed in sc.codes:
                    diags.append(Diagnostic(
                        sc.rel, sc.codes[shed], "GM1002",
                        f"server emits HTTP {shed} but no client "
                        f"branches on it (unhandled-error path)",
                    ))
                    break
    return diags


def _check_timeouts(project: Project) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    for src in list(project.files) + list(project.collect_only):
        if src.tree is None:
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            final = _final(node)
            if final not in _PRIMITIVES:
                continue
            timeout = None
            for kw in node.keywords:
                if kw.arg == "timeout":
                    timeout = kw.value
            pos = _PRIMITIVES[final]
            if timeout is None and len(node.args) > pos:
                timeout = node.args[pos]
            if timeout is None or (
                isinstance(timeout, ast.Constant)
                and timeout.value is None
            ):
                diags.append(Diagnostic(
                    src.rel, node.lineno, "GM1003",
                    f"outbound {final}() without an explicit finite "
                    f"timeout — a dead peer hangs this call forever",
                ))
    return diags


def _check_headers(ex: _Extraction) -> List[Diagnostic]:
    diags = list(ex.bad_tokens)
    for sc in ex.servers:
        rules = sc.rules & HANDLER_RULES
        if not rules:
            continue
        for rule, code in (("503-retry-after", 503),
                           ("429-retry-after", 429)):
            if rule not in rules:
                continue
            for line, sent, headers in sc.send_sites:
                if sent != code or headers is None:
                    continue
                if "retry-after" not in headers:
                    diags.append(Diagnostic(
                        sc.rel, line, "GM1004",
                        f"{sc.name} promises {rule} but this {code} "
                        f"response carries no Retry-After header",
                    ))
        if "etag-cache-control" in rules:
            for line, sent, headers in sc.send_sites:
                if headers and "etag" in headers \
                        and "cache-control" not in headers:
                    diags.append(Diagnostic(
                        sc.rel, line, "GM1004",
                        f"{sc.name}: response sets ETag without "
                        f"Cache-Control — edge caches will guess the "
                        f"TTL",
                    ))
            for line, keys in sc.dicts:
                if "etag" in keys and "cache-control" not in keys:
                    diags.append(Diagnostic(
                        sc.rel, line, "GM1004",
                        f"{sc.name}: header dict sets ETag without "
                        f"Cache-Control — edge caches will guess the "
                        f"TTL",
                    ))
        if "echo-traceparent" in rules \
                and "traceparent" not in sc.header_names:
            diags.append(Diagnostic(
                sc.rel, sc.line, "GM1004",
                f"{sc.name} promises echo-traceparent but never sends "
                f"a traceparent header",
            ))
    return diags


def _check_key_parity(ex: _Extraction) -> List[Diagnostic]:
    produced = set(ex.produced)
    for sc in ex.servers:
        produced |= sc.produced
    if not produced:
        return []
    diags: List[Diagnostic] = []
    seen: Set[Tuple[str, str, int]] = set()
    for key, rel, line in ex.consumed:
        if key in produced or (key, rel, line) in seen:
            continue
        seen.add((key, rel, line))
        diags.append(Diagnostic(
            rel, line, "GM1005",
            f"wire payload key {key!r} is consumed here but no "
            f"producer dict ever writes it",
        ))
    return diags


def _check_docs(ex: _Extraction, project: Project) -> List[Diagnostic]:
    serving_rel = "docs/SERVING.md"
    try:
        serving_text = (project.root / serving_rel).read_text(
            encoding="utf-8", errors="replace"
        )
    except OSError:
        serving_text = ""
    rows = _doc_rows(serving_text, serving_rel)
    rows += _doc_rows(project.observability_md, "docs/OBSERVABILITY.md")
    diags: List[Diagnostic] = []
    if rows:
        documented: Set[Tuple[str, str, str, bool]] = set()
        for sc in ex.servers:
            for (method, path, prefix), line in sorted(
                sc.routes.items(), key=lambda kv: kv[1]
            ):
                dedup = (sc.rel, method, path, prefix)
                if dedup in documented:
                    continue
                documented.add(dedup)
                ok = any(
                    method == m
                    and _paths_overlap(path, prefix, p, pre)
                    for (m, p, pre, _r, _l) in rows
                )
                if not ok:
                    diags.append(Diagnostic(
                        sc.rel, line, "GM1006",
                        f"{sc.name} serves {method} "
                        f"{path}{'...' if prefix else ''} but the "
                        f"endpoint tables in docs/SERVING.md / "
                        f"docs/OBSERVABILITY.md do not document it",
                    ))
    if rows and ex.servers:
        table = [
            (method, path, prefix)
            for sc in ex.servers
            for (method, path, prefix) in sc.routes
        ]
        for m, p, pre, rel, line in rows:
            ok = any(
                m == method and _paths_overlap(p, pre, path, prefix)
                for (method, path, prefix) in table
            )
            if not ok:
                diags.append(Diagnostic(
                    rel, line, "GM1006",
                    f"documented endpoint {m} "
                    f"{p}{'...' if pre else ''} matches no extracted "
                    f"server route",
                ))
    return diags


def check(project: Project) -> List[Diagnostic]:
    ex = _Extraction(project)
    diags: List[Diagnostic] = []
    diags.extend(_check_routes(ex))
    diags.extend(_check_status_parity(ex))
    diags.extend(_check_timeouts(project))
    diags.extend(_check_headers(ex))
    diags.extend(_check_key_parity(ex))
    diags.extend(_check_docs(ex, project))
    return diags
