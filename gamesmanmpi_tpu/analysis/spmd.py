"""GM6xx — SPMD / collective safety.

At multi-host scale every collective is a fleet-wide appointment: all
ranks must dispatch the same collectives in the same order, or the job
wedges silently (the Pentago-scale failure mode — one rank takes a
different branch and its peers wait in an ``all_to_all`` forever).
These checkers enforce the repo's collective conventions over the
whole-program call graph (analysis/project.CallGraph), so a collective
buried three calls deep under a rank test is still found.

Collective sites (resolved through the call graph, including kernel
builders handed to ``get_kernel``/``shard_map``):

* device (ICI) collectives — ``all_to_all``, ``psum``, ``all_gather``,
  ``pmax``, ``pmin``, ``ppermute``, ``pmean``;
* host (DCN) collectives — ``process_allgather``,
  ``sync_global_devices``, ``resume_digest`` (every rank must digest
  the same checkpoint state);
* consensus barriers — ``.barrier()`` / ``.propose()`` on a
  coordination handle (receiver chain mentions ``coord``, or the
  resolved method lives on ``EpochBarrier``/``Coordination``).

Rank-dependence is a small dataflow index: an ``if`` test is
rank-dependent when it reads ``jax.process_index()`` (directly, via a
local assigned from it, via an attribute assigned from it in the same
class, or via a parameter literally named ``rank``/``process_id``).
``process_count()`` is NOT rank-dependent — it is uniform across ranks.

| id | finding |
|---|---|
| GM601 | collective reachable in only one arm of a rank-dependent branch |
| GM602 | both arms dispatch collectives, but in a different order |
| GM603 | device collective dispatched outside ``_retry_collective`` routing (modules that define it) |
| GM604 | collective/barrier invoked while holding a lock |

A branch that ends in ``raise`` is exempt from GM601/GM602: aborting is
the one divergence the runtime contracts (watchdog exit-124, barrier
deadline) already handle.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from gamesmanmpi_tpu.analysis.diagnostics import Diagnostic
from gamesmanmpi_tpu.analysis.project import (
    CallEvent,
    Project,
    SourceFile,
    attr_chain,
    stmt_terminates,
)

#: Device-interconnect collectives: every participating rank's *device*
#: must enter; the dispatch is what GM603's retry routing protects.
ICI_COLLECTIVES = frozenset({
    "all_to_all", "psum", "all_gather", "pmax", "pmin", "ppermute",
    "pmean",
})

#: Host-side collectives: every *process* must call them together.
HOST_COLLECTIVES = frozenset({
    "process_allgather", "sync_global_devices", "resume_digest",
})

#: Methods that are consensus rounds when called on a coordination
#: handle (receiver chain mentions "coord"), or resolved onto these
#: classes.
BARRIER_METHODS = frozenset({"barrier", "propose"})
BARRIER_CLASSES = frozenset({"EpochBarrier", "Coordination"})

#: Callback funnels that do NOT dispatch what they receive: background
#: AOT compilation only builds, a thread target runs on its own thread
#: (not in this rank's collective program order).
NON_DISPATCH_VIAS = frozenset({"schedule_kernel", "Thread"})

#: Callback funnels whose received function becomes a *traced* kernel
#: body — its collectives dispatch where the built kernel is invoked,
#: so the body itself is exempt from GM603.
TRACED_VIAS = frozenset({
    "shard_map", "jit", "pallas_call", "get_kernel", "checkify",
})

#: Names whose value is this process's rank.
_RANK_CALLS = frozenset({"process_index", "process_id"})
_RANK_NAMES = frozenset({"PROCESS_ID", "rank", "process_id"})

#: Footprint expansion cap — divergence is decidable from a prefix;
#: unbounded expansion through deep call chains buys nothing.
_MAX_SEQ = 64


# ---------------------------------------------------------------- analysis


class _Collectives:
    """Shared per-project index: which functions reach collectives, and
    ordered per-function collective footprints."""

    def __init__(self, project: Project):
        self.project = project
        self.graph = project.callgraph()
        direct_any: Dict[str, bool] = {}
        direct_ici: Dict[str, bool] = {}
        for key, fn in self.graph.functions.items():
            for ev in fn.events:
                kind = self.direct_kind(ev)
                if kind is None:
                    continue
                direct_any[key] = True
                if kind == "ici":
                    direct_ici[key] = True
        # Consensus primitives: propose/barrier on the coordination
        # classes ARE rounds even though their bodies are socket code.
        for key, fn in self.graph.functions.items():
            if fn.cls in BARRIER_CLASSES and fn.name in BARRIER_METHODS:
                direct_any[key] = True
        self.reach_any = self.graph.reach(
            direct_any, exclude_vias=NON_DISPATCH_VIAS)
        self.reach_ici = self.graph.reach(
            direct_ici, exclude_vias=NON_DISPATCH_VIAS)
        self._seq_cache: Dict[str, List[str]] = {}

    def direct_kind(self, ev: CallEvent) -> Optional[str]:
        """"ici" / "host" / "barrier" when the event itself is a
        collective call, else None. Callback events never are (passing
        a function is not calling it)."""
        if ev.via:
            return None
        if ev.final in ICI_COLLECTIVES:
            return "ici"
        if ev.final in HOST_COLLECTIVES:
            return "host"
        if ev.final in BARRIER_METHODS:
            if any("coord" in part for part in ev.chain[:-1]):
                return "barrier"
            if ev.callee is not None:
                target = self.graph.functions.get(ev.callee)
                if target is not None and target.cls in BARRIER_CLASSES:
                    return "barrier"
        return None

    def event_footprint(self, ev: CallEvent) -> List[str]:
        """Ordered collective names this event dispatches."""
        kind = self.direct_kind(ev)
        if kind is not None:
            return [ev.final]
        if ev.via in NON_DISPATCH_VIAS:
            return []
        if ev.via:
            return []  # callbacks dispatch at their receiver, not here
        if ev.callee is not None and ev.callee in self.reach_any:
            return self.func_seq(ev.callee)
        return []

    def func_seq(self, key: str) -> List[str]:
        """Memoized ordered collective footprint of one function
        (callback edges expand too — calling a function that *hands* a
        kernel to get_kernel and invokes it dispatches the kernel)."""
        cached = self._seq_cache.get(key)
        if cached is not None:
            return cached
        self._seq_cache[key] = []  # cycle guard
        fn = self.graph.functions.get(key)
        out: List[str] = []
        if fn is not None:
            for ev in fn.events:
                if len(out) >= _MAX_SEQ:
                    break
                kind = self.direct_kind(ev)
                if kind is not None:
                    out.append(ev.final)
                elif (ev.callee is not None
                      and ev.via not in NON_DISPATCH_VIAS
                      and ev.callee in self.reach_any):
                    out.extend(self.func_seq(ev.callee))
        out = out[:_MAX_SEQ]
        self._seq_cache[key] = out
        return out

    def branch_events(self, fn_key: str, stmts: list) -> List[CallEvent]:
        """This function's events whose AST nodes sit inside ``stmts``
        (source order preserved), nested defs excluded — their events
        belong to the nested function."""
        nodes = set()
        for s in stmts:
            for n in ast.walk(s):
                nodes.add(id(n))
        fn = self.graph.functions[fn_key]
        return [ev for ev in fn.events if id(ev.node) in nodes]

    def branch_seq(self, fn_key: str, stmts: list) -> List[str]:
        out: List[str] = []
        for ev in self.branch_events(fn_key, stmts):
            out.extend(self.event_footprint(ev))
        return out[:_MAX_SEQ]


# ----------------------------------------------------------- rank taint


class _RankTaint:
    """Names/attributes in one module whose value depends on this
    process's rank."""

    def __init__(self, src: SourceFile):
        self.src = src
        #: attribute names assigned from a rank source anywhere in the
        #: module (class-field taint: ``self.rank = jax.process_index()``)
        self.attrs: Set[str] = set()
        self._collect_attrs()

    @staticmethod
    def _expr_is_rank_source(node: ast.AST,
                             local: Set[str] = frozenset(),
                             attrs: Set[str] = frozenset()) -> bool:
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                chain = attr_chain(n.func)
                if chain and chain[-1] in _RANK_CALLS:
                    return True
            elif isinstance(n, ast.Name):
                if n.id == "PROCESS_ID" or n.id in local:
                    return True
            elif isinstance(n, ast.Attribute):
                if n.attr == "PROCESS_ID" or n.attr in attrs:
                    return True
        return False

    def _collect_attrs(self) -> None:
        for node in ast.walk(self.src.tree):
            value = None
            target = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                target, value = node.target, node.value
            if (
                target is not None
                and isinstance(target, ast.Attribute)
                and self._expr_is_rank_source(value)
            ):
                self.attrs.add(target.attr)

    def function_locals(self, fn) -> Set[str]:
        """Rank-tainted local names inside one function: parameters
        literally named rank/process_id, plus locals assigned from a
        rank source (one forward pass — good enough for init-then-test
        code)."""
        local: Set[str] = set()
        args = fn.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            if a.arg in ("rank", "process_id"):
                local.add(a.arg)
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                if self._expr_is_rank_source(node.value, local,
                                             self.attrs):
                    local.add(node.targets[0].id)
        return local

    def test_is_rank_dependent(self, test: ast.AST,
                               local: Set[str]) -> bool:
        return self._expr_is_rank_source(test, local, self.attrs)


# ------------------------------------------------------------- checkers


def _check_rank_branches(coll: _Collectives, src: SourceFile,
                         diags: List[Diagnostic]) -> None:
    taint = _RankTaint(src)
    graph = coll.graph
    for key in graph.by_module.get(src.rel, []):
        fn = graph.functions[key]
        if key not in coll.reach_any:
            continue
        local = taint.function_locals(fn.node)
        _walk_rank_ifs(coll, src, key, fn.node.body, taint, local, diags)


def _walk_rank_ifs(coll, src, key, stmts, taint, local, diags) -> None:
    for i, node in enumerate(stmts):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue  # nested defs are walked under their own key
        if isinstance(node, ast.If) and taint.test_is_rank_dependent(
                node.test, local):
            _check_one_if(coll, src, key, node, stmts[i + 1:], diags)
        for child_body in _stmt_bodies(node):
            _walk_rank_ifs(coll, src, key, child_body, taint, local,
                           diags)


def _stmt_bodies(node):
    for field in ("body", "orelse", "finalbody"):
        body = getattr(node, field, None)
        if body:
            yield body
    for handler in getattr(node, "handlers", []) or []:
        yield handler.body


def _check_one_if(coll, src, key, node: ast.If, rest, diags) -> None:
    t_body = stmt_terminates(node.body)
    t_else = stmt_terminates(node.orelse)
    rest_seq = coll.branch_seq(key, list(rest))
    seq_a = coll.branch_seq(key, node.body)
    seq_b = coll.branch_seq(key, node.orelse)
    if t_body != "raise" and t_body != "return":
        seq_a = seq_a + rest_seq
    if t_else != "raise" and t_else != "return":
        seq_b = seq_b + rest_seq
    if t_body == "raise":
        seq_a = seq_b  # aborting arm: divergence handled by contract
    if t_else == "raise":
        seq_b = seq_a
    if seq_a == seq_b:
        return
    if sorted(seq_a) == sorted(seq_b):
        diags.append(Diagnostic(
            src.rel, node.lineno, "GM602",
            "collective call order diverges between the arms of this "
            "rank-dependent branch — ranks will meet different "
            "collectives",
        ))
        return
    # One-sided: name the first surplus collective at its own line.
    surplus = _surplus_names(seq_a, seq_b)
    line, name = _first_surplus_event(coll, key, node, rest, surplus)
    diags.append(Diagnostic(
        src.rel, line, "GM601",
        f"collective {name!r} is reachable in only one arm of a "
        "rank-dependent branch — ranks that skip it will wedge their "
        "peers",
    ))


def _surplus_names(seq_a: List[str], seq_b: List[str]) -> Set[str]:
    from collections import Counter

    a, b = Counter(seq_a), Counter(seq_b)
    return {n for n in (a | b) if a[n] != b[n]}


def _first_surplus_event(coll, key, node: ast.If, rest, surplus):
    for stmts in (node.body, node.orelse, list(rest)):
        for ev in coll.branch_events(key, stmts):
            for name in coll.event_footprint(ev):
                if name in surplus:
                    return ev.lineno, name
    return node.lineno, sorted(surplus)[0] if surplus else "?"


def _check_retry_routing(coll: _Collectives, src: SourceFile,
                         diags: List[Diagnostic]) -> None:
    """GM603: in modules that define ``_retry_collective``, device
    collectives must be dispatched from a function routed through
    ``_retry``/``_retry_collective`` (passed as its thunk)."""
    graph = coll.graph
    keys = graph.by_module.get(src.rel, [])
    if not any(graph.functions[k].name == "_retry_collective"
               for k in keys):
        return
    protected: Set[str] = set()
    traced: Set[str] = set()
    for k in keys:
        for ev in graph.functions[k].events:
            if ev.callee is None:
                continue
            if ev.via in ("_retry", "_retry_collective"):
                protected.add(ev.callee)
            if ev.via in TRACED_VIAS:
                traced.add(ev.callee)
    # closure: everything a protected/traced function calls inherits
    changed = True
    while changed:
        changed = False
        for k in keys:
            if k in protected:
                for ev in graph.functions[k].events:
                    if ev.callee is not None and ev.callee not in protected:
                        protected.add(ev.callee)
                        changed = True
            if k in traced:
                for ev in graph.functions[k].events:
                    if ev.callee is not None and ev.callee not in traced:
                        traced.add(ev.callee)
                        changed = True
    # nesting: a def inside a protected/traced def inherits its context
    for k in keys:
        for container in (protected, traced):
            if k in container:
                prefix = graph.functions[k].qualname + "."
                for other in keys:
                    if graph.functions[other].qualname.startswith(prefix):
                        container.add(other)
    retry_fns = {k for k in keys
                 if graph.functions[k].name in ("_retry",
                                                "_retry_collective")}
    # Kernel producers: functions that hand an ICI-collective kernel
    # body to a build/trace funnel (get_kernel/shard_map/jit) and return
    # the built callable — CALLING one is fetching a kernel the caller
    # immediately dispatches. An ordinary call into a function that
    # dispatches internally is NOT flagged at the caller: the dispatch
    # site inside it is judged where it stands.
    producers: Set[str] = set()
    for k, fn in graph.functions.items():
        for ev in fn.events:
            if (ev.via in TRACED_VIAS and ev.callee is not None
                    and ev.callee in coll.reach_ici):
                producers.add(k)
                break
    for k in keys:
        if k in protected or k in traced or k in retry_fns:
            continue
        fn = graph.functions[k]
        for ev in fn.events:
            if ev.via:
                continue
            is_direct = coll.direct_kind(ev) == "ici"
            fetches = ev.callee is not None and ev.callee in producers
            if is_direct or fetches:
                diags.append(Diagnostic(
                    src.rel, ev.lineno, "GM603",
                    f"device collective dispatch ({ev.final}) outside "
                    "_retry_collective routing — a transient here "
                    "retries on one rank while peers enter the "
                    "collective",
                ))


def _check_collective_under_lock(coll: _Collectives, project: Project,
                                 src: SourceFile,
                                 diags: List[Diagnostic]) -> None:
    """GM604: a collective blocks until every rank arrives; holding a
    lock across one starves every thread that needs it (and a peer's
    death turns that into a permanent wedge)."""
    mod = project.module_locks(src)
    if not mod.lock_kind:
        return
    graph = coll.graph
    events_by_node = {}
    for k in graph.by_module.get(src.rel, []):
        for ev in graph.functions[k].events:
            events_by_node[id(ev.node)] = ev

    def scan_expr(n, held):
        """Report collective events in one expression subtree (nested
        defs/lambdas excluded — their bodies run later, elsewhere)."""
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            return
        ev = events_by_node.get(id(n))
        if ev is not None and not ev.via:
            kind = coll.direct_kind(ev)
            reaches = (ev.callee is not None
                       and ev.callee in coll.reach_any)
            if kind is not None or reaches:
                diags.append(Diagnostic(
                    src.rel, ev.lineno, "GM604",
                    f"collective/barrier ({ev.final}) invoked while "
                    "holding a lock — a slow or dead peer wedges "
                    "every thread waiting on it",
                ))
        for c in ast.iter_child_nodes(n):
            if not isinstance(c, ast.stmt):
                scan_expr(c, held)

    def walk(stmts, held):
        for node in stmts:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(node, ast.With):
                inner = set(held)
                for item in node.items:
                    ln = mod.with_lock(item.context_expr)
                    if ln is not None:
                        inner.add(ln)
                    elif held:
                        scan_expr(item.context_expr, held)
                walk(node.body, inner)
                continue
            if held:
                for c in ast.iter_child_nodes(node):
                    if not isinstance(c, ast.stmt):
                        scan_expr(c, held)
            for body in _stmt_bodies(node):
                walk(body, held)

    def visit_functions(body, cls):
        for node in body:
            if isinstance(node, ast.ClassDef):
                visit_functions(node.body, node.name)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                held = set()
                req = mod.requires.get(node)
                if req is not None:
                    held.add(mod.canonical(req))
                walk(node.body, held)
                visit_functions(node.body, cls)

    visit_functions(src.tree.body, None)


def check(project: Project) -> List[Diagnostic]:
    coll = _Collectives(project)
    diags: List[Diagnostic] = []
    for src in project.files:
        if src.tree is None:
            continue
        _check_rank_branches(coll, src, diags)
        _check_retry_routing(coll, src, diags)
        _check_collective_under_lock(coll, project, src, diags)
    return diags
