"""gamesmanmpi_tpu — a TPU-native strong game solver.

A from-scratch rebuild of the capabilities of swerwath/GamesmanMPI (a distributed
mpi4py strong solver for abstract two-player games): computes the game-theoretic
value (WIN / LOSE / TIE) and remoteness of every reachable position, behind the
same minimal game-plugin boundary, re-expressed as a level-synchronous retrograde
sweep over bit-packed state tensors in JAX/XLA.

Reference architecture mapping (see SURVEY.md; the reference mount was empty this
session, so citations are to SURVEY sections rather than file:line):

  reference (SURVEY.md §2.2)          this package
  ---------------------------------   -------------------------------------------
  solver_launcher.py  (CLI)           solve_launcher.py / gamesmanmpi_tpu.cli
  src/process.py      (event loop)    gamesmanmpi_tpu.solve.engine (level sweep)
                                      gamesmanmpi_tpu.parallel.sharded (multi-chip)
  src/job.py          (Job types)     replaced by level-synchronous phases; see
                                      solve/engine.py docstring for the mapping
  src/game_state.py   (GameState)     gamesmanmpi_tpu.core (bit-packed states,
                                      owner hashing) + games.base (expand)
  src/utils.py        (value algebra) gamesmanmpi_tpu.core.values / ops.combine
  games/*.py          (plugins)       gamesmanmpi_tpu.games.* (tensorized) and
                                      gamesmanmpi_tpu.compat (unmodified modules)
  mpi4py transport                    jax.lax.all_to_all / psum over the ICI mesh

States are packed uint64; we therefore require 64-bit mode in JAX. This must be
configured before any tracing happens, which is why it lives at package import.
"""

import jax

jax.config.update("jax_enable_x64", True)

from gamesmanmpi_tpu.core.values import WIN, LOSE, TIE, UNDECIDED  # noqa: E402
from gamesmanmpi_tpu.games import get_game  # noqa: E402

__version__ = "0.1.0"

__all__ = ["WIN", "LOSE", "TIE", "UNDECIDED", "get_game", "__version__"]
