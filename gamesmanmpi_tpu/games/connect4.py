"""Connect-N on a w x h board, column-drop rules (reference games/win4.py-style;
BASELINE configs #3-4 and the 6x7 north star).

State encoding: column c occupies bits [c*(h+1), c*(h+1)+h] — h cell bits plus
one guard position. Within a column, the stones of the *player to move* are
set bits below the guard; the guard is a single 1 at bit `height` (number of
stones in the column). The guard is therefore always the column's
most-significant set bit, which makes the encoding self-delimiting: height,
filled-cell mask and both players' stones are all recoverable with clz/mask
arithmetic, no side tables. An empty column is 0b1; the whole encoding fits
(h+1)*w <= 63 bits — 49 bits for the 7x6 north star — and runs in uint32 when
(h+1)*w <= 31 (boards up to 5x5 / 7x3), which matters on v5e TPUs where
64-bit lanes are emulated. This is the column-wise perfect encoding SURVEY.md
§7 calls for ("Hashing/indexing 4.5e12 C4 states: perfect column-wise
encoding").

A move in column c is branch-free: with g the column's guard bit,
    child = opponent_stones | (guards + g)
— adding g slides that column's guard up one cell, and the mover's new stone
(belonging to the player who will then be the opponent) is implicitly the hole
below the new guard that is absent from the new current-player stones.

Win test is the standard 4-direction bitboard fold on the last mover's stones:
directions {1, h, h+1, h+2} (vertical, diagonals, horizontal) — guard bits are
stripped first, and the per-column spare bit prevents cross-column wraps.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from gamesmanmpi_tpu.core.bitops import popcount, msb_index
from gamesmanmpi_tpu.core.values import LOSE, TIE, UNDECIDED
from gamesmanmpi_tpu.games.base import TensorGame
from gamesmanmpi_tpu.utils.env import env_bool


class Connect4(TensorGame):
    uniform_level_jump = True  # every move drops exactly one stone

    def __init__(self, width: int = 7, height: int = 6, connect: int = 4,
                 sym: bool = False):
        if (height + 1) * width > 63:
            raise ValueError("board too large for uint64 packing")
        self.width, self.height, self.connect = width, height, connect
        self.sym = bool(sym)
        suffix = "_sym" if self.sym else ""
        self.name = f"connect{connect}_{width}x{height}{suffix}"
        self.max_moves = width
        self.num_levels = width * height + 1
        self.max_level_jump = 1
        self.state_bits = (height + 1) * width
        dt = self.state_dtype
        h1 = height + 1
        self._col_masks = np.array(
            [((1 << h1) - 1) << (c * h1) for c in range(width)], dtype=dt
        )
        self._top_bits = np.array(
            [1 << (c * h1 + height) for c in range(width)], dtype=dt
        )
        self._full_mask = dt(
            sum(((1 << height) - 1) << (c * h1) for c in range(width))
        )
        self._bottom_mask = dt(sum(1 << (c * h1) for c in range(width)))
        self._one = dt(1)
        # {vertical, diag down, horizontal, diag up} strides.
        self._dirs = tuple(dt(d) for d in (1, height, h1, height + 2))
        # Whole-word guard extraction (the Ludii-style bitboard fast path,
        # arXiv 2111.02839): masks for the leak-killed down-smear in
        # _decompose. Shifting the whole word right by i moves a column's
        # bottom bits into the column BELOW it; every such leak lands at
        # in-column offset >= h1-i, while every legitimate smear landing
        # (source offset <= h, the guard) stays < h1-i — so one mask per
        # shift distance separates them exactly.
        self._bitboard = env_bool("GAMESMAN_C4_BITBOARD", True)
        self._smear_keep = {}
        i = 1
        while i <= height:
            self._smear_keep[i] = dt(
                sum(((1 << (h1 - i)) - 1) << (c * h1) for c in range(width))
            )
            i <<= 1
        if 1 not in self._smear_keep:  # height 1: smear loop never runs
            self._smear_keep[1] = dt(
                sum(((1 << (h1 - 1)) - 1) << (c * h1) for c in range(width))
            )

    @property
    def cache_key(self):
        # The bitboard flag changes the traced programs; without it in the
        # key an env flip mid-process would reuse kernels lowered the other
        # way (the exact staleness the lowering-tuple convention prevents).
        return (type(self).__qualname__, self.name, self.state_bits,
                self._bitboard)

    def initial_state(self):
        return self._bottom_mask

    def _mirror(self, states):
        """Reflect the board left-right: column c <-> column w-1-c."""
        dt = self.state_dtype
        h1 = self.height + 1
        out = jnp.zeros(states.shape, dtype=dt)
        for c in range(self.width):
            col = (states >> dt(c * h1)) & self._col_masks[0]
            out = out | (col << dt((self.width - 1 - c) * h1))
        return out

    def canonicalize(self, states):
        """Class representative under the mirror symmetry (when sym=1).

        Mirroring commutes with drops and preserves wins, so min(state,
        mirror) picks a consistent representative per class — the standard
        2-fold reduction of Connect-4 solvers (PAPERS.md: 2507.05267).
        """
        if not self.sym:
            return states
        return jnp.minimum(states, self._mirror(states))

    def _decompose(self, states):
        """-> (guards, filled, current, opponent) bitboards for a [B] batch.

        Bitboard fast path (default): all columns' guards are extracted in
        one masked down-smear over the whole word — log2(height) shift+
        and+or passes — instead of a per-column msb loop (width x ~5 ops).
        The smear fills every position at or below each column's msb; the
        per-shift masks kill cross-column leaks exactly (see __init__).
        A contiguous run xored with its own 1-shift leaves only the top
        bit, which per column IS the guard. GAMESMAN_C4_BITBOARD=0 keeps
        the per-column loop for A/B (tests assert bit-equality of both).
        """
        if not self._bitboard:
            return self._decompose_loop(states)
        dt = self.state_dtype
        smear = states
        i = 1
        while i <= self.height:
            smear = smear | ((smear >> dt(i)) & self._smear_keep[i])
            i <<= 1
        guards = smear ^ ((smear >> dt(1)) & self._smear_keep[1])
        filled = smear ^ guards
        current = states ^ guards
        opponent = filled ^ current
        return guards, filled, current, opponent

    def _decompose_loop(self, states):
        """Per-column reference decompose (the pre-ISSUE-14 kernel): kept
        as the parity oracle for the bitboard fast path."""
        dt = self.state_dtype
        guards = jnp.zeros(states.shape, dtype=dt)
        filled = jnp.zeros(states.shape, dtype=dt)
        one = self._one
        for c in range(self.width):
            colv = states & self._col_masks[c]
            g = one << msb_index(colv | one).astype(dt)
            guards = guards | g
            filled = filled | ((g - one) & self._col_masks[c])
        current = states ^ guards
        opponent = filled ^ current
        return guards, filled, current, opponent

    def expand(self, states):
        guards, _, _, opponent = self._decompose(states)
        children = []
        masks = []
        for c in range(self.width):
            g = guards & self._col_masks[c]
            children.append(opponent | (guards + g))
            masks.append((guards & self._top_bits[c]) == 0)
        return jnp.stack(children, axis=-1), jnp.stack(masks, axis=-1)

    def _connected(self, stones):
        won = jnp.zeros(stones.shape, dtype=bool)
        for d in self._dirs:
            x = stones
            for i in range(1, self.connect):
                x = x & (stones >> (d * self.state_dtype(i)))
            won = won | (x != 0)
        return won

    def primitive(self, states):
        guards, filled, _, opponent = self._decompose(states)
        lost = self._connected(opponent)
        full = filled == self._full_mask
        return jnp.where(
            lost, jnp.uint8(LOSE), jnp.where(full, jnp.uint8(TIE), jnp.uint8(UNDECIDED))
        )

    def level_of(self, states):
        _, filled, _, _ = self._decompose(states)
        return popcount(filled)

    def describe(self, state) -> str:
        s = int(state)
        h1 = self.height + 1
        cols = [(s >> (c * h1)) & ((1 << h1) - 1) for c in range(self.width)]
        heights = [cv.bit_length() - 1 for cv in cols]
        total = sum(heights)
        # Even total stones -> first player ('X') to move; current-player
        # stones are the set bits below each guard.
        cur_char, opp_char = ("X", "O") if total % 2 == 0 else ("O", "X")
        rows = []
        for r in range(self.height - 1, -1, -1):
            row = ""
            for c in range(self.width):
                if r >= heights[c]:
                    row += "."
                elif (cols[c] >> r) & 1:
                    row += cur_char
                else:
                    row += opp_char
            rows.append(row)
        return "\n".join(rows)
