"""Subtraction games ("1210" / ten-to-zero family; BASELINE config #5).

Reference counterpart: games/1210.py-style teaching game (SURVEY.md §2.2):
start from `total` objects, a move removes any amount in `moves`; in normal
play the player who cannot move (0 left) has lost (primitive LOSE); in misère
play they have won (primitive WIN).

State = number of objects remaining, as uint64. This is the one shipped game
whose moves jump levels by more than 1 (removing s objects advances the level
by s), so it exercises the engine's multi-level lookup window.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from gamesmanmpi_tpu.core.values import WIN, LOSE, UNDECIDED
from gamesmanmpi_tpu.games.base import TensorGame


class Subtract(TensorGame):
    def __init__(self, total: int = 10, moves=(1, 2), misere: bool = False):
        self.total = int(total)
        self.moves = tuple(sorted(int(m) for m in moves))
        if not self.moves or self.moves[0] < 1:
            raise ValueError("moves must be positive")
        self.misere = misere
        suffix = "m" if misere else ""
        self.name = f"subtract_{total}_{'-'.join(map(str, self.moves))}{suffix}"
        self.max_moves = len(self.moves)
        self.num_levels = self.total + 1
        self.max_level_jump = self.moves[-1]
        self.state_bits = max(int(self.total).bit_length(), 1)
        self._terminal_value = np.uint8(WIN if misere else LOSE)

    def initial_state(self):
        return self.state_dtype(self.total)

    def expand(self, states):
        dt = self.state_dtype
        children = []
        masks = []
        for mv in self.moves:
            amt = dt(mv)
            masks.append(states >= amt)
            children.append(states - amt)
        return jnp.stack(children, axis=-1), jnp.stack(masks, axis=-1)

    def primitive(self, states):
        return jnp.where(states == 0, self._terminal_value, jnp.uint8(UNDECIDED))

    def level_of(self, states):
        return (self.state_dtype(self.total) - states).astype(jnp.int32)
