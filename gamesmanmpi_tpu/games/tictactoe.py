"""Generalized m,n,k tic-tac-toe (3,3,3 = the reference's games/tictactoe.py).

Reference counterpart: games/tictactoe.py — board packed as an int, 4-function
scalar API (SURVEY.md §2.2). Same packing here, tensorized: an m x n board with
k-in-a-row to win, X moving first.

State layout: bits [0, m*n) are X's stones, bits [m*n, 2*m*n) are O's stones,
cell index = row * n + col; packed in uint32 when 2*m*n <= 31 (the 3x3 board),
uint64 otherwise. Player to move: X iff popcount(X plane) == popcount(O plane).
The scalar twin in examples/ref_games/tictactoe.py uses the identical layout,
which is what makes full-table oracle parity tests possible.

Primitive semantics (perspective of player to move): if the *last mover* has k
in a row the mover has lost -> LOSE; else a full board is TIE; else UNDECIDED.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from gamesmanmpi_tpu.core.bitops import popcount
from gamesmanmpi_tpu.core.values import LOSE, TIE, UNDECIDED
from gamesmanmpi_tpu.games.base import TensorGame


def _win_lines(m: int, n: int, k: int, dtype) -> np.ndarray:
    """All k-in-a-row masks on the X bit-plane (bits 0..m*n)."""
    lines = []
    cells = [[r * n + c for c in range(n)] for r in range(m)]
    for r in range(m):
        for c in range(n):
            for dr, dc in ((0, 1), (1, 0), (1, 1), (1, -1)):
                rr, cc = r + dr * (k - 1), c + dc * (k - 1)
                if 0 <= rr < m and 0 <= cc < n:
                    mask = 0
                    for i in range(k):
                        mask |= 1 << cells[r + dr * i][c + dc * i]
                    lines.append(mask)
    return np.array(sorted(set(lines)), dtype=dtype)


class TicTacToe(TensorGame):
    uniform_level_jump = True  # every move places exactly one stone

    def __init__(self, m: int = 3, n: int = 3, k: int = 3, sym: bool = False):
        if 2 * m * n > 63:
            raise ValueError("board too large for uint64 packing")
        self.m, self.n, self.k = m, n, k
        self.cells = m * n
        self.sym = bool(sym)
        suffix = "_sym" if self.sym else ""
        self.name = f"tictactoe_{m}x{n}x{k}{suffix}"
        self.max_moves = self.cells
        self.num_levels = self.cells + 1
        self.max_level_jump = 1
        self.state_bits = 2 * self.cells
        dt = self.state_dtype
        self._lines = _win_lines(m, n, k, dt)
        self._plane_mask = dt((1 << self.cells) - 1)
        self._full = dt((1 << self.cells) - 1)
        self._cells_shift = dt(self.cells)
        self._bits = np.array([1 << i for i in range(self.cells)], dtype=dt)
        self._sym_perms = self._board_symmetries() if self.sym else []

    def initial_state(self):
        return self.state_dtype(0)

    def _board_symmetries(self):
        """Cell permutations of the board's symmetry group, identity excluded.

        Dihedral-4 (8 transforms) for square boards, the Klein group (4) for
        rectangular ones. perm[dst] = src cell index.
        """
        m, n = self.m, self.n
        coord_maps = [
            lambda r, c: (r, n - 1 - c),          # horizontal flip
            lambda r, c: (m - 1 - r, c),          # vertical flip
            lambda r, c: (m - 1 - r, n - 1 - c),  # 180 rotation
        ]
        if m == n:
            coord_maps += [
                lambda r, c: (c, r),                      # main transpose
                lambda r, c: (n - 1 - c, m - 1 - r),      # anti transpose
                lambda r, c: (c, m - 1 - r),              # rot 90
                lambda r, c: (n - 1 - c, r),              # rot 270
            ]
        perms = []
        for f in coord_maps:
            perm = [0] * self.cells
            for r in range(m):
                for c in range(n):
                    sr, sc = f(r, c)
                    perm[r * n + c] = sr * n + sc
            perms.append(tuple(perm))
        return sorted(set(perms))

    def canonicalize(self, states):
        """Min over the board symmetry group applied to both planes (sym=1).

        Board symmetries permute cells identically on the X and O planes and
        map win-lines to win-lines, so they are game automorphisms; taking
        the minimum packed value picks a consistent class representative.
        """
        if not self.sym:
            return states
        dt = self.state_dtype
        best = states
        for perm in self._sym_perms:
            out = jnp.zeros(states.shape, dtype=dt)
            for dst, src in enumerate(perm):
                bit = dt(1)
                x = (states >> dt(src)) & bit
                o = (states >> dt(self.cells + src)) & bit
                out = out | (x << dt(dst)) | (o << dt(self.cells + dst))
            best = jnp.minimum(best, out)
        return best

    def _planes(self, states):
        x = states & self._plane_mask
        o = (states >> self._cells_shift) & self._plane_mask
        return x, o

    def _x_to_move(self, states):
        x, o = self._planes(states)
        return popcount(x) == popcount(o)

    def expand(self, states):
        x, o = self._planes(states)
        occupied = x | o
        x_to_move = self._x_to_move(states)
        # The mover's stone lands at cell i on their own plane.
        zero = self.state_dtype(0)
        shift = jnp.where(x_to_move, zero, self._cells_shift)
        children = []
        masks = []
        for i in range(self.cells):
            bit = self._bits[i]
            empty = (occupied & bit) == 0
            child = states | (bit << shift)
            children.append(child)
            masks.append(empty)
        return jnp.stack(children, axis=-1), jnp.stack(masks, axis=-1)

    def primitive(self, states):
        x, o = self._planes(states)
        # Last mover is the player NOT to move.
        last = jnp.where(self._x_to_move(states), o, x)
        won = jnp.zeros(states.shape, dtype=bool)
        for i in range(self._lines.shape[0]):
            line = self._lines[i]
            won = won | ((last & line) == line)
        full = (x | o) == self._full
        return jnp.where(
            won, jnp.uint8(LOSE), jnp.where(full, jnp.uint8(TIE), jnp.uint8(UNDECIDED))
        )

    def level_of(self, states):
        return popcount(states)

    def describe(self, state) -> str:
        s = int(state)
        rows = []
        for r in range(self.m):
            row = ""
            for c in range(self.n):
                i = r * self.n + c
                if (s >> i) & 1:
                    row += "X"
                elif (s >> (self.cells + i)) & 1:
                    row += "O"
                else:
                    row += "."
            rows.append(row)
        return "\n".join(rows)
