"""Nim (multi-heap), normal and misère (BASELINE config #5 regression family).

Reference counterpart: the Nim-style teaching games in games/ (SURVEY.md §2.2,
§4.2 — "closed-form oracle for property tests": normal-play Nim is a first
player WIN iff the XOR of heap sizes is nonzero).

State layout: heap i occupies `bits` bits starting at i*bits, where `bits` is
sized to hold the largest initial heap. A move removes 1..heap[i] objects from
one heap; with packed heaps that is plain unsigned subtraction at the heap's
offset (uint32 when the packing fits 31 bits, else uint64). Terminal: all
heaps empty — LOSE for the player to move in normal play, WIN in misère.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from gamesmanmpi_tpu.core.values import WIN, LOSE, UNDECIDED
from gamesmanmpi_tpu.games.base import TensorGame


class Nim(TensorGame):
    def __init__(self, heaps=(3, 4, 5), misere: bool = False):
        self.heaps = tuple(int(h) for h in heaps)
        if not self.heaps or min(self.heaps) < 0:
            raise ValueError("heaps must be non-negative")
        self.misere = misere
        self.bits = max(max(self.heaps), 1).bit_length()
        if self.bits * len(self.heaps) > 63:
            raise ValueError("heaps too large for uint64 packing")
        suffix = "m" if misere else ""
        self.name = f"nim_{'-'.join(map(str, self.heaps))}{suffix}"
        # Moves are (heap, amount) pairs, amount in 1..initial[heap].
        self._move_list = [
            (i, t) for i, h in enumerate(self.heaps) for t in range(1, h + 1)
        ]
        self.max_moves = max(len(self._move_list), 1)
        self.num_levels = sum(self.heaps) + 1
        self.max_level_jump = max(max(self.heaps), 1)
        self.state_bits = self.bits * len(self.heaps)
        self._heap_mask = self.state_dtype((1 << self.bits) - 1)

    def initial_state(self):
        s = 0
        for i, h in enumerate(self.heaps):
            s |= h << (i * self.bits)
        return self.state_dtype(s)

    def _heap(self, states, i: int):
        return (states >> self.state_dtype(i * self.bits)) & self._heap_mask

    def expand(self, states):
        dt = self.state_dtype
        children = []
        masks = []
        for i, t in self._move_list:
            amt = dt(t << (i * self.bits))
            masks.append(self._heap(states, i) >= dt(t))
            children.append(states - amt)
        return jnp.stack(children, axis=-1), jnp.stack(masks, axis=-1)

    def primitive(self, states):
        terminal = np.uint8(WIN if self.misere else LOSE)
        return jnp.where(states == 0, terminal, jnp.uint8(UNDECIDED))

    def level_of(self, states):
        total = jnp.zeros(states.shape, dtype=jnp.int32)
        for i in range(len(self.heaps)):
            total = total + self._heap(states, i).astype(jnp.int32)
        return sum(self.heaps) - total
