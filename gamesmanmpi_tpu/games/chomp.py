"""Chomp: the poisoned-cookie game, tensorized.

A GamesCrafters classic in the same family as the reference's shipped
teaching games (SURVEY.md §2.2 games/ dir; the reference's game modules are
interchangeable plugins, so widening the catalog is parity work, not scope
creep). Rules: a width x height bar of cookies; a move picks a remaining
cookie and eats it together with every cookie above and to the right; the
bottom-left cookie is poisoned, and the player forced to eat it — it is the
only one left — loses (primitive LOSE at the poison-only position; eating
poison voluntarily is never legal here, which is the standard normal-play
formulation). Strategy stealing makes every board larger than 1x1 a
first-player WIN, the closed-form check the tests use.

State encoding: the remaining cookies always form a staircase (downward-
closed) region, so the position is exactly the vector of column heights
h_0 >= h_1 >= ... >= h_{w-1}, packed little-endian at bit_length(height)
bits per column — 7x7 fits 21 bits (uint32). A move at (col c, row r)
clamps every column >= c to height r: one vectorized min over the height
lane, unrolled over the static move list (w*h-1 moves).

Moves eat 1..w*h-1 cookies, so levels (cookies eaten) jump arbitrarily —
this is a generic-path (multi-jump) game like the subtraction family, and
the widest-M game in the catalog (kernel width w*h-1).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from gamesmanmpi_tpu.core.values import LOSE, UNDECIDED
from gamesmanmpi_tpu.games.base import TensorGame


class Chomp(TensorGame):
    def __init__(self, width: int = 4, height: int = 3, sym: bool = False):
        if width < 1 or height < 1:
            raise ValueError("board must be at least 1x1")
        self.w = int(width)
        self.h = int(height)
        self.sym = bool(sym)
        if self.sym and self.w != self.h:
            raise ValueError("sym=1 (transpose symmetry) needs a square board")
        self.bits = max(int(self.h).bit_length(), 1)  # heights 0..h
        self.state_bits = self.bits * self.w
        if self.state_bits > 63:
            raise ValueError(f"board too large to pack: {width}x{height}")
        suffix = "_sym" if self.sym else ""
        self.name = f"chomp_{width}x{height}{suffix}"
        # Static move list: every cell but the poisoned (0, 0).
        self._moves = [
            (c, r)
            for c in range(self.w)
            for r in range(self.h)
            if (c, r) != (0, 0)
        ]
        self.max_moves = max(len(self._moves), 1)
        self.num_levels = self.w * self.h
        self.max_level_jump = max(self.w * self.h - 1, 1)
        self.uniform_level_jump = False

    # -------------------------------------------------------------- packing

    def _heights(self, states):
        """[B] packed -> [B, w] int32 column heights."""
        dt = self.state_dtype
        mask = dt((1 << self.bits) - 1)
        cols = [
            ((states >> dt(c * self.bits)) & mask).astype(jnp.int32)
            for c in range(self.w)
        ]
        return jnp.stack(cols, axis=-1)

    def _pack(self, heights):
        """[B, w] int32 -> [B] packed."""
        dt = self.state_dtype
        out = jnp.zeros(heights.shape[:-1], dtype=dt)
        for c in range(self.w):
            out = out | (heights[..., c].astype(dt) << dt(c * self.bits))
        return out

    def canonicalize(self, states):
        """Transpose-class representative (square boards, sym=1).

        Chomp is self-dual under transposing the staircase (the poison cell
        (0,0) is fixed), so value/remoteness are invariant within a class.
        The transposed height vector is h'_r = #{c : h_c > r} — a
        branch-free count per row lane.
        """
        if not self.sym:
            return states
        hs = self._heights(states)  # [B, w]
        rows = [
            jnp.sum((hs > r).astype(jnp.int32), axis=-1)
            for r in range(self.h)
        ]
        flipped = self._pack(jnp.stack(rows, axis=-1))
        return jnp.minimum(states, flipped)

    # -------------------------------------------------------------- protocol

    def initial_state(self):
        packed = 0
        for c in range(self.w):
            packed |= self.h << (c * self.bits)
        return self.state_dtype(packed)

    def expand(self, states):
        if not self._moves:  # 1x1 board: poison only, no legal moves ever
            shape = states.shape + (1,)
            return (
                jnp.full(shape, self.sentinel, dtype=states.dtype),
                jnp.zeros(shape, dtype=bool),
            )
        hs = self._heights(states)  # [B, w]
        col_idx = jnp.arange(self.w)
        children = []
        masks = []
        for c, r in self._moves:
            legal = hs[..., c] > r
            clamped = jnp.where(col_idx >= c, jnp.minimum(hs, r), hs)
            children.append(self._pack(clamped))
            masks.append(legal)
        return jnp.stack(children, axis=-1), jnp.stack(masks, axis=-1)

    def primitive(self, states):
        # Poison-only board: h = (1, 0, ..., 0), packed == 1.
        return jnp.where(
            states == self.state_dtype(1),
            jnp.uint8(LOSE),
            jnp.uint8(UNDECIDED),
        )

    def level_of(self, states):
        return self.w * self.h - jnp.sum(self._heights(states), axis=-1)

    def describe(self, state) -> str:
        hs = [
            (int(state) >> (c * self.bits)) & ((1 << self.bits) - 1)
            for c in range(self.w)
        ]
        return f"{self.name} heights={hs}"
