"""The tensorized game-plugin boundary.

This is the rebuild of the reference's L3 plugin API (SURVEY.md §1, §2.1.1):
a game there is a module with `initial_position`, `gen_moves(pos)`,
`do_move(pos, move)`, `primitive(pos)` operating on one position at a time.
On TPU the same boundary is expressed over *batches of bit-packed uint64
positions*: `expand` fuses gen_moves+do_move over a whole frontier, and
`primitive` is vectorized. Unmodified reference-style scalar modules are
lifted onto this protocol by gamesmanmpi_tpu.compat.

One addition relative to the reference: `level_of`. The reference's top-down
memoized recursion needs no global ordering; a level-synchronous retrograde
sweep does. `level_of` must be a *topological level function*: every move from
state s leads to a state with strictly greater level, and
level_of(child) - level_of(s) <= max_level_jump. For the shipped games this is
just "pieces placed" / "objects removed" — the standard retrograde-analysis
sectioning (PAPERS.md: Pentago). Games where every move advances the level by
exactly 1 (tic-tac-toe, connect4) have max_level_jump == 1.

Engine-side contracts (so game kernels stay branch-free):
  - expand/primitive may be called on SENTINEL padding lanes; their output
    there is garbage and the engine masks it out. Kernels must merely not
    crash on sentinel input (uint64 arithmetic wraps; that is fine).
  - expand returns (children [B, max_moves] uint64, mask [B, max_moves] bool);
    lanes with mask False are ignored by the engine.
  - primitive returns uint8 values from the perspective of the player to move
    (WIN/LOSE/TIE/UNDECIDED), UNDECIDED meaning non-terminal.
"""

from __future__ import annotations

import abc

import numpy as np

from gamesmanmpi_tpu.core.bitops import sentinel_for, state_dtype_for


class TensorGame(abc.ABC):
    """A two-player abstract game over batches of packed unsigned states."""

    #: short name used by the registry / CLI
    name: str = "game"
    #: static maximum number of moves from any position (M in [B, M] kernels)
    max_moves: int
    #: upper bound (exclusive) on level_of over reachable states; the engines
    #: enforce it during forward discovery (a broken level_of would otherwise
    #: loop forever) and use it for capacity planning
    num_levels: int
    #: max of level_of(child) - level_of(parent) over all moves
    max_level_jump: int = 1
    #: number of bits a packed state occupies. Games that fit 31 bits run in
    #: uint32 (v5e TPUs emulate 64-bit; narrow states sort ~2x faster and
    #: compile much smaller programs); wider games run in uint64. The bound is
    #: strict (31/63, not 32/64) so the all-ones SENTINEL can never collide
    #: with a real state.
    state_bits: int = 63
    #: True when *every* move advances level_of by exactly 1 (tic-tac-toe,
    #: connect4: level == stones placed). Engines then take the device-resident
    #: fast path: each level's children all land in level k+1, so frontiers
    #: chain on-device with no host-side pool merging.
    uniform_level_jump: bool = False

    @property
    def state_dtype(self):
        """Narrowest numpy dtype holding a packed state (uint32/uint64)."""
        return state_dtype_for(self.state_bits)

    @property
    def sentinel(self):
        """The padding sentinel in this game's state dtype."""
        return sentinel_for(self.state_dtype)

    @property
    def cache_key(self):
        """Hashable identity for compiled-kernel caching.

        Two game instances with equal cache_key must trace to identical
        kernels; the engines key their module-level jit caches on this, so
        re-instantiated solvers (benchmark repeats, CLI reruns in-process)
        reuse XLA executables instead of recompiling. Parametrized built-ins
        encode every parameter in `name`; override if that ever stops holding.
        """
        return (type(self).__qualname__, self.name, self.state_bits)

    def canonicalize(self, states):
        """Map each state to its symmetry-class representative.

        Identity by default. Games with board symmetries (connect4 mirror,
        tic-tac-toe dihedral group) override this with a branch-free
        min-over-transforms; the engines then solve only canonical
        representatives — the standard state-space reduction of retrograde
        analysis (PAPERS.md: Pentago 8-fold, 2507.05267 mirror). The override
        must be a game automorphism projection: canonicalize(do_move(s)) must
        equal canonicalize(do_move(canonicalize(s))) for the matching move,
        and value/remoteness must be invariant within a class. The reference
        has no symmetry reduction, so this is off unless a game opts in
        (spec flag `sym=1`); results are observably identical either way
        (root value/remoteness, and lookup() canonicalizes queries).
        """
        return states

    @abc.abstractmethod
    def initial_state(self):
        """The packed initial position (reference: `initial_position`)."""

    @abc.abstractmethod
    def expand(self, states):
        """Batched gen_moves+do_move: [B] -> (children [B, M], mask [B, M])."""

    @abc.abstractmethod
    def primitive(self, states):
        """Batched primitive value: [B] -> uint8 [B]."""

    @abc.abstractmethod
    def level_of(self, states):
        """Topological level of each state: [B] -> int32 [B]."""

    def describe(self, state) -> str:
        """Optional human-readable rendering of one packed state (debugging)."""
        return f"{self.name} state {int(state):#x}"
