"""Game registry: the tensorized counterparts of the reference's games/ dir.

The reference CLI takes a path to a game module (solver_launcher.py,
SURVEY.md §3.1); here built-in games are constructed from a spec string, and
reference-style module files are still accepted via gamesmanmpi_tpu.compat.

Spec grammar: "name" or "name:key=value,key=value", e.g.
    tictactoe            tictactoe:m=4,n=4,k=4
    connect4:w=5,h=4     subtract:total=10,moves=1-2,misere=1
    nim:heaps=3-4-5      nim:heaps=1-2-10,misere=1
    chomp:w=4,h=3        chomp:w=3,h=3,sym=1

A spec ending in ".json" is a declarative GameSpec file (docs/GAMEDSL.md)
compiled on the fly by gamesmanmpi_tpu.gamedsl — new games with zero
Python:
    examples/specs/gomoku_4x3x3.json
"""

from __future__ import annotations

from gamesmanmpi_tpu.games.base import TensorGame
from gamesmanmpi_tpu.games.tictactoe import TicTacToe
from gamesmanmpi_tpu.games.subtract import Subtract
from gamesmanmpi_tpu.games.nim import Nim
from gamesmanmpi_tpu.games.connect4 import Connect4
from gamesmanmpi_tpu.games.chomp import Chomp


def _parse_kwargs(spec: str) -> dict:
    out = {}
    if not spec:
        return out
    for item in spec.split(","):
        k, _, v = item.partition("=")
        out[k.strip()] = v.strip()
    return out


def _intlist(v: str):
    return tuple(int(x) for x in v.replace("-", " ").split())


def get_game(spec: str) -> TensorGame:
    """Construct a built-in game from a spec string (see module docstring)."""
    if spec.strip().lower().endswith(".json"):
        # A declarative GameSpec file: compile it. SpecError subclasses
        # ValueError, so callers' bad-spec handling covers both paths.
        from gamesmanmpi_tpu.gamedsl.compiler import compile_spec
        try:
            return compile_spec(spec.strip())
        except OSError as e:
            raise ValueError(
                f"cannot read game spec file {spec!r}: {e}"
            ) from e
    name, _, rest = spec.partition(":")
    kw = _parse_kwargs(rest)
    name = name.strip().lower()
    def _flag(key):
        v = kw.get(key, "0").strip().lower()
        if v in ("0", "false", "no", "off", ""):
            return False
        if v in ("1", "true", "yes", "on"):
            return True
        raise ValueError(f"bad boolean for {key!r} in spec {spec!r}: {v!r}")

    if name in ("tictactoe", "ttt", "mnk"):
        return TicTacToe(
            m=int(kw.get("m", 3)), n=int(kw.get("n", 3)), k=int(kw.get("k", 3)),
            sym=_flag("sym"),
        )
    if name in ("connect4", "c4", "win4", "connectn"):
        return Connect4(
            width=int(kw.get("w", kw.get("width", 7))),
            height=int(kw.get("h", kw.get("height", 6))),
            connect=int(kw.get("k", kw.get("connect", 4))),
            sym=_flag("sym"),
        )
    if name in ("subtract", "1210", "tentozero"):
        return Subtract(
            total=int(kw.get("total", kw.get("n", 10))),
            moves=_intlist(kw.get("moves", "1-2")),
            misere=_flag("misere"),
        )
    if name == "nim":
        return Nim(
            heaps=_intlist(kw.get("heaps", "3-4-5")),
            misere=_flag("misere"),
        )
    if name == "chomp":
        return Chomp(
            width=int(kw.get("w", kw.get("width", 4))),
            height=int(kw.get("h", kw.get("height", 3))),
            sym=_flag("sym"),
        )
    raise KeyError(f"unknown game spec {spec!r}")


__all__ = [
    "TensorGame",
    "TicTacToe",
    "Subtract",
    "Nim",
    "Connect4",
    "Chomp",
    "get_game",
]
