"""Frontier deduplication: sort-unique over packed states.

The reference dedups implicitly through its per-rank memo dict — a position
seen twice hits `resolved`/`pending` and is not re-expanded (src/process.py,
SURVEY.md §3.2). A dict is hostile to TPUs; the level-synchronous rebuild
dedups each frontier wholesale with sort + neighbor-compare + resort, a
static-shape O(n log n) pattern XLA maps well (SURVEY.md §7 "Dedup at scale").
"""

import jax.numpy as jnp

from gamesmanmpi_tpu.core.bitops import sentinel_for


def sort_unique(states):
    """Sort states, replace duplicates with SENTINEL, resort, count uniques.

    Input: [N] uint32/uint64 (may contain SENTINEL padding of the same dtype).
    Returns (sorted_unique [N] with all uniques first then SENTINEL tail,
             count of unique non-sentinel entries, int32).
    """
    sentinel = sentinel_for(states.dtype)
    s = jnp.sort(states)
    dup = jnp.concatenate([jnp.zeros((1,), bool), s[1:] == s[:-1]])
    s = jnp.where(dup, sentinel, s)
    s = jnp.sort(s)
    count = jnp.sum(s != sentinel).astype(jnp.int32)
    return s, count
