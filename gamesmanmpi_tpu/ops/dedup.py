"""Frontier deduplication: sort-unique over packed states.

The reference dedups implicitly through its per-rank memo dict — a position
seen twice hits `resolved`/`pending` and is not re-expanded (src/process.py,
SURVEY.md §3.2). A dict is hostile to TPUs; the level-synchronous rebuild
dedups each frontier wholesale with sort + neighbor-compare + resort, a
static-shape O(n log n) pattern XLA maps well (SURVEY.md §7 "Dedup at scale").
"""

import jax.numpy as jnp

from gamesmanmpi_tpu.core.bitops import sentinel_for
# sort1 dispatches to XLA's sort network, or to the merge ladder under
# GAMESMAN_SORT=merge (resolved at build time by kernel builders — see
# sort1's docstring; engine.get_kernel keys its cache on the flag).
from gamesmanmpi_tpu.ops.mergesort import sort1 as _sort


def sort_unique(states, merge: bool | None = None):
    """Sort states, drop duplicates/sentinels, compact to the front.

    Input: [N] uint32/uint64 (may contain SENTINEL padding of the same dtype).
    Returns (sorted_unique [N] with all uniques first then SENTINEL tail,
             count of unique non-sentinel entries, int32).

    Sort, mark duplicate-run followers as SENTINEL, then re-sort: sentinels
    (all-ones) sink to the tail, compacting survivors to the front in sorted
    order. The obvious O(N) alternative — cumsum + scatter compaction — is
    1.7x SLOWER on TPU v5e (tools/microbench.py: 393 ms vs 231 ms at 32M
    uint32): XLA lowers arbitrary-index scatters to a serialized path, while
    its TPU sort is a fast vectorized network. Mark+resort keeps the whole
    kernel on the happy path.
    """
    sentinel = sentinel_for(states.dtype)
    s = _sort(states, merge)
    first = jnp.concatenate([jnp.ones((1,), bool), s[1:] != s[:-1]])
    keep = first & (s != sentinel)
    out = _sort(jnp.where(keep, s, sentinel), merge)
    count = jnp.sum(keep).astype(jnp.int32)
    return out, count
