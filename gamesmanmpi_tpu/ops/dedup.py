"""Frontier deduplication: sort-unique over packed states.

The reference dedups implicitly through its per-rank memo dict — a position
seen twice hits `resolved`/`pending` and is not re-expanded (src/process.py,
SURVEY.md §3.2). A dict is hostile to TPUs; the level-synchronous rebuild
dedups each frontier wholesale with sort + neighbor-compare + resort, a
static-shape O(n log n) pattern XLA maps well (SURVEY.md §7 "Dedup at scale").
"""

import jax.numpy as jnp

from gamesmanmpi_tpu.core.bitops import sentinel_for
from gamesmanmpi_tpu.utils.platform import platform_auto_flag
# sort1 dispatches to XLA's sort network, or to the merge ladder under
# GAMESMAN_SORT=merge (resolved at build time by kernel builders — see
# sort1's docstring; engine.get_kernel keys its cache on the flag).
from gamesmanmpi_tpu.ops.mergesort import sort1 as _sort


def compact_method() -> str:
    """Compaction lowering for the dedup's keep-mask, resolved at
    builder/cache-key time for the executing platform. 'resort' (re-sort
    with sentinels sinking to the tail) on accelerators: cumsum+scatter is
    1.7x SLOWER on the v5e (tools/microbench.py: 393 ms vs 231 ms at 32M
    uint32) because XLA serializes arbitrary-index scatters while its TPU
    sort is a fast vectorized network. On CPU the scatter is O(N) and
    beats the re-sort (~1.4x at 4M uint64). GAMESMAN_COMPACT=
    resort|scatter overrides (unknown values raise)."""
    return platform_auto_flag(
        "GAMESMAN_COMPACT", accel="resort", cpu="scatter",
        choices=("resort", "scatter"),
    )


def compaction_sort_bytes(itemsize: int) -> int:
    """Sort-operand bytes per element the compaction adds — the one place
    the traffic model knows 'resort' is a sort and 'scatter' is not
    (callers sum this into bytes_sorted roofline denominators)."""
    return itemsize if compact_method() == "resort" else 0


def compact_sorted(s, keep, merge: bool | None = None,
                   compact: str | None = None):
    """Compact kept entries of a SORTED array to the front (sorted order
    preserved), sentinel tail. keep must be False on sentinel entries.
    compact: lowering; kernel builders resolve via compact_method() at
    builder time and pass it down (see lookup_sorted's method param for
    why). None = resolve at trace time."""
    sentinel = sentinel_for(s.dtype)
    if compact is None:
        compact = compact_method()
    if compact == "scatter":
        n = s.shape[0]
        idx = jnp.cumsum(keep.astype(jnp.int32)) - 1
        # Dropped (out-of-bounds) writes for non-kept entries; kept ones
        # land at their run index. No slot is written twice.
        return jnp.full_like(s, sentinel).at[
            jnp.where(keep, idx, n)
        ].set(s, mode="drop")
    return _sort(jnp.where(keep, s, sentinel), merge)


def sort_unique(states, merge: bool | None = None,
                compact: str | None = None):
    """Sort states, drop duplicates/sentinels, compact to the front.

    Input: [N] uint32/uint64 (may contain SENTINEL padding of the same dtype).
    Returns (sorted_unique [N] with all uniques first then SENTINEL tail,
             count of unique non-sentinel entries, int32).

    Sort, mark duplicate-run followers as SENTINEL, then compact (re-sort
    on accelerators, cumsum+scatter on CPU — see compact_method).
    """
    sentinel = sentinel_for(states.dtype)
    s = _sort(states, merge)
    first = jnp.concatenate([jnp.ones((1,), bool), s[1:] != s[:-1]])
    keep = first & (s != sentinel)
    out = compact_sorted(s, keep, merge, compact)
    count = jnp.sum(keep).astype(jnp.int32)
    return out, count
