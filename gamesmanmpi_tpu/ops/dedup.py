"""Frontier deduplication: sort-unique over packed states.

The reference dedups implicitly through its per-rank memo dict — a position
seen twice hits `resolved`/`pending` and is not re-expanded (src/process.py,
SURVEY.md §3.2). A dict is hostile to TPUs; the level-synchronous rebuild
dedups each frontier wholesale with sort + neighbor-compare + resort, a
static-shape O(n log n) pattern XLA maps well (SURVEY.md §7 "Dedup at scale").
"""

import jax.numpy as jnp

from gamesmanmpi_tpu.core.bitops import sentinel_for


def sort_unique(states):
    """Sort states, drop duplicates/sentinels, compact to the front.

    Input: [N] uint32/uint64 (may contain SENTINEL padding of the same dtype).
    Returns (sorted_unique [N] with all uniques first then SENTINEL tail,
             count of unique non-sentinel entries, int32).

    One sort + prefix-sum scatter compaction: after the sort, the survivor
    of each duplicate run is its first element; cumsum of the keep-mask is
    each survivor's target slot, and a scatter-with-drop writes them — O(N)
    instead of the naive mark-and-resort second O(N log N) pass.
    """
    sentinel = sentinel_for(states.dtype)
    s = jnp.sort(states)
    first = jnp.concatenate([jnp.ones((1,), bool), s[1:] != s[:-1]])
    keep = first & (s != sentinel)
    idx = jnp.cumsum(keep) - 1  # target slot per survivor (sorted order kept)
    out = jnp.full(s.shape, sentinel, dtype=s.dtype)
    out = out.at[jnp.where(keep, idx, s.shape[0])].set(s, mode="drop")
    count = jnp.sum(keep).astype(jnp.int32)
    return out, count
