"""Dedup provenance: carry origin slots through the frontier sort.

The forward pass's dedup sort already determines where every child lands
in the next level's sorted table. Keeping that knowledge costs one extra
pair sort in forward (the "pair-sort trick": sort (child, origin-slot)
pairs, number the unique runs, route the run index back through a second
pair sort on the origin) and turns the backward pass into pure index
arithmetic — gathers + combine, no search and no re-expansion. This is
the shape both Pentago's parallel in-core retrograde analysis
(arXiv:1404.0743) and the consumer-grade 7x6 Connect-Four solve
(arXiv:2507.05267) use to keep retrograde passes bandwidth-bound.

Two consumers share these kernels (the reason they live in ops/, not in
an engine):

* the single-device engine (solve/engine.py expand_provenance /
  resolve_provenance): uidx indexes the next level's sorted prefix
  directly, the backward resolve is one gather per child;
* the sharded engine (parallel/sharded.py, GAMESMAN_BACKWARD=edges):
  the dedup runs on the OWNER shard after the all_to_all, so the
  unique-index is within the owner's level slice and travels back to the
  parent shard as a routed "edge" — the backward step all_to_alls the
  stored edge indices instead of re-expanded child states.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from gamesmanmpi_tpu.core.bitops import sentinel_for
from gamesmanmpi_tpu.core.codec import pack_cells, unpack_cells
from gamesmanmpi_tpu.core.values import UNDECIDED
from gamesmanmpi_tpu.ops.dedup import compact_sorted
from gamesmanmpi_tpu.ops.mergesort import sort_with_payload


def dedup_provenance(flat, merge: bool | None = None,
                     compact: str | None = None):
    """Sort-unique [N] states AND report where each input slot landed.

    Returns (uniq [N] sorted uniques first + sentinel tail, count int32,
    uidx [N] int32): uidx[j] is the index of flat[j] within the `uniq`
    prefix, -1 for sentinel slots. Every slot in a duplicate run shares
    the survivor's unique-index (cumsum over run-first markers is
    constant within the run).

    merge/compact: sort-backend and compaction lowerings, resolved at
    BUILD time by kernel builders (None = read env/platform at trace
    time; see ops.mergesort.sort1, ops.dedup.compact_method).
    """
    sentinel = sentinel_for(flat.dtype)
    origin = jax.lax.iota(jnp.int32, flat.shape[0])
    # Sorts dispatch through ops.mergesort: XLA's network by default, the
    # elementwise merge ladder under GAMESMAN_SORT=merge.
    s, o = sort_with_payload(flat, origin, merge)
    first = jnp.concatenate([jnp.ones((1,), bool), s[1:] != s[:-1]])
    keep = first & (s != sentinel)
    uid = jnp.cumsum(keep.astype(jnp.int32)) - 1
    uid = jnp.where(s != sentinel, uid, -1)
    _, uidx = sort_with_payload(o, uid, merge)
    uniq = compact_sorted(s, keep, merge, compact)
    count = jnp.sum(keep).astype(jnp.int32)
    return uniq, count, uidx


def gather_cells(uidx, wvals, wrem):
    """Packed (value, remoteness) cells for stored unique-indices.

    uidx: [...] int32 indices into the deeper level's prefix (-1 = no
    child — yields the UNDECIDED cell 0). wvals/wrem: the deeper level's
    solved values [W] uint8 / remoteness [W] int32. Returns uint32 cells,
    same shape as uidx.
    """
    cells = pack_cells(wvals, wrem)
    got = cells[jnp.clip(uidx, 0, cells.shape[0] - 1)]
    return jnp.where(uidx >= 0, got, jnp.uint32(0))


def provenance_sort_bytes(itemsize: int, compaction: int) -> int:
    """Sort-operand bytes per child slot of dedup_provenance: the
    (state, i32) pair sort + the (i32, i32) inversion pair sort + the
    compaction (callers sum this into bytes_sorted roofline
    denominators; see docs/ARCHITECTURE.md "Efficiency accounting")."""
    return itemsize + 12 + compaction


def combine_edge_cells(cells_flat, max_moves: int):
    """Unpack per-edge reply cells into ([B, M] values, remoteness, mask).

    cells_flat: [B*M] uint32 packed cells in parent child-slot order,
    cell 0 (UNDECIDED) marking no-edge slots — a real edge always carries
    a decided value, so the UNDECIDED cell doubles as the invalid-slot
    flag exactly like the lookup path's miss flag.
    """
    cv, cr = unpack_cells(cells_flat.reshape(-1, max_moves))
    mask = cv != UNDECIDED
    return cv, cr, mask
