"""Static-shape capacity planning: bucketed padding.

XLA compiles one program per shape; per-level frontier sizes vary wildly
(SURVEY.md §7 "Dynamic frontier vs static shapes"). We round every frontier up
to a power-of-two bucket and pad with SENTINEL, so the whole solve reuses a
small, bounded set of compiled programs regardless of level sizes.
"""

import numpy as np

from gamesmanmpi_tpu.core.bitops import SENTINEL

# Smallest bucket: keeps tiny levels from generating many near-empty programs.
MIN_BUCKET = 256


def bucket_size(n: int, minimum: int = MIN_BUCKET) -> int:
    """Smallest power-of-two >= max(n, minimum)."""
    return 1 << int(max(n, minimum, 1) - 1).bit_length()


def pad_to_bucket(states: np.ndarray, minimum: int = MIN_BUCKET) -> np.ndarray:
    """Pad a 1-D uint64 host array to its bucket size with SENTINEL."""
    states = np.asarray(states, dtype=np.uint64)
    cap = bucket_size(states.shape[0], minimum)
    out = np.full(cap, SENTINEL, dtype=np.uint64)
    out[: states.shape[0]] = states
    return out
