"""Static-shape capacity planning: bucketed padding.

XLA compiles one program per shape; per-level frontier sizes vary wildly
(SURVEY.md §7 "Dynamic frontier vs static shapes"). We round every frontier up
to a power-of-two bucket and pad with the dtype's SENTINEL, so the whole solve
reuses a small, bounded set of compiled programs regardless of level sizes.
This matters double in environments where XLA compilation is remote/expensive:
every distinct shape is a compile, so the engines also keep capacities
monotone across levels (solve/engine.py) to bound the shape count by
log2(max frontier), not by level count.
"""

import numpy as np

from gamesmanmpi_tpu.core.bitops import sentinel_for

# Smallest bucket: keeps tiny levels from generating many near-empty programs.
MIN_BUCKET = 256


def bucket_size(n: int, minimum: int = MIN_BUCKET) -> int:
    """Smallest power-of-two >= max(n, minimum)."""
    return 1 << int(max(n, minimum, 1) - 1).bit_length()


def pad_to_bucket(states: np.ndarray, minimum: int = MIN_BUCKET) -> np.ndarray:
    """Pad a 1-D unsigned host array to its bucket size with SENTINEL.

    The dtype (and therefore the sentinel) is taken from the input array.
    """
    states = np.asarray(states)
    cap = bucket_size(states.shape[0], minimum)
    out = np.full(cap, sentinel_for(states.dtype), dtype=states.dtype)
    out[: states.shape[0]] = states
    return out


def pad_to(states: np.ndarray, cap: int) -> np.ndarray:
    """Pad a 1-D unsigned host array to exactly `cap` with SENTINEL."""
    states = np.asarray(states)
    out = np.full(cap, sentinel_for(states.dtype), dtype=states.dtype)
    out[: states.shape[0]] = states
    return out
