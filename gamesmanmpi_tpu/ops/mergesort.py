"""Merge-ladder sort: batched row sorts + bitonic merge stages, pure XLA.

Why this exists: the BFS engines are sort-bound on TPU — XLA's sort ran at
~0.85 GB/s operand throughput on the v5e (tools/microbench.py), far below
the chip's ~819 GB/s HBM roofline. A bitonic MERGE of two sorted arrays is
log2(n) compare-exchange stages, each a pure elementwise min/max pass that
XLA fuses and runs at memory bandwidth — no sorting network. Sorting via
"row-sort small chunks, then merge pairwise" therefore replaces most of
the sort network with elementwise passes:

  sort [R, C] rows (XLA batched sort, C sized so a row is cheap)
  repeat log2(R) times: merge row pairs [R, C] -> [R/2, 2C]

Total stage count ~ log2(R) * log2(N) elementwise passes vs the sort
network's ~log2(N)^2/2 — and the passes are cheaper. Whether that wins on
the real chip is an empirical question (tools/microbench2.py measures
both); the engines adopt it behind GAMESMAN_SORT=merge, default XLA sort,
so the flag can flip on measurement without code changes.

Correctness notes: inputs are padded to a power-of-two length with the
all-ones sentinel (which sorts last, matching the engines' padding
convention); merging keys with an i32 payload uses compare-on-key
exchanges of both arrays.

Known limitation (2026-07-30): with GAMESMAN_SORT=merge set for an entire
test-suite process, XLA's CPU compiler segfaulted twice, reproducibly,
while compiling an UNRELATED backend-independent kernel late in the run
(tests/test_symmetry.py chomp case; the same test passes in isolation and
the whole suite passes under the default backend). The merge ladder's
unrolled stage chain produces much larger HLO than lax.sort, and
compiling many of those earlier in the process appears to leave the CPU
compiler in a state where a later compile crashes — an upstream stress
bug, not a correctness issue (every equivalence test passes). Treat
GAMESMAN_SORT=merge as a per-process experimental flag; the default
stays "xla" until the chip measurement decides (docs/CHIP_PLAN.md).

MEASURED no-go (chip session r04, v5e): merge_sort u32 [32M] =
1.13-1.16 s across row sizes vs jnp.sort's 0.15 s — the ladder LOSES
7.5x. The premise failed on silicon: XLA's one-shot sort ran at
1.76 GB/s (not the 0.85 GB/s round-3 figure), while the ladder's many
full-array elementwise stages each pay real HBM traffic (measured
elementwise ceiling ~4 GB/s through the relay) and their sum dwarfs the
sort network. The u32+payload variant additionally crashed the relay's
compile helper (HTTP 500). The flag stays for CPU experiments; do NOT
flip it for accelerators.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from gamesmanmpi_tpu.core.bitops import sentinel_for
from gamesmanmpi_tpu.utils.env import env_int, env_str


def use_merge_sort() -> bool:
    """Engines consult this flag at trace time (GAMESMAN_SORT=merge)."""
    return env_str("GAMESMAN_SORT", "xla") == "merge"


def backend_key():
    """Cache-key element describing the resolved sort backend.

    Includes the row width when the merge backend is active: it is read at
    trace time too (see _row_width), so two row settings are two different
    programs. (A GAMESMAN_SORT_ROW flip between scheduling a background
    compile and its worker tracing can still race — flip row widths only
    at process start or with inline-jitted kernels, as tools/microbench2
    does.)
    """
    if not use_merge_sort():
        return "xla"
    return ("merge", env_str("GAMESMAN_SORT_ROW", "2048"))


def _pay_max(dtype):
    """Largest value of an integer payload dtype (pad marker)."""
    return np.iinfo(np.dtype(dtype)).max


def _row_width(n: int) -> int:
    """Base row width for the row-sort stage (power of two).

    GAMESMAN_SORT_ROW tunes it; default 2048 keeps each row's sort network
    shallow while leaving most of the work to the merge ladder.
    """
    w = env_int("GAMESMAN_SORT_ROW", 2048)
    w = 1 << max(int(w).bit_length() - 1, 0)  # round down to a power of two
    return max(min(w, n), 1)


def _merge_rows(a, b, *payloads_ab):
    """Merge sorted rows pairwise: a, b [R, C] -> [R, 2C] sorted rows.

    concat(a, reverse(b)) is bitonic per row; log2(2C) compare-exchange
    stages sort it. With payloads, exchanges compare (key, first payload)
    lexicographically: merge_sort pads with MAX payloads under sentinel
    keys, and the tie-break guarantees every REAL (sentinel, payload) pair
    sorts before the padding — without it, truncating back to the input
    length could keep fake pad pairs and drop real ones (which would
    corrupt expand_provenance's origin permutation under
    GAMESMAN_SORT=merge).
    payloads_ab: (pa, pb) pairs following a/b.
    """
    R, C = a.shape
    z = jnp.concatenate([a, b[:, ::-1]], axis=1)  # [R, 2C] bitonic rows
    ps = [
        jnp.concatenate([pa, pb[:, ::-1]], axis=1)
        for pa, pb in zip(payloads_ab[0::2], payloads_ab[1::2])
    ]
    n = 2 * C
    s = n // 2
    while s >= 1:
        y = z.reshape(R, -1, 2, s)
        k0, k1 = y[:, :, 0, :], y[:, :, 1, :]
        if ps:
            q0 = ps[0].reshape(R, -1, 2, s)
            lo_is_first = (k0 < k1) | (
                (k0 == k1) & (q0[:, :, 0, :] <= q0[:, :, 1, :])
            )
        else:
            lo_is_first = k0 <= k1
        lo = jnp.where(lo_is_first, k0, k1)
        hi = jnp.where(lo_is_first, k1, k0)
        z = jnp.stack([lo, hi], axis=2).reshape(R, n)
        new_ps = []
        for p in ps:
            q = p.reshape(R, -1, 2, s)
            plo = jnp.where(lo_is_first, q[:, :, 0, :], q[:, :, 1, :])
            phi = jnp.where(lo_is_first, q[:, :, 1, :], q[:, :, 0, :])
            new_ps.append(jnp.stack([plo, phi], axis=2).reshape(R, n))
        ps = new_ps
        s //= 2
    return (z, *ps)


def sort1(x, merge: bool | None = None):
    """Flag-dispatched key sort.

    merge=None reads the env flag AT TRACE TIME — fine for direct/eager
    callers. Kernel builders must instead resolve use_merge_sort() at
    BUILD time and pass it explicitly: background precompile workers trace
    later, and an ambient read there could disagree with the cache key
    sampled when the kernel was scheduled.
    """
    if use_merge_sort() if merge is None else merge:
        return merge_sort(x)
    return jnp.sort(x)


def sort_with_payload(keys, payload, merge: bool | None = None):
    """Flag-dispatched (keys, payload) sort by keys (see sort1 re: merge).

    Integer payload only; with the merge backend, signed non-negative keys
    are viewed as unsigned (order-preserving) so sentinel padding works.
    """
    if not (use_merge_sort() if merge is None else merge):
        import jax

        return jax.lax.sort((keys, payload), num_keys=1, is_stable=False)
    kd = np.dtype(keys.dtype)
    if kd.kind == "i":
        # Permutation/index keys are non-negative; the unsigned view keeps
        # their order and gives merge_sort a valid sentinel.
        k2, p2 = merge_sort(keys.astype(np.dtype(f"u{kd.itemsize}")),
                            payload)
        return k2.astype(keys.dtype), p2
    return merge_sort(keys, payload)


def sort_rank(x, merge: bool | None = None):
    """Key sort that also reports where every input slot landed.

    Returns (sorted [N], rank_back [N] int32): rank_back[j] is the index
    of input slot j within the sorted output. One (key, origin) pair sort
    plus one permutation-inverting scatter — the sorted origin column is a
    permutation of iota, so `zeros.at[origin].set(iota)` inverts it in one
    O(n) pass. This is the fused half of the rank/sort+dedup kernel
    (ops/fused.fused_dedup_provenance 'scatterinv'): dedup_provenance
    reconstructs the same mapping with a SECOND (origin, uid) pair sort,
    i.e. a full extra ~log2(n)-pass network of HBM traffic per level.

    merge: sort-backend flag, resolved at BUILD time by kernel builders
    (see sort1).
    """
    import jax

    origin = jax.lax.iota(jnp.int32, x.shape[0])
    s, o = sort_with_payload(x, origin, merge)
    rank_back = jnp.zeros_like(origin).at[o].set(origin)
    return s, rank_back


def merge_sort(x, *payloads):
    """Sort [N] keys ascending (with optional same-length payloads carried).

    Pads to a power of two with the key dtype's sentinel; returns arrays of
    the ORIGINAL length. Stable ordering is NOT guaranteed (the engines'
    uses — dedup, permutation routing — don't need stability).
    """
    n = x.shape[0]
    n2 = 1 << max((n - 1).bit_length(), 0)
    sentinel = sentinel_for(np.dtype(x.dtype))
    if n2 != n:
        pad = jnp.full((n2 - n,), sentinel, x.dtype)
        x = jnp.concatenate([x, pad])
        # MAX payload under the sentinel key + the merge stages' payload
        # tie-break => padding sorts strictly after every real pair, so
        # truncation back to n can only ever drop padding.
        payloads = tuple(
            jnp.concatenate([
                p,
                jnp.full((n2 - n,), _pay_max(p.dtype), p.dtype),
            ])
            for p in payloads
        )
    C = _row_width(n2)
    R = n2 // C
    rows = [x.reshape(R, C)] + [p.reshape(R, C) for p in payloads]
    if len(rows) == 1:
        sorted_rows = [jnp.sort(rows[0], axis=-1)]
    else:
        # Two sort keys: the merge stages' compare-exchange breaks key ties
        # on the first payload, which is only correct if its inputs are
        # lex-sorted the same way (comparator networks need one total
        # order end to end).
        import jax

        sorted_rows = list(
            jax.lax.sort(tuple(rows), dimension=-1, num_keys=2,
                         is_stable=False)
        )
    while R > 1:
        args = []
        for r in sorted_rows:
            args += [r[0::2], r[1::2]]
        merged = _merge_rows(args[0], args[1], *args[2:])
        sorted_rows = list(merged)
        R //= 2
    out = tuple(r.reshape(-1)[:n] for r in sorted_rows)
    return out[0] if not payloads else out
