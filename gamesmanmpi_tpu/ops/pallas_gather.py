"""Pallas monotone-window gather (round-4 scaffold, interpret-tested).

The dense engine's backward step is, per move, one byte-gather with a
globally NON-DECREASING flat index vector (solve/dense.py sorted-gather
mode builds exactly that). XLA's TPU gather treats it as random access
(~11 ns/element measured); a monotone gather can instead stream: each
block of K indices touches a bounded window of the table, so the kernel
DMAs two window-aligned table tiles into VMEM and selects locally —
HBM traffic becomes sequential tile reads instead of per-element
transactions.

Status: the kernel is written against the documented Pallas/Mosaic API
and validated in INTERPRET mode (tests/test_pallas_gather.py) — the TPU
relay was down for the whole build session, so Mosaic has never compiled
it (docs/CHIP_PLAN.md gates its adoption on that). It is NOT wired into
any engine; solve/dense.py's flag-gated lowerings are the shipping paths.

Contract: monotone_window_gather(table_u32, idx_i32) == table[idx] for
non-decreasing idx, EXCEPT for elements whose block spans more than one
window width — those are miss-flagged (out undefined there) and counted;
the caller sizes `window` so misses are structurally rare and falls back
to a plain gather when nmiss > 0. The dense child gathers have expansion
ratio C(L+1,n1')/C(L,n1) <= 2, so window = 4*block covers them with
margin.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def monotone_window_gather(table, idx, block: int = 2048,
                           window: int = 8192, interpret: bool = False):
    """table [M] uint32, idx [N] int32 non-decreasing ->
    (out [N] uint32, nmiss scalar int32).

    Misses (a block spanning past its 2-window view) leave garbage in
    `out` at those positions and are counted (when nonzero, the count may
    include padding replicas of a missing tail element); callers must
    treat any nonzero nmiss as "fall back to a plain gather".
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = idx.shape[0]
    npad = -n % block
    if npad:
        idx = jnp.concatenate([idx, jnp.full((npad,), idx[-1], idx.dtype)])
    nblk = idx.shape[0] // block
    # Window-aligned base of each block's view, clamped so tile q+1 exists.
    m = table.shape[0]
    nwin = max(-(-m // window), 2)
    tpad = nwin * window - m
    if tpad:
        table = jnp.concatenate(
            [table, jnp.zeros((tpad,), table.dtype)]
        )
    starts = idx[:: block]  # [nblk] first index of each block
    base_win = jnp.clip(starts // window, 0, nwin - 2).astype(jnp.int32)
    aligned = base_win * window

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # aligned bases (element units + window units)
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((block,), lambda i, al, bw: (i,)),
            pl.BlockSpec((window,), lambda i, al, bw: (bw[i],)),
            pl.BlockSpec((window,), lambda i, al, bw: (bw[i] + 1,)),
        ],
        out_specs=[
            pl.BlockSpec((block,), lambda i, al, bw: (i,)),
            # One miss COUNT per block, not a per-element vector: the
            # kernel is judged on HBM traffic, and a 4N-byte bookkeeping
            # write would double its output volume.
            pl.BlockSpec((1,), lambda i, al, bw: (i,)),
        ],
    )

    def kernel(al_ref, bw_ref, idx_ref, t0_ref, t1_ref, out_ref, miss_ref):
        i = pl.program_id(0)
        idxs = idx_ref[:]
        base = al_ref[i]
        off = idxs - base
        in0 = (off >= 0) & (off < window)
        in1 = (off >= window) & (off < 2 * window)
        t0 = t0_ref[:]
        t1 = t1_ref[:]
        g0 = jnp.take(t0, jnp.clip(off, 0, window - 1))
        g1 = jnp.take(t1, jnp.clip(off - window, 0, window - 1))
        out_ref[:] = jnp.where(in0, g0, g1)
        miss_ref[0] = jnp.sum((~(in0 | in1)).astype(jnp.int32))

    out, miss = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((nblk * block,), table.dtype),
            jax.ShapeDtypeStruct((nblk,), jnp.int32),
        ],
        grid_spec=grid_spec,
        interpret=interpret,
    )(aligned, base_win, idx, table, table)
    # Padding lanes replicate idx[-1]; they miss iff the real tail element
    # misses, so nmiss stays 0 exactly when every real element hit (the
    # contract callers check). When nonzero it may count tail replicas.
    return out[:n], jnp.sum(miss)
