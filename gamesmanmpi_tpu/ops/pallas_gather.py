"""Pallas monotone-window gather (round-4; first Mosaic-compiled on chip).

The dense engine's backward step is, per move, one byte-gather with a
globally NON-DECREASING flat index vector (solve/dense.py sorted-gather
mode builds exactly that). XLA's TPU gather treats it as random access
(~9-11 ns/element measured, microbench2 r04: 32M u32 gathers = 357 ms
regardless of table size or the sorted-indices hint); a monotone gather
can instead stream: each block of K indices touches a bounded window of
the table, so the kernel keeps two window-aligned table tiles in VMEM
and selects locally — HBM traffic becomes sequential tile reads instead
of per-element transactions.

Mosaic constraints that shaped this kernel (verified against the
installed lowering, jax/_src/pallas/mosaic/lowering.py):

* rank-1 block shapes must be whole-array or 128-multiples — the original
  per-block (1,) miss-count output could not lower; the miss count is now
  computed OUTSIDE the kernel (it depends only on idx and the window
  bases, one fused elementwise XLA pass).
* `lax.gather` lowers ONLY as 2-D `take_along_axis` with operand, indices
  and output all the same 2-D shape (tpu.dynamic_gather along dim 0 or
  dim 1). A rank-1 in-kernel `jnp.take` can never compile. The kernel
  therefore views the 2-window tile as a [R, 128] matrix and decomposes
  each offset into (row = off // 128, lane = off % 128):

      v   = take_along_axis(tile, row*, axis=0)   # sublane row-select
      out = take_along_axis(v,    lane*, axis=1)[:, 0]  # lane select

  (row*/lane* broadcast to the [R, 128] operand shape), processing R
  outputs per step so every gather operand/index shape matches.

Contract: monotone_window_gather(table_u32, idx) == table[idx] for
non-decreasing idx, EXCEPT for elements whose block spans more than one
window width — those are miss-flagged (out undefined there) and counted;
the caller sizes `window` so misses are structurally rare and falls back
to a plain gather when nmiss > 0. The dense child gathers have expansion
ratio C(L+1,n1')/C(L,n1) <= 2, so window = 4*block covers them with
margin.

idx may be int32 OR int64 (round 5): the kernel never sees the absolute
indices — BLOCK-LOCAL offsets (idx - block's window-aligned base, in
[0, 2*window)) are computed outside in one fused elementwise XLA pass
and enter Mosaic as int32. int64 inside a Mosaic kernel is a hard
no-go (the int64->int32 convert lowering recurses forever — r04 chip
session), but an int64 FLAT INDEX SPACE only needs 64-bit arithmetic
outside: the per-block window base (window units) stays under 2^31 for
any table XLA can allocate, and offsets are bounded by 2*window. This
is what unlocks gather_mode=pallas for int64-flat boards (6x6+), where
the gather win matters most (solve/dense.py:~1103, VERDICT r4 #3).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _dyn_gather(x, idx, axis: int):
    """x[idx[r,c], c] (axis=0) / x[r, idx[r,c]] (axis=1) for 2-D x, idx.

    This is take_along_axis's gather, built directly so the indices stay
    int32: under jax_enable_x64 (which this package turns on for uint64
    boards) jnp.take_along_axis converts indices to int64 for its
    negative-index normalization, and Mosaic's int64->int32 convert
    lowering recurses forever (observed on-chip as a RecursionError,
    microbench2 r04). The dimension numbers below are exactly the two
    forms _gather_lowering_rule pattern-matches into tpu.dynamic_gather.
    """
    dnums = lax.GatherDimensionNumbers(
        offset_dims=(),
        collapsed_slice_dims=(axis,),
        start_index_map=(axis,),
        operand_batching_dims=(1 - axis,),
        start_indices_batching_dims=(1 - axis,),
    )
    return lax.gather(
        x, idx[..., None], dnums, (1, 1),
        mode=lax.GatherScatterMode.PROMISE_IN_BOUNDS)


def cells_table_gather(cells, idx, valid):
    """Direct-address packed-cell gather: cells[idx] where valid, else 0.

    cells: [T] uint32 packed (value, remoteness) cells indexed by packed
    STATE (the fused backward's persistent value table — T = 2^state_bits,
    gated by ops.fused.use_value_table). idx: [...] unsigned states (may
    hold sentinel / garbage on invalid lanes). valid: [...] bool.

    The gather indices are states in frontier order — NOT monotone — so
    the monotone-window kernel above does not apply; XLA's plain gather is
    the right lowering on both backends (measured 0.015 s for 4M lanes
    from a 128 MB table on this host's CPU, vs 0.148 s for the binary
    search it replaces). Cell 0 is UNDECIDED, so the same zero doubles as
    the miss flag downstream (ops.provenance.combine_edge_cells contract).
    Kept beside the pallas kernel because it shares its one constraint:
    indices enter the gather clamped in-bounds, with validity handled by
    select — PROMISE_IN_BOUNDS-style lowering with no branch.
    """
    t = cells.shape[0]
    safe = jnp.clip(idx, 0, t - 1).astype(
        jnp.uint32 if t <= (1 << 32) else jnp.uint64
    )
    return jnp.where(valid, cells[safe], jnp.uint32(0))


def padded_table_len(m: int, window: int) -> int:
    """Table length monotone_window_gather pads to internally: a whole
    number of windows, at least two (so tile q+1 always exists). Callers
    that gather repeatedly from one table (the dense backward's w
    per-move gathers) pre-pad to this length once, making the kernel's
    internal pad a no-op."""
    return max(-(-m // window), 2) * window


def monotone_window_gather(table, idx, block: int = 2048,
                           window: int = 8192, interpret: bool = False):
    """table [M], idx [N] int32/int64 non-decreasing ->
    (out [N] table.dtype, nmiss scalar int32).

    Misses (a block spanning past its 2-window view) leave garbage in
    `out` at those positions and are counted (when nonzero, the count may
    include padding replicas of a missing tail element); callers must
    treat any nonzero nmiss as "fall back to a plain gather".
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if window % 128:
        raise ValueError(f"window must be a multiple of 128, got {window}")
    if block % 128:
        # Mosaic rank-1 block rule (module docstring): fail loudly here,
        # not with an opaque lowering error on chip.
        raise ValueError(f"block must be a multiple of 128, got {block}")
    rows = (2 * window) // 128          # [rows, 128] view of the 2-window tile
    if block % rows:
        raise ValueError(
            f"block ({block}) must be a multiple of 2*window/128 ({rows})")
    nchunk = block // rows

    n = idx.shape[0]
    npad = -n % block
    if npad:
        idx = jnp.concatenate([idx, jnp.full((npad,), idx[-1], idx.dtype)])
    nblk = idx.shape[0] // block
    # Window-aligned base of each block's view, clamped so tile q+1 exists.
    m = table.shape[0]
    padded = padded_table_len(m, window)
    nwin = padded // window
    tpad = padded - m
    if tpad:
        table = jnp.concatenate(
            [table, jnp.zeros((tpad,), table.dtype)]
        )
    starts = idx[:: block]  # [nblk] first index of each block
    # All absolute-index arithmetic happens HERE, in idx's own dtype
    # (int64 for 6x6+ flat spaces): only window-unit bases (< 2^31 for
    # any allocatable table) and 2*window-bounded offsets reach Mosaic.
    base_win = jnp.clip(starts // window, 0, nwin - 2).astype(jnp.int32)
    aligned = base_win.astype(idx.dtype) * idx.dtype.type(window)

    # The table reaches the kernel as a [padded/128, 128] matrix, reshaped
    # ONCE outside (a free XLA relayout): an in-kernel rank-1 -> rank-2
    # reshape is a Mosaic shape cast, and for packed dtypes (the dense
    # engine's u8 cells) layout inference rejects it on chip —
    # "infer-vector-layout: unsupported shape cast, vector<8192xi8> ->
    # vector<64x128xi8>" (chip session r04). With 2-D BlockSpecs the tiles
    # arrive already [window/128, 128] and no shape cast exists for ANY
    # table dtype.
    wrows = window // 128
    table2d = table.reshape(padded // 128, 128)

    # Block-local offsets, computed OUTSIDE the kernel (one fused
    # elementwise XLA pass in idx's dtype) and clamped into the tile:
    # the kernel receives only these int32 offsets, so an int64 flat
    # index space never enters Mosaic (module docstring). The miss count
    # shares the same pass — misses depend only on idx and the window
    # bases (Mosaic's rank-1 output block rule keeps it out of the
    # kernel regardless).
    off_all = idx - jnp.repeat(aligned, block)
    miss = jnp.sum(((off_all < 0) | (off_all >= 2 * window))
                   .astype(jnp.int32))
    off_i32 = jnp.clip(off_all, 0, 2 * window - 1).astype(jnp.int32)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,  # window-unit tile bases
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((block,), lambda i, bw: (i,)),
            pl.BlockSpec((wrows, 128), lambda i, bw: (bw[i], 0)),
            pl.BlockSpec((wrows, 128), lambda i, bw: (bw[i] + 1, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block,), lambda i, bw: (i,)),
        ],
    )

    def kernel(bw_ref, off_ref, t0_ref, t1_ref, out_ref):
        # [rows, 128] view of the two window tiles. Sub-32-bit tables (the
        # dense engine's u8 cells) gather as i32 — Mosaic's dynamic_gather
        # targets 32-bit lanes; the cast back on store is exact for
        # unsigned sub-ranges.
        tile = jnp.concatenate([t0_ref[:], t1_ref[:]], axis=0)
        if tile.dtype.itemsize < 4:
            tile = tile.astype(jnp.int32)
        # All scalars below are pinned int32: under jax_enable_x64 bare
        # Python ints trace as weak int64 scalars, and ANY int64 in a
        # Mosaic kernel hits the infinitely-recursing int64->int32
        # convert lowering (see _dyn_gather's docstring). Chunks are
        # STATIC rank-1 slices of off_ref — a [nchunk, rows] reshape
        # would be another Mosaic shape cast (see the tile note above).
        c128 = jnp.int32(128)
        for k in range(nchunk):
            off = off_ref[k * rows:(k + 1) * rows]          # [rows]
            r = lax.div(off, c128)
            c = lax.rem(off, c128)
            v = _dyn_gather(
                tile, jnp.broadcast_to(r[:, None], (rows, 128)), axis=0)
            sel = _dyn_gather(
                v, jnp.broadcast_to(c[:, None], (rows, 128)), axis=1)
            out_ref[k * rows:(k + 1) * rows] = sel[:, 0].astype(out_ref.dtype)

    (out,) = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((nblk * block,), table.dtype),
        ],
        grid_spec=grid_spec,
        interpret=interpret,
    )(base_win, off_i32, table2d, table2d)
    # Padding lanes replicate idx[-1]; they miss iff the real tail element
    # misses, so nmiss stays 0 exactly when every real element hit (the
    # contract callers check). When nonzero it may count tail replicas.
    return out[:n], miss
