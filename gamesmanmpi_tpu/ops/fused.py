"""Fused rank/sort+dedup: the single-stage dedup core of the level megakernel.

ISSUE 14's roofline push starts from a measurement, not a hunch: on the CPU
fallback that produced every committed bench so far, XLA's sort is the solve
(`BENCH_r05.json` operand_gbps 0.069). Microbenchmarks on this host:

    jnp.sort            4M u32   0.324 s     (XLA comparator network)
    np.sort             4M u32   0.023 s     (numpy radix sort, 14x)
    lax.sort (k, i32)   4M pairs 1.452 s     (the provenance pair sort)
    np.unique           4M u32   0.042 s     (sort + dedup + compact, fused)

So the fused dedup has two lowerings, resolved per platform at kernel-BUILD
time exactly like the sort/search/compact knobs (GAMESMAN_FUSED_DEDUP
overrides for A/B):

* ``callback`` (CPU default): one `jax.pure_callback` into numpy's radix
  sort+unique. On the CPU backend the "device" IS the host, so the callback
  is a function call, not a transfer — and it unlocks something static-shape
  XLA cannot express: the megakernel threads the previous level's COUNT into
  the callback, which dedups only the real prefix instead of the padded
  capacity (bucket padding makes those differ by up to 2x). Misuse guard:
  this lowering would be a host round-trip on a real accelerator; the
  platform-auto default only picks it on CPU.
* ``scatterinv`` (accelerator default): the pair-sort trick of
  ops/provenance.dedup_provenance with its second pair sort replaced by a
  permutation-inverting scatter (`ops.mergesort.sort_rank`): the sorted
  origin column IS a permutation, so one O(n) scatter routes each run's
  unique-index back to its origin slot. One pair sort + compaction instead
  of two pair sorts + compaction — measured 1.5x on this host's pair-sort
  costs, and on TPU it removes one full ~log2(n)-pass sort network from the
  forward's HBM traffic.

Both lowerings are byte-parity-tested against sort_unique/dedup_provenance
(tests/test_fused.py); every consumer keys its kernel cache on the resolved
method so a mid-process flag flip can never mix programs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from gamesmanmpi_tpu.core.bitops import sentinel_for
from gamesmanmpi_tpu.ops.dedup import compact_sorted, sort_unique
from gamesmanmpi_tpu.ops.mergesort import sort_rank
from gamesmanmpi_tpu.utils.env import env_int, env_str
from gamesmanmpi_tpu.utils.platform import platform_auto_flag


def fused_enabled() -> bool:
    """GAMESMAN_FUSED=1: engines collapse each level's forward path into
    one megakernel dispatch (and, where the gate below allows, the
    backward into one table-resolve dispatch). Default OFF — every fused
    variant lands behind this gate with byte-parity A/B against the
    unfused path (ISSUE 14)."""
    return env_str("GAMESMAN_FUSED", "0") not in ("0", "", "off", "false")


def pipeline_mode() -> str:
    """GAMESMAN_PIPELINE: 'level' (default — each level's host work runs
    before the next dispatch, the historical order) or 'pingpong' (level
    N's host-side downloads/export/checkpoint run AFTER level N-1's kernel
    is dispatched, overlapping them with device execution; the deferred
    seconds are reported as stats.overlap_secs)."""
    v = env_str("GAMESMAN_PIPELINE", "level")
    if v not in ("level", "pingpong"):
        raise ValueError(
            f"GAMESMAN_PIPELINE={v!r}: expected 'level' or 'pingpong'"
        )
    return v


def fused_dedup_method() -> str:
    """Fused-dedup lowering, resolved at builder/cache-key time for the
    executing platform (module docstring has the measurements)."""
    return platform_auto_flag(
        "GAMESMAN_FUSED_DEDUP", accel="scatterinv", cpu="callback",
        choices=("callback", "scatterinv"),
    )


def value_table_bits() -> int:
    """Direct-address value-table gate for the fused backward: games whose
    packed states fit this many bits (and run in uint32) resolve against a
    persistent [2^bits] packed-cell table — one gather per child instead
    of a per-level search — at 4*2^bits bytes of device memory. Default 26
    (256 MB) covers every uint32 board through 5x4; 0 disables."""
    return env_int("GAMESMAN_FUSED_TABLE_BITS", 26)


def use_value_table(game) -> bool:
    """Whether the fused backward may use the direct-address cell table."""
    bits = value_table_bits()
    return (
        bits > 0
        and game.state_bits <= bits
        and np.dtype(game.state_dtype).itemsize == 4
    )


# ------------------------------------------------------------- callback side


def _np_sort_unique(flat, nvalid):
    """Host half of the callback lowering: radix sort+unique over the real
    prefix. Engine contract mirror of ops.dedup.sort_unique: uniques first
    (ascending), sentinel tail, int32 count."""
    flat = np.asarray(flat)
    n = min(max(int(nvalid), 0), flat.shape[0])
    sent = np.iinfo(flat.dtype).max
    u = np.unique(flat[:n])
    k = int(u.shape[0])
    if k and u[-1] == sent:
        k -= 1
    out = np.full(flat.shape[0], sent, dtype=flat.dtype)
    out[:k] = u[:k]
    return out, np.int32(k)


def _np_dedup_provenance(flat, nvalid):
    """Host half with provenance: np.unique's return_inverse IS uidx (the
    index of each input slot within the unique prefix; -1 for sentinel and
    beyond-count slots) — the quantity dedup_provenance reconstructs with a
    second pair sort."""
    flat = np.asarray(flat)
    n = min(max(int(nvalid), 0), flat.shape[0])
    sent = np.iinfo(flat.dtype).max
    u, inv = np.unique(flat[:n], return_inverse=True)
    k = int(u.shape[0])
    if k and u[-1] == sent:
        k -= 1
    out = np.full(flat.shape[0], sent, dtype=flat.dtype)
    out[:k] = u[:k]
    uidx = np.full(flat.shape[0], -1, dtype=np.int32)
    if n:
        # inv == k only for sentinel slots (the one unique past the
        # prefix); everything else indexes the kept uniques directly.
        uidx[:n] = np.where(inv < k, inv, -1).astype(np.int32)
    return out, np.int32(k), uidx


# ---------------------------------------------------------------- public api


def _nvalid_or_full(flat, nvalid):
    if nvalid is None:
        return jnp.int32(flat.shape[0])
    return jnp.minimum(nvalid.astype(jnp.int32), jnp.int32(flat.shape[0]))


def fused_sort_unique(flat, nvalid=None, method: str | None = None,
                      merge: bool | None = None, compact: str | None = None):
    """sort_unique with the fused lowering: [N] -> (uniq [N], count).

    nvalid: optional traced count of real leading slots — the callback
    lowering dedups only that prefix (slots past it must already be
    sentinel; the engines guarantee this because children of beyond-count
    parents are sentinel-masked). method/merge/compact: lowerings resolved
    at BUILD time by kernel builders (None = resolve at trace time).
    """
    if method is None:
        method = fused_dedup_method()
    if method == "callback":
        return jax.pure_callback(
            _np_sort_unique,
            (
                jax.ShapeDtypeStruct(flat.shape, flat.dtype),
                jax.ShapeDtypeStruct((), np.int32),
            ),
            flat,
            _nvalid_or_full(flat, nvalid),
        )
    # scatterinv has no non-provenance shortcut — plain dedup already is
    # one sort + compaction; share it so the two paths cannot drift.
    return sort_unique(flat, merge, compact)


def fused_dedup_provenance(flat, nvalid=None, method: str | None = None,
                           merge: bool | None = None,
                           compact: str | None = None):
    """dedup_provenance with the fused lowering:
    [N] -> (uniq [N], count, uidx [N] int32). Same contract as
    ops.provenance.dedup_provenance (byte-parity-tested)."""
    if method is None:
        method = fused_dedup_method()
    if method == "callback":
        return jax.pure_callback(
            _np_dedup_provenance,
            (
                jax.ShapeDtypeStruct(flat.shape, flat.dtype),
                jax.ShapeDtypeStruct((), np.int32),
                jax.ShapeDtypeStruct(flat.shape, np.int32),
            ),
            flat,
            _nvalid_or_full(flat, nvalid),
        )
    sentinel = sentinel_for(flat.dtype)
    s, rank_back = sort_rank(flat, merge)
    first = jnp.concatenate([jnp.ones((1,), bool), s[1:] != s[:-1]])
    keep = first & (s != sentinel)
    uid = jnp.cumsum(keep.astype(jnp.int32)) - 1
    uid = jnp.where(s != sentinel, uid, -1)
    # rank_back[j] = where input slot j landed in s; one gather replaces
    # dedup_provenance's second (origin, uid) pair sort.
    uidx = uid[rank_back]
    uniq = compact_sorted(s, keep, merge, compact)
    count = jnp.sum(keep).astype(jnp.int32)
    return uniq, count, uidx
