"""Parent value/remoteness combine: the RESOLVE kernel.

Reference counterpart: the negamax reduce over accumulated child results when a
position's outstanding count hits zero (src/process.py RESOLVE, SURVEY.md §3.3,
rules in §2.1.2-3). The reference reduces one parent at a time as messages
arrive; here children are regenerated aligned per parent, so the whole
frontier's combine is two masked row-reductions over a [B, M] block — the
moral equivalent of the segment-reduce in BASELINE.json's north star, with the
segmentation made trivial by alignment.
"""

import jax.numpy as jnp

from gamesmanmpi_tpu.core.values import (
    WIN,
    LOSE,
    TIE,
    MAX_REMOTENESS,
    REMOTENESS_DTYPE,
    VALUE_DTYPE,
)


def combine_children(child_values, child_remoteness, mask):
    """Combine child results into parent (value, remoteness).

    child_values: [B, M] uint8 (child-perspective values).
    child_remoteness: [B, M] int32.
    mask: [B, M] bool — True where a real child exists.

    Rules (SURVEY.md §2.1.2-3):
      value:  WIN if any child LOSE; else TIE if any child TIE; else LOSE.
              (Zero children -> vacuous LOSE with remoteness 0; the engine only
              feeds non-primitive positions here, and a non-primitive position
              with no moves is a game-definition error — the engines count such
              rows in their consistency counter and --paranoid raises on it.)
      remoteness: WIN  -> 1 + min over LOSE children
                  LOSE -> 1 + max over all children
                  TIE  -> 1 + max over TIE children
    Returns (values [B] uint8, remoteness [B] int32).
    """
    cv = child_values
    cr = child_remoteness.astype(REMOTENESS_DTYPE)

    lose = mask & (cv == LOSE)
    tie = mask & (cv == TIE)

    any_lose = jnp.any(lose, axis=-1)
    any_tie = jnp.any(tie, axis=-1)

    values = jnp.where(
        any_lose,
        jnp.uint8(WIN),
        jnp.where(any_tie, jnp.uint8(TIE), jnp.uint8(LOSE)),
    ).astype(VALUE_DTYPE)

    win_rem = 1 + jnp.min(jnp.where(lose, cr, MAX_REMOTENESS), axis=-1)
    lose_rem = 1 + jnp.max(jnp.where(mask, cr, -1), axis=-1)
    tie_rem = 1 + jnp.max(jnp.where(tie, cr, -1), axis=-1)

    remoteness = jnp.where(any_lose, win_rem, jnp.where(any_tie, tie_rem, lose_rem))
    return values, remoteness.astype(REMOTENESS_DTYPE)
