"""Memo-table lookup: child-value queries against solved levels.

Reference counterpart: `pos in resolved` dict probes plus the SEND_BACK
round-trip to the owner rank (src/process.py LOOK_UP path, SURVEY.md §3.2-3.3).
Here solved levels are sorted uint32/uint64 arrays with SENTINEL tails, and a
whole frontier's child queries become one vectorized search per level of the
lookup window — no messages, no dict.

TPU notes (tools/microbench.py, v5e): `jnp.searchsorted`'s default
binary-search method ('scan') costs log2(N) dependent gathers per key —
7.0 s for 32M keys in an 8M table — while method='sort' (sort-merge join)
does the same in 1.0 s; and three separate value gathers cost ~0.35 s each,
so for uint32 games the (state, value, remoteness) record is fused into ONE
uint64 payload gather (state in the high 32 bits doubles as the hit check).
This kernel is the backward pass's dominant cost; these two choices are what
took the r02 solve off the 8x-slower-than-CPU floor.
"""

import jax.numpy as jnp

from gamesmanmpi_tpu.core.bitops import sentinel_for
from gamesmanmpi_tpu.utils.platform import platform_auto_flag
from gamesmanmpi_tpu.core.codec import pack_cells, unpack_cells
from gamesmanmpi_tpu.core.values import UNDECIDED


def search_method() -> str:
    """searchsorted lowering, resolved at trace time for the platform that
    will execute: 'sort' (sort-merge join) on accelerators — binary search
    costs log2(N) DEPENDENT gathers/key, 7x slower at 32M keys on the v5e
    (module docstring) — but on CPU the dependent gathers are cheap and the
    merge's full re-sort is what dominates (the r03 backward ran 20 s vs
    r01's ~2 s on the same 5x4 board because of it). GAMESMAN_SEARCH=
    sort|scan overrides for A/B."""
    return platform_auto_flag(
        "GAMESMAN_SEARCH", accel="sort", cpu="scan",
        choices=("sort", "scan"),
    )


def lookup_sorted(keys, table_states, table_values, table_remoteness,
                  method: str | None = None):
    """Look keys up in one sorted solved level.

    keys: [K] unsigned (SENTINEL entries allowed; they miss).
    table_states: [N] sorted, same dtype as keys, SENTINEL tail.
    method: searchsorted lowering; kernel BUILDERS resolve it via
    search_method() when the builder runs (the moment the cache key is
    computed) and pass it down, so a flag flip between scheduling a
    background compile and its tracing cannot produce a program that
    disagrees with its key. None = resolve at trace time (non-cached uses).
    Returns (values [K] uint8 — UNDECIDED on miss, remoteness [K] int32,
    hit [K] bool).
    """
    if method is None:
        method = search_method()
    sentinel = sentinel_for(keys.dtype)
    n = table_states.shape[0]
    idx = jnp.searchsorted(table_states, keys, method=method)
    idx = jnp.clip(idx, 0, n - 1).astype(jnp.int32)
    cells = pack_cells(table_values, table_remoteness)
    if keys.dtype == jnp.uint32:
        # Fused record: one u64 gather instead of three (state high, cell low).
        payload = (table_states.astype(jnp.uint64) << jnp.uint64(32)) | (
            cells.astype(jnp.uint64)
        )
        p = payload[idx]
        hit = ((p >> jnp.uint64(32)).astype(keys.dtype) == keys) & (
            keys != sentinel
        )
        values, remoteness = unpack_cells(
            (p & jnp.uint64(0xFFFF_FFFF)).astype(jnp.uint32)
        )
    else:
        hit = (table_states[idx] == keys) & (keys != sentinel)
        values, remoteness = unpack_cells(cells[idx])
    values = jnp.where(hit, values, jnp.uint8(UNDECIDED))
    remoteness = jnp.where(hit, remoteness, 0)
    return values, remoteness, hit


def lookup_window(keys, window, method: str | None = None):
    """Look keys up across a window of solved levels.

    window: sequence of (states, values, remoteness) triples (each as in
    lookup_sorted). Each key hits at most one level (a state's level is a
    function of the state). method: see lookup_sorted. Returns
    (values, remoteness, hit) like lookup_sorted.
    """
    shape = keys.shape
    values = jnp.full(shape, UNDECIDED, dtype=jnp.uint8)
    remoteness = jnp.zeros(shape, dtype=jnp.int32)
    hit = jnp.zeros(shape, dtype=bool)
    for ts, tv, tr in window:
        v, r, h = lookup_sorted(keys, ts, tv, tr, method)
        values = jnp.where(h, v, values)
        remoteness = jnp.where(h, r, remoteness)
        hit = hit | h
    return values, remoteness, hit
