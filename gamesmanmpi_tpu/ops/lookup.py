"""Memo-table lookup: child-value queries against solved levels.

Reference counterpart: `pos in resolved` dict probes plus the SEND_BACK
round-trip to the owner rank (src/process.py LOOK_UP path, SURVEY.md §3.2-3.3).
Here solved levels are sorted uint32/uint64 arrays with SENTINEL tails, and a
whole frontier's child queries become one vectorized binary search
(searchsorted + gather) per level of the lookup window — no messages, no dict.
"""

import jax.numpy as jnp

from gamesmanmpi_tpu.core.bitops import sentinel_for
from gamesmanmpi_tpu.core.values import UNDECIDED


def lookup_sorted(keys, table_states, table_values, table_remoteness):
    """Look keys up in one sorted solved level.

    keys: [K] unsigned (SENTINEL entries allowed; they miss).
    table_states: [N] sorted, same dtype as keys, SENTINEL tail.
    Returns (values [K] uint8 — UNDECIDED on miss, remoteness [K] int32,
    hit [K] bool).
    """
    sentinel = sentinel_for(keys.dtype)
    idx = jnp.searchsorted(table_states, keys)
    idx = jnp.clip(idx, 0, table_states.shape[0] - 1)
    hit = (table_states[idx] == keys) & (keys != sentinel)
    values = jnp.where(hit, table_values[idx], jnp.uint8(UNDECIDED))
    remoteness = jnp.where(hit, table_remoteness[idx], 0)
    return values, remoteness, hit


def lookup_window(keys, window):
    """Look keys up across a window of solved levels.

    window: sequence of (states, values, remoteness) triples (each as in
    lookup_sorted). Each key hits at most one level (a state's level is a
    function of the state). Returns (values, remoteness, hit) like lookup_sorted.
    """
    shape = keys.shape
    values = jnp.full(shape, UNDECIDED, dtype=jnp.uint8)
    remoteness = jnp.zeros(shape, dtype=jnp.int32)
    hit = jnp.zeros(shape, dtype=bool)
    for ts, tv, tr in window:
        v, r, h = lookup_sorted(keys, ts, tv, tr)
        values = jnp.where(h, v, values)
        remoteness = jnp.where(h, r, remoteness)
        hit = hit | h
    return values, remoteness, hit
