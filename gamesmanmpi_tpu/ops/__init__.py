"""ops: the XLA kernels of the solver.

These are the vmapped/fused replacements for the reference's per-position hot
loops (SURVEY.md §3.5): `expand()`'s one-at-a-time move generation becomes a
batched kernel in each game module; the per-message combine in RESOLVE becomes
ops.combine.combine_children; memo-table lookups become sorted-array
searchsorted in ops.lookup; frontier dedup is ops.dedup.sort_unique.
"""

from gamesmanmpi_tpu.ops.padding import bucket_size, pad_to_bucket
from gamesmanmpi_tpu.ops.dedup import sort_unique
from gamesmanmpi_tpu.ops.fused import (
    fused_dedup_provenance,
    fused_sort_unique,
)
from gamesmanmpi_tpu.ops.lookup import lookup_sorted, lookup_window
from gamesmanmpi_tpu.ops.combine import combine_children
from gamesmanmpi_tpu.ops.provenance import dedup_provenance, gather_cells

__all__ = [
    "bucket_size",
    "pad_to_bucket",
    "sort_unique",
    "fused_sort_unique",
    "fused_dedup_provenance",
    "lookup_sorted",
    "lookup_window",
    "combine_children",
    "dedup_provenance",
    "gather_cells",
]
