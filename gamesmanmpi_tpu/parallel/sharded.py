"""The sharded level-synchronous solver (multi-device).

This is the TPU rebuild of the reference's distributed runtime proper
(src/process.py's cross-rank behavior, SURVEY.md §3.2-3.3 and §5.8):

  reference (per message/position)      here (per level, per shard)
  ------------------------------------  --------------------------------------
  comm.send(Job(LOOK_UP, child),        forward: expand locally, bucket all
     dest=hash(child) % world_size)     children by owner_shard(child), one
                                        lax.all_to_all over the ICI mesh,
                                        then sort-unique locally (dedup is
                                        local after owner routing)
  per-rank memo dict {pos: value}       per-shard sorted (states, cells)
                                        arrays — the hash-partitioned
                                        position table in sharded HBM
  SEND_BACK child result to parent      backward: all_gather the (tiny,
                                        transient) solved window of deeper
                                        levels, look child values up locally
  FINISHED broadcast                    backward loop reaching the root level

Capacity planning: all_to_all buffers are [num_shards, capacity] with
SENTINEL padding. Overflow (a shard receiving more than capacity from one
peer) is detected on host via returned per-destination counts and retried
with a doubled capacity — the "capacity counters + host-side spill loop
(rare path)" design of SURVEY.md §5.8.

Shard-count invariance (same tables for 1 and N shards) is the test contract
replacing the reference's `mpirun -np 1` vs `-np N` (SURVEY.md §4.2).
"""

from __future__ import annotations

import time
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from gamesmanmpi_tpu.core.bitops import SENTINEL
from gamesmanmpi_tpu.core.hashing import owner_shard, owner_shard_np
from gamesmanmpi_tpu.core.values import UNDECIDED
from gamesmanmpi_tpu.games.base import TensorGame
from gamesmanmpi_tpu.ops.combine import combine_children
from gamesmanmpi_tpu.ops.dedup import sort_unique
from gamesmanmpi_tpu.ops.lookup import lookup_window
from gamesmanmpi_tpu.ops.padding import bucket_size
from gamesmanmpi_tpu.parallel.mesh import AXIS, make_mesh
from gamesmanmpi_tpu.solve.engine import LevelTable, SolveResult, SolverError


def _pad_shards(shard_arrays: List[np.ndarray], cap: int) -> np.ndarray:
    """Stack per-shard 1-D uint64 arrays into [S, cap] with SENTINEL pad."""
    S = len(shard_arrays)
    out = np.full((S, cap), SENTINEL, dtype=np.uint64)
    for s, arr in enumerate(shard_arrays):
        out[s, : arr.shape[0]] = arr
    return out


class ShardedSolver:
    """Hash-partitioned solver over a 1-D device mesh."""

    def __init__(
        self,
        game: TensorGame,
        *,
        num_shards: int | None = None,
        mesh=None,
        min_bucket: int = 256,
        paranoid: bool = False,
        logger=None,
        checkpointer=None,
    ):
        self.game = game
        self.mesh = mesh if mesh is not None else make_mesh(num_shards)
        self.S = self.mesh.devices.shape[0]
        self.min_bucket = min_bucket
        self.paranoid = paranoid
        self.logger = logger
        self.checkpointer = checkpointer
        # Per-instance caches of jitted steps keyed on static shapes (a
        # class-level functools.cache would pin instances for process life).
        self._forward_cache: dict = {}
        self._backward_cache: dict = {}

    # ------------------------------------------------------------- jit builds

    def _forward_fn(self, cap: int, route_cap: int):
        """Compiled forward step: [S, cap] states -> routed unique children."""
        key = (cap, route_cap)
        if key in self._forward_cache:
            return self._forward_cache[key]
        g = self.game
        S = self.S

        def per_shard(local):  # local: [1, cap]
            local = local[0]
            valid = local != SENTINEL
            prim = g.primitive(local)
            children, mask = g.expand(local)
            mask = mask & (valid & (prim == UNDECIDED))[:, None]
            flat = jnp.where(mask, children, SENTINEL).reshape(-1)
            owner = jnp.where(
                flat == SENTINEL, S, owner_shard(flat, S)
            ).astype(jnp.int32)
            # Bucket by owner: stable-sort children by destination shard.
            order = jnp.argsort(owner, stable=True)
            s_owner = owner[order]
            s_kids = flat[order]
            # Position of each element within its destination bucket.
            first = jnp.searchsorted(s_owner, jnp.arange(S + 1))
            pos = jnp.arange(s_owner.shape[0]) - first[jnp.clip(s_owner, 0, S)]
            counts = first[1:] - first[:-1]  # per-destination send counts [S]
            out = jnp.full((S, route_cap), SENTINEL, dtype=jnp.uint64)
            # Out-of-range rows (owner==S) and overflow (pos>=route_cap) drop.
            out = out.at[s_owner, pos].set(s_kids, mode="drop")
            routed = jax.lax.all_to_all(
                out, AXIS, split_axis=0, concat_axis=0, tiled=True
            )
            uniq, count = sort_unique(routed.reshape(-1))
            levels = jnp.where(uniq != SENTINEL, g.level_of(uniq), -1)
            return (
                uniq[None],
                levels[None],
                count[None],
                counts[None],
            )

        fn = jax.shard_map(
            per_shard,
            mesh=self.mesh,
            in_specs=P(AXIS),
            out_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
        )
        self._forward_cache[key] = jax.jit(fn)
        return self._forward_cache[key]

    def _backward_fn(self, cap: int, window_caps: tuple):
        """Compiled backward step for one level against a solved window."""
        key = (cap, window_caps)
        if key in self._backward_cache:
            return self._backward_cache[key]
        g = self.game
        S = self.S

        def per_shard(local, *window_flat):  # local: [1, cap]
            local = local[0]
            valid = local != SENTINEL
            prim = g.primitive(local)
            undecided = valid & (prim == UNDECIDED)
            children, mask = g.expand(local)
            mask = mask & undecided[:, None]
            children = jnp.where(mask, children, SENTINEL)
            # Gather the solved window from all shards; each shard's slice is
            # sorted, so lookups are per-chunk binary searches.
            tables = []
            for i in range(0, len(window_flat), 3):
                ts = jax.lax.all_gather(window_flat[i][0], AXIS)  # [S, capL]
                tv = jax.lax.all_gather(window_flat[i + 1][0], AXIS)
                tr = jax.lax.all_gather(window_flat[i + 2][0], AXIS)
                for s in range(S):
                    tables.append((ts[s], tv[s], tr[s]))
            child_vals, child_rem, hit = lookup_window(children, tuple(tables))
            values, remoteness = combine_children(child_vals, child_rem, mask)
            values = jnp.where(undecided, values, jnp.where(valid, prim, UNDECIDED))
            remoteness = jnp.where(undecided, remoteness, 0)
            # Misses + zero-move UNDECIDED positions (see engine._resolve_impl).
            misses = jnp.sum(mask & ~hit) + jnp.sum(
                undecided & ~jnp.any(mask, axis=-1)
            )
            return values[None], remoteness[None], misses[None]

        n_windows = len(window_caps)
        fn = jax.shard_map(
            per_shard,
            mesh=self.mesh,
            in_specs=(P(AXIS),) + (P(AXIS),) * (3 * n_windows),
            out_specs=(P(AXIS), P(AXIS), P(AXIS)),
        )
        self._backward_cache[key] = jax.jit(fn)
        return self._backward_cache[key]

    # ----------------------------------------------------------------- phases

    def _forward(self, pools: Dict[int, List[np.ndarray]], start_level: int):
        g = self.game
        S = self.S
        k = start_level
        while pools and k <= max(pools):
            if k not in pools:
                k += 1
                continue
            t0 = time.perf_counter()
            shards = pools[k]
            cap = bucket_size(max(a.shape[0] for a in shards), self.min_bucket)
            total = sum(a.shape[0] for a in shards)
            route_cap = bucket_size(
                max(64, 2 * cap * g.max_moves // S), self.min_bucket
            )
            stacked = _pad_shards(shards, cap)
            while True:
                uniq, levels, count, send_counts = self._forward_fn(
                    cap, route_cap
                )(stacked)
                max_sent = int(np.asarray(send_counts).max())
                if max_sent <= route_cap:
                    break
                route_cap = bucket_size(max_sent)  # spill path: retry bigger
            uniq = np.asarray(uniq)
            levels = np.asarray(levels)
            count = np.asarray(count)
            for s in range(S):
                n = int(count[s])
                kids = uniq[s, :n]
                kid_levels = levels[s, :n]
                for lv in np.unique(kid_levels):
                    lv = int(lv)
                    batch = kids[kid_levels == lv]
                    if lv not in pools:
                        pools[lv] = [np.empty(0, np.uint64) for _ in range(S)]
                    pools[lv][s] = np.union1d(pools[lv][s], batch)
            if self.logger is not None:
                self.logger.log(
                    {
                        "phase": "forward",
                        "level": k,
                        "frontier": total,
                        "shards": S,
                        "route_cap": route_cap,
                        "secs": time.perf_counter() - t0,
                    }
                )
            k += 1

    def _repartition(self, states: np.ndarray) -> List[np.ndarray]:
        """Split a sorted global state array into per-shard sorted arrays."""
        owners = owner_shard_np(states, self.S)
        return [states[owners == s] for s in range(self.S)]

    def _backward(self, pools: Dict[int, List[np.ndarray]]):
        g = self.game
        S = self.S
        resolved: Dict[int, LevelTable] = {}
        padded_cache: Dict[int, tuple] = {}
        completed = (
            set(self.checkpointer.completed_levels())
            if self.checkpointer is not None
            else set()
        )
        for k in sorted(pools, reverse=True):
            t0 = time.perf_counter()
            shards = pools[k]
            cap = bucket_size(max(a.shape[0] for a in shards), self.min_bucket)
            stacked = _pad_shards(shards, cap)
            pv = np.full((S, cap), UNDECIDED, dtype=np.uint8)
            pr = np.zeros((S, cap), dtype=np.int32)
            from_checkpoint = k in completed
            if from_checkpoint:
                # Restart-from-level: reload the solved table, re-partition it
                # by owner to refill the per-shard window cache.
                table = self.checkpointer.load_level(k)
                expected = np.sort(np.concatenate(shards))
                if table.states.shape[0] != expected.shape[0] or not (
                    table.states == expected
                ).all():
                    raise SolverError(
                        f"checkpointed level {k} does not match the "
                        "discovered frontier — stale checkpoint directory?"
                    )
                owners = owner_shard_np(table.states, S)
                for s in range(S):
                    sel = owners == s
                    pv[s, : sel.sum()] = table.values[sel]
                    pr[s, : sel.sum()] = table.remoteness[sel]
            else:
                window_levels = [
                    k + j
                    for j in range(1, g.max_level_jump + 1)
                    if (k + j) in padded_cache
                ]
                window_caps = tuple(
                    padded_cache[L][0].shape[1] for L in window_levels
                )
                window_flat = []
                for L in window_levels:
                    window_flat.extend(padded_cache[L])
                values, remoteness, misses = self._backward_fn(cap, window_caps)(
                    stacked, *window_flat
                )
                if self.paranoid and int(np.asarray(misses).sum()) > 0:
                    raise SolverError(
                        f"level {k}: consistency failures (missed child "
                        "lookups or zero-move non-primitive positions)"
                    )
                values = np.asarray(values)
                remoteness = np.asarray(remoteness)
                # Global table for this level: concatenate shards (kept
                # sharded on device during the solve; materialized for the
                # result).
                gs, gv, gr = [], [], []
                for s in range(S):
                    n = shards[s].shape[0]
                    gs.append(shards[s])
                    gv.append(values[s, :n])
                    gr.append(remoteness[s, :n])
                    pv[s, :n] = values[s, :n]
                    pr[s, :n] = remoteness[s, :n]
                states = np.concatenate(gs)
                order = np.argsort(states)
                table = LevelTable(
                    states=states[order],
                    values=np.concatenate(gv)[order],
                    remoteness=np.concatenate(gr)[order],
                )
            resolved[k] = table
            padded_cache[k] = (stacked, pv, pr)
            for done in [d for d in padded_cache if d > k + g.max_level_jump]:
                del padded_cache[done]
            if self.logger is not None:
                self.logger.log(
                    {
                        "phase": "backward",
                        "level": k,
                        "n": int(table.states.shape[0]),
                        "shards": S,
                        "resumed": from_checkpoint,
                        "secs": time.perf_counter() - t0,
                    }
                )
            if self.checkpointer is not None and not from_checkpoint:
                self.checkpointer.save_level(k, table)
        return resolved

    # ------------------------------------------------------------------ solve

    def solve(self) -> SolveResult:
        g = self.game
        S = self.S
        t0 = time.perf_counter()
        init = np.uint64(g.initial_state())
        start_level = int(np.asarray(g.level_of(jnp.asarray([init])))[0])
        global_pools = (
            self.checkpointer.load_frontiers()
            if self.checkpointer is not None
            else None
        )
        if global_pools is not None:
            pools = {
                k: self._repartition(v) for k, v in global_pools.items()
            }
        else:
            owner = int(owner_shard_np(np.array([init]), S)[0])
            shards = [np.empty(0, np.uint64) for _ in range(S)]
            shards[owner] = np.array([init], np.uint64)
            pools = {start_level: shards}
            self._forward(pools, start_level)
            if self.checkpointer is not None:
                self.checkpointer.save_frontiers(
                    {
                        k: np.sort(np.concatenate(v))
                        for k, v in pools.items()
                    }
                )
        t_forward = time.perf_counter() - t0
        resolved = self._backward(pools)
        t_total = time.perf_counter() - t0
        root = resolved[start_level]
        i = int(np.searchsorted(root.states, init))
        num_positions = sum(t.states.shape[0] for t in resolved.values())
        stats = {
            "game": g.name,
            "shards": S,
            "positions": num_positions,
            "levels": len(resolved),
            "secs_forward": t_forward,
            "secs_total": t_total,
            "positions_per_sec": num_positions / max(t_total, 1e-9),
        }
        if self.logger is not None:
            self.logger.log({"phase": "done", **stats})
        return SolveResult(
            g, int(root.values[i]), int(root.remoteness[i]), resolved, stats
        )
